"""The Table API — keyed, incrementally-maintained tables.

reference: python/pathway/internals/table.py (2675 LoC; select:382,
filter:490, groupby:942, join flavors via joins.py, concat:1334,
update_cells:1064, update_rows:1164, flatten, ix, deduplicate, …).
"""

from __future__ import annotations

from typing import Any, Iterable, TYPE_CHECKING

from . import dtype as dt
from .desugaring import expand_select_args, resolve_expression
from .expression import (
    CastExpression,
    ColumnExpression,
    ColumnReference,
    DeclareTypeExpression,
    IdExpression,
    PointerExpression,
    smart_wrap,
)
from .graph import Operator
from .groupbys import GroupedTable
from .joins import JoinMode, JoinResult
from .schema import ColumnSchema, Schema, SchemaMetaclass, _schema_from_columns
from .universe import Universe

__all__ = ["Table", "TableLike", "ColumnNamespace", "groupby"]


class ColumnNamespace:
    """``table.C.<name>`` / ``table.C[<name>]`` column accessor
    (reference repo: python/pathway/internals/table.py ``Table.C``,
    python/pathway/tests/test_colnamespace.py) — reaches columns whose
    names collide with Table methods (``select``, ``filter``, even ``C``)."""

    __slots__ = ("_table",)

    def __init__(self, table: "Table"):
        object.__setattr__(self, "_table", table)

    def __getattr__(self, name: str):
        # validate eagerly: this is the *safe* accessor, so a typo must
        # fail here with the column list, not later as a deep KeyError.
        # Leading-underscore names would also swallow notebook/hasattr
        # protocol probes (_repr_html_ etc.) — bracket access is the
        # escape hatch for such column names.
        if name.startswith("_"):
            raise AttributeError(name)
        table = self._table
        if name == "id" or name in table._schema.__columns__:
            return table[name]
        raise AttributeError(
            f"Table has no column {name!r}; columns: {table.column_names()}"
        )

    def __getitem__(self, name):
        return self._table[name]


class Table:
    """A keyed table = incrementally maintained collection of rows.

    Each row has a 128-bit ``id`` (Pointer); every operation derives a new
    lazy operator in the global parse graph, executed by ``pw.run`` /
    ``pw.debug`` helpers."""

    _operator: Operator
    _schema: SchemaMetaclass

    # ``pw.Table[SomeSchema]`` annotations (reference: Table is
    # Generic[TSchema]); the parameter is carried for table_transformer /
    # typing introspection, not enforced at construction
    def __class_getitem__(cls, item):
        import types as _types

        return _types.GenericAlias(cls, item)
    _universe: Universe

    # -- construction --
    @classmethod
    def _new(cls, operator: Operator, schema: SchemaMetaclass, universe: Universe) -> "Table":
        self = object.__new__(cls)
        self._operator = operator
        self._schema = schema
        self._universe = universe
        operator.outputs.append(self)
        return self

    @classmethod
    def empty(cls, **kwargs: Any) -> "Table":
        from .schema import schema_from_types

        schema = schema_from_types(**kwargs)
        op = Operator("input", [], params=dict(rows=[], schema=schema))
        return cls._new(op, schema, Universe())

    # -- basic info --
    @property
    def schema(self) -> SchemaMetaclass:
        return self._schema

    def column_names(self) -> list[str]:
        return list(self._schema.column_names())

    def keys(self):
        return self._schema.keys()

    def typehints(self) -> dict[str, Any]:
        return self._schema.typehints()

    @property
    def id(self) -> IdExpression:
        return IdExpression(self)

    @property
    def C(self) -> "ColumnNamespace":
        """Column accessor immune to Table method-name collisions
        (reference: internals/table.py ``Table.C``, tests/test_colnamespace.py):
        ``t.C.select`` reads the column named "select"."""
        return ColumnNamespace(self)

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._schema.__columns__:
            return ColumnReference(self, name)
        raise AttributeError(
            f"Table has no column {name!r}; columns: {self.column_names()}"
        )

    def __getitem__(self, arg):
        if isinstance(arg, str):
            if arg == "id":
                return self.id
            return ColumnReference(self, arg)
        if isinstance(arg, ColumnReference):
            return ColumnReference(self, arg.name)
        if isinstance(arg, (list, tuple)):
            return self.select(*[self[a] for a in arg])
        raise TypeError(f"cannot index Table with {arg!r}")

    def __iter__(self):
        raise TypeError("Table is not iterable; use pw.debug helpers")

    def __repr__(self):
        return f"<pathway_tpu.Table schema={dict(self._schema.dtypes())}>"

    # -- core relational ops --
    def select(self, *args: Any, **kwargs: Any) -> "Table":
        """Project and compute columns (reference: table.py:382).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... a | b
        ... 1 | x
        ... 2 | y
        ... ''')
        >>> pw.debug.compute_and_print(
        ...     t.select(t.b, double=t.a * 2), include_id=False)
        b | double
        x | 2
        y | 4
        """
        exprs = expand_select_args(args, kwargs, self)
        return self._select_exprs(exprs, universe=self._universe)

    def _select_exprs(
        self, exprs: dict[str, ColumnExpression], universe: Universe
    ) -> "Table":
        columns = {
            name: ColumnSchema(name=name, dtype=e._dtype) for name, e in exprs.items()
        }
        schema = _schema_from_columns(columns)
        extra = _referenced_tables(exprs.values(), primary=self)
        op = Operator(
            "rowwise",
            [self, *extra],
            params=dict(exprs=exprs),
        )
        return Table._new(op, schema, universe)

    def filter(self, condition: Any) -> "Table":
        """Keep rows satisfying ``condition`` (reference: table.py).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... v
        ... 1
        ... 5
        ... 9
        ... ''')
        >>> pw.debug.compute_and_print(t.filter(t.v >= 5), include_id=False)
        v
        5
        9

        reference: table.py:490"""
        cond = resolve_expression(condition, self)
        extra = _referenced_tables([cond], primary=self)
        op = Operator(
            "filter",
            [self, *extra],
            params=dict(condition=cond),
        )
        return Table._new(op, self._schema, self._universe.subuniverse())

    def split(self, condition: Any) -> tuple["Table", "Table"]:
        cond = resolve_expression(condition, self)
        positive = self.filter(cond)
        negative = self.filter(~cond)
        return positive, negative

    def groupby(
        self,
        *args: Any,
        id: ColumnReference | None = None,
        sort_by: Any = None,
        instance: Any = None,
        persistent_id: str | None = None,
        **kwargs,
    ) -> GroupedTable:
        """Group rows for ``.reduce`` (reference: table.py:942).

        ``persistent_id`` opts the reduction's state into the chunked
        operator-snapshot plane: under
        ``PersistenceMode.OPERATOR_PERSISTING`` the group state
        checkpoints as per-commit deltas and restores on restart
        (``pw.persistence`` module docstring documents the format).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... word  | n
        ... apple | 2
        ... pear  | 1
        ... apple | 3
        ... ''')
        >>> r = t.groupby(t.word).reduce(
        ...     t.word, total=pw.reducers.sum(t.n), c=pw.reducers.count())
        >>> pw.debug.compute_and_print(r, include_id=False)
        word | total | c
        apple | 5 | 2
        pear | 1 | 1
        """
        grouping = [resolve_expression(a, self) for a in args]
        set_id = False
        if id is not None:
            grouping = [resolve_expression(id, self)]
            set_id = True
        return GroupedTable(
            self,
            grouping,
            set_id=set_id,
            sort_by=resolve_expression(sort_by, self) if sort_by is not None else None,
            instance=resolve_expression(instance, self) if instance is not None else None,
            persistent_id=persistent_id,
        )

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        """Global reduction to a single row (reference: table.py reduce)."""
        return GroupedTable(self, []).reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any,
        instance: Any = None,
        acceptor: Any = None,
        persistent_id: str | None = None,
        name: str | None = None,
    ) -> "Table":
        """Keep one accepted row per instance
        (reference: stdlib/stateful/deduplicate.py).

        Example — keep each sensor's highest sequence number:

        >>> import pathway_tpu as pw
        >>> d = pw.debug.table_from_markdown('''
        ...   | sensor | val | seq
        ... 1 | s1     | 5   | 1
        ... 2 | s1     | 9   | 2
        ... 3 | s2     | 7   | 1
        ... ''')
        >>> out = d.deduplicate(value=d.seq, instance=d.sensor,
        ...                     acceptor=lambda new, cur: new > cur)
        >>> pw.debug.compute_and_print(out, include_id=False)
        sensor | val | seq
        s1 | 9 | 2
        s2 | 7 | 1
        """
        value_e = resolve_expression(value, self)
        instance_e = (
            resolve_expression(instance, self) if instance is not None else None
        )
        if acceptor is None:
            acceptor = lambda new, old: new != old
        op = Operator(
            "deduplicate",
            [self],
            params=dict(
                value=value_e,
                instance=instance_e,
                acceptor=acceptor,
                persistent_id=persistent_id or name,
            ),
        )
        return Table._new(op, self._schema, Universe())

    # -- joins --
    def join(
        self,
        other: "Table",
        *on: Any,
        id: Any = None,
        how: JoinMode = JoinMode.INNER,
        left_instance: Any = None,
        right_instance: Any = None,
        exact_match: bool = False,
    ) -> JoinResult:
        """Equi-join (reference: table.py join / joins.py; modes
        INNER/LEFT/RIGHT/OUTER via ``how`` or ``join_left``/... sugar).

        Example:

        >>> import pathway_tpu as pw
        >>> left = pw.debug.table_from_markdown('''
        ... k | v
        ... a | 1
        ... b | 2
        ... ''')
        >>> right = pw.debug.table_from_markdown('''
        ... rk | label
        ... a  | ant
        ... b  | bee
        ... ''')
        >>> j = left.join(right, left.k == right.rk).select(left.v, right.label)
        >>> pw.debug.compute_and_print(j, include_id=False)
        v | label
        1 | ant
        2 | bee
        """
        on = list(on)
        if left_instance is not None and right_instance is not None:
            on.append(
                smart_wrap(resolve_expression(left_instance, self))
                == resolve_expression(right_instance, other)
            )
        id_expr = None
        if id is not None:
            id_expr = resolve_expression(id, self, self, other)
        return JoinResult(self, other, tuple(on), how, id_expr, exact_match)

    def join_inner(self, other: "Table", *on: Any, **kwargs: Any) -> JoinResult:
        return self.join(other, *on, how=JoinMode.INNER, **kwargs)

    def join_left(self, other: "Table", *on: Any, **kwargs: Any) -> JoinResult:
        return self.join(other, *on, how=JoinMode.LEFT, **kwargs)

    def join_right(self, other: "Table", *on: Any, **kwargs: Any) -> JoinResult:
        return self.join(other, *on, how=JoinMode.RIGHT, **kwargs)

    def join_outer(self, other: "Table", *on: Any, **kwargs: Any) -> JoinResult:
        return self.join(other, *on, how=JoinMode.OUTER, **kwargs)

    # -- set/universe ops --
    def concat(self, *others: "Table") -> "Table":
        """Universes must be disjoint (reference: table.py:1334)."""
        tables = [self, *others]
        schema = _common_schema(tables)
        op = Operator("concat", tables, params=dict(reindex=False))
        return Table._new(op, schema, Universe())

    def concat_reindex(self, *others: "Table") -> "Table":
        """Union with fresh row keys, so universes never collide
        (reference: table.py concat_reindex).

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('''
        ... v
        ... 1
        ... ''')
        >>> b = pw.debug.table_from_markdown('''
        ... v
        ... 2
        ... ''')
        >>> pw.debug.compute_and_print(a.concat_reindex(b), include_id=False)
        v
        1
        2
        """
        tables = [self, *others]
        schema = _common_schema(tables)
        op = Operator("concat", tables, params=dict(reindex=True))
        return Table._new(op, schema, Universe())

    def update_rows(self, other: "Table") -> "Table":
        """Union where ``other``'s rows win on key collision
        (reference: table.py:1164).

        Example:

        >>> import pathway_tpu as pw
        >>> base = pw.debug.table_from_markdown('''
        ...   | name  | v
        ... 1 | alice | 1
        ... 2 | bob   | 2
        ... ''')
        >>> fresh = pw.debug.table_from_markdown('''
        ...   | name  | v
        ... 2 | bobby | 20
        ... 3 | carol | 30
        ... ''')
        >>> pw.debug.compute_and_print(base.update_rows(fresh), include_id=False)
        name | v
        alice | 1
        bobby | 20
        carol | 30
        """
        schema = _common_schema([self, other])
        universe = Universe()
        self._universe.promise_subset_of(universe)
        other._universe.promise_subset_of(universe)
        op = Operator("update_rows", [self, other], params=dict())
        return Table._new(op, schema, universe)

    def update_cells(self, other: "Table") -> "Table":
        """Override ``other``'s columns on rows where it has the key
        (reference: table.py:1064).

        Example:

        >>> import pathway_tpu as pw
        >>> base = pw.debug.table_from_markdown('''
        ...   | name  | v
        ... 1 | alice | 1
        ... 2 | bob   | 2
        ... ''')
        >>> upd = pw.debug.table_from_markdown('''
        ...   | v
        ... 2 | 99
        ... ''')
        >>> patched = base.update_cells(upd.promise_universe_is_subset_of(base))
        >>> pw.debug.compute_and_print(patched, include_id=False)
        name | v
        alice | 1
        bob | 99
        """
        if not other._universe.is_subset_of(self._universe):
            raise ValueError(
                "update_cells: other table's universe is not a subset of self's; "
                "use promise_universe_is_subset_of if this is guaranteed"
            )
        my_cols = self.column_names()
        other_cols = other.column_names()
        unknown = set(other_cols) - set(my_cols)
        if unknown:
            raise ValueError(f"update_cells: unknown columns {sorted(unknown)}")
        positions = [
            other_cols.index(c) if c in other_cols else None for c in my_cols
        ]
        columns = {}
        for c in my_cols:
            if c in other_cols:
                dtype = dt.types_lcm(self._schema[c].dtype, other._schema[c].dtype)
            else:
                dtype = self._schema[c].dtype
            columns[c] = ColumnSchema(name=c, dtype=dtype)
        op = Operator("update_cells", [self, other], params=dict(positions=positions))
        return Table._new(op, _schema_from_columns(columns), self._universe)

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def with_universe_of(self, other: "TableLike | Table") -> "Table":
        op = Operator("with_universe_of", [self, other], params=dict())
        return Table._new(op, self._schema, other._universe)

    def restrict(self, other: "Table") -> "Table":
        if not other._universe.is_subset_of(self._universe):
            raise ValueError(
                "restrict: other's universe is not promised to be a subset of self's"
            )
        op = Operator("semijoin", [self, other], params=dict(mode="intersect"))
        return Table._new(op, self._schema, other._universe)

    def intersect(self, *tables: "Table") -> "Table":
        result = self
        for t in tables:
            op = Operator("semijoin", [result, t], params=dict(mode="intersect"))
            result = Table._new(op, result._schema, result._universe.subuniverse())
        return result

    def difference(self, other: "Table") -> "Table":
        op = Operator("semijoin", [self, other], params=dict(mode="difference"))
        return Table._new(op, self._schema, self._universe.subuniverse())

    def having(self, *indexers: ColumnReference) -> "Table":
        """Restrict to rows whose id appears among indexer values
        (reference: table.py having / indexing)."""
        result = self
        for indexer in indexers:
            op = Operator(
                "semijoin",
                [result, indexer.table],
                params=dict(mode="intersect", right_key=indexer),
            )
            result = Table._new(op, result._schema, result._universe.subuniverse())
        return result

    # -- pointer ops --
    def pointer_from(self, *args: Any, optional: bool = False, instance: Any = None) -> PointerExpression:
        return PointerExpression(
            self,
            *[resolve_expression(a, self) for a in args],
            instance=resolve_expression(instance, self) if instance is not None else None,
            optional=optional,
        )

    @property
    def slice(self):
        """Column-set view supporting without/rename/with_prefix/with_suffix
        (reference: table.py ``slice`` + table_slice.py)."""
        from .table_slice import TableSlice

        return TableSlice({n: self[n] for n in self.column_names()}, self)

    def live(self):
        """Run this table's subgraph on a background thread and return a
        live replica (reference: table.py:2565 + interactive.py)."""
        from .interactive import LiveTable

        return LiveTable._create(self)

    def ix(
        self,
        expression: Any,
        *,
        optional: bool = False,
        context: "Table | None" = None,
    ) -> "Table":
        """``other.ix(t.ptr)`` — fetch rows of ``self`` by pointer
        (reference: table.py ix / internals thisclass ix)."""
        if context is None:
            tables = _tables_of(expression)
            if len(tables) != 1:
                raise ValueError("ix: cannot infer context table; pass context=")
            (context,) = tables
        expr = resolve_expression(expression, context)
        op = Operator(
            "ix",
            [context, self],
            params=dict(ptr=expr, optional=optional),
        )
        schema = self._schema
        if optional:
            schema = _schema_from_columns(
                {
                    n: ColumnSchema(name=n, dtype=dt.Optional(c.dtype))
                    for n, c in self._schema.columns().items()
                }
            )
        return Table._new(op, schema, context._universe)

    def ix_ref(self, *args: Any, optional: bool = False, instance: Any = None, context: "Table | None" = None) -> "Table":
        if context is None:
            tables = set()
            for a in args:
                tables |= set(_tables_of(a))
            if len(tables) != 1:
                raise ValueError("ix_ref: cannot infer context table; pass context=")
            (context,) = tables
        ptr = PointerExpression(
            self,
            *[resolve_expression(a, context) for a in args],
            instance=resolve_expression(instance, context) if instance is not None else None,
            optional=optional,
        )
        return self.ix(ptr, optional=optional, context=context)

    # -- reshaping --
    def flatten(self, to_flatten: ColumnReference, *, origin_id: str | None = None) -> "Table":
        """Explode a sequence column (reference: table.py flatten /
        graph.rs flatten_table).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... who | items
        ... ann | a,b
        ... bob | c
        ... ''')
        >>> parts = t.select(t.who, item=pw.apply(lambda s: tuple(s.split(",")), t.items))
        >>> pw.debug.compute_and_print(parts.flatten(parts.item), include_id=False)
        who | item
        ann | a
        ann | b
        bob | c
        """
        col = resolve_expression(to_flatten, self)
        if not isinstance(col, ColumnReference):
            raise TypeError("flatten expects a column reference")
        inner = self._schema[col.name].dtype
        if isinstance(inner, dt.List):
            flat_dtype = inner.wrapped
        elif isinstance(inner, dt.Tuple):
            flat_dtype = dt.types_lcm(*inner.args) if inner.args else dt.ANY
        elif inner is dt.STR:
            flat_dtype = dt.STR
        elif isinstance(inner, dt.Array):
            flat_dtype = dt.ANY
        elif inner is dt.JSON:
            flat_dtype = dt.JSON
        else:
            flat_dtype = dt.ANY
        columns = {}
        for n, c in self._schema.columns().items():
            columns[n] = ColumnSchema(
                name=n, dtype=flat_dtype if n == col.name else c.dtype
            )
        if origin_id is not None:
            columns[origin_id] = ColumnSchema(name=origin_id, dtype=dt.POINTER)
        op = Operator(
            "flatten",
            [self],
            params=dict(column=col.name, origin_id=origin_id),
        )
        return Table._new(op, _schema_from_columns(columns), Universe())

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        """Re-key rows by hash of expressions (reference: table.py
        with_id_from)."""
        exprs = [resolve_expression(a, self) for a in args]
        op = Operator(
            "reindex",
            [self],
            params=dict(
                exprs=exprs,
                instance=resolve_expression(instance, self) if instance is not None else None,
            ),
        )
        return Table._new(op, self._schema, Universe())

    def with_id(self, new_index: ColumnReference) -> "Table":
        expr = resolve_expression(new_index, self)
        op = Operator("reindex", [self], params=dict(exprs=[expr], instance=None, raw=True))
        return Table._new(op, self._schema, Universe())

    # -- column-level sugar --
    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        exprs = expand_select_args(args, kwargs, self)
        all_exprs: dict[str, ColumnExpression] = {
            n: self[n] for n in self.column_names()
        }
        all_exprs.update(exprs)
        return self._select_exprs(all_exprs, universe=self._universe)

    def _export(self):
        """Expose this table to other graphs in the process
        (reference: export.rs ExportedTable / dataflow.rs:3871); import
        with ``internals.export.import_table``."""
        from .export import export_table

        return export_table(self)

    def remove_errors(self) -> "Table":
        """Drop rows containing ``ERROR`` cells
        (reference: graph.rs:984 ``remove_errors_from_table``)."""
        from .expression import ApplyExpression, FillErrorExpression
        from .value import ERROR
        from . import dtype as dt

        def row_ok(*vals) -> bool:
            return not any(v is ERROR for v in vals)

        # the evaluator short-circuits apply args containing ERROR to ERROR,
        # so wrap with fill_error to turn those rows into False
        cond = FillErrorExpression(
            ApplyExpression(row_ok, dt.BOOL, *[self[n] for n in self.column_names()]),
            False,
        )
        return self.filter(cond)

    def without(self, *columns: Any) -> "Table":
        names = {c.name if isinstance(c, ColumnReference) else c for c in columns}
        keep = [n for n in self.column_names() if n not in names]
        return self._select_exprs({n: self[n] for n in keep}, universe=self._universe)

    def rename(self, names_mapping: dict | None = None, **kwargs: Any) -> "Table":
        if names_mapping:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def rename_columns(self, **kwargs: Any) -> "Table":
        # kwargs: new_name=old_ref
        mapping = {}
        for new, old in kwargs.items():
            mapping[old.name if isinstance(old, ColumnReference) else old] = new
        return self.rename_by_dict(mapping)

    def rename_by_dict(self, names_mapping: dict) -> "Table":
        mapping = {
            (k.name if isinstance(k, ColumnReference) else k): v
            for k, v in names_mapping.items()
        }
        exprs = {}
        for n in self.column_names():
            exprs[mapping.get(n, n)] = self[n]
        return self._select_exprs(exprs, universe=self._universe)

    def cast_to_types(self, **kwargs: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {n: self[n] for n in self.column_names()}
        for n, t in kwargs.items():
            exprs[n] = CastExpression(t, self[n])
        return self._select_exprs(exprs, universe=self._universe)

    def update_types(self, **kwargs: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {n: self[n] for n in self.column_names()}
        for n, t in kwargs.items():
            exprs[n] = DeclareTypeExpression(t, self[n])
        return self._select_exprs(exprs, universe=self._universe)

    def copy(self) -> "Table":
        return self._select_exprs(
            {n: self[n] for n in self.column_names()}, universe=self._universe
        )

    # -- universe promises (reference: table.py promise_*) --
    def promise_universes_are_equal(self, other: "Table") -> "Table":
        self._universe.promise_equal(other._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        self._universe.promise_subset_of(other._universe)
        return self

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        self._universe.promise_equal(other._universe)
        return self

    # -- temporal sugar (implemented in stdlib.temporal) --
    def windowby(self, time_expr: Any, *, window: Any, instance: Any = None, behavior: Any = None, origin=None):
        from ..stdlib.temporal import windowby as _windowby

        return _windowby(self, time_expr, window=window, instance=instance, behavior=behavior)

    def sort(self, key: Any, instance: Any = None) -> "Table":
        from ..stdlib.indexing.sorting import sort as _sort

        return _sort(self, key=key, instance=instance)

    def diff(self, timestamp: Any, *values: Any, instance: Any = None) -> "Table":
        from ..stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    def interpolate(self, timestamp: Any, *values: Any, mode: Any = None) -> "Table":
        from ..stdlib.statistical import interpolate as _interpolate

        return _interpolate(self, timestamp, *values, mode=mode)

    def asof_join(self, other, self_time, other_time, *on, **kwargs):
        from ..stdlib.temporal import asof_join as _asof_join

        return _asof_join(self, other, self_time, other_time, *on, **kwargs)

    def asof_now_join(self, other, *on, **kwargs):
        from ..stdlib.temporal import asof_now_join as _asof_now_join

        return _asof_now_join(self, other, *on, **kwargs)

    def interval_join(self, other, self_time, other_time, interval, *on, **kwargs):
        from ..stdlib.temporal import interval_join as _interval_join

        return _interval_join(self, other, self_time, other_time, interval, *on, **kwargs)

    def window_join(self, other, self_time, other_time, window, *on, **kwargs):
        from ..stdlib.temporal import window_join as _window_join

        return _window_join(self, other, self_time, other_time, window, *on, **kwargs)

    def _external_index_as_of_now(self, index_factory, query_table, **kwargs):
        from ..stdlib.indexing.data_index import _external_index_as_of_now

        return _external_index_as_of_now(self, index_factory, query_table, **kwargs)


# named temporal-join modes (reference: Table.interval_join_left etc.) —
# thin delegates to the stdlib wrappers so each mode exists in one place
def _bind_temporal_mode_methods():
    names = [
        "asof_join_left", "asof_join_right", "asof_join_outer",
        "interval_join_inner", "interval_join_left",
        "interval_join_right", "interval_join_outer",
        "window_join_inner", "window_join_left",
        "window_join_right", "window_join_outer",
    ]
    for name in names:
        def method(self, other, *args, _name=name, **kwargs):
            from ..stdlib import temporal as _t

            return getattr(_t, _name)(self, other, *args, **kwargs)

        method.__name__ = name
        method.__qualname__ = f"Table.{name}"
        setattr(Table, name, method)


_bind_temporal_mode_methods()


class TableLike:
    """Anything with a universe (reference: table.py TableLike)."""

    def __init__(self, universe: Universe):
        self._universe = universe


def groupby(table: Table, *args, **kwargs) -> GroupedTable:
    return table.groupby(*args, **kwargs)


# -- helpers --

def _referenced_tables(
    exprs: Iterable[ColumnExpression], primary: Table
) -> list[Table]:
    """Additional same-universe tables referenced by the expressions."""
    found: dict[int, Table] = {}

    def walk(e: ColumnExpression):
        if isinstance(e, ColumnReference) and e.table is not None and e.table is not primary:
            t = e.table
            if id(t) not in found:
                if not t._universe.is_equal_to(primary._universe) and not (
                    t._universe.is_subset_of(primary._universe)
                    or primary._universe.is_subset_of(t._universe)
                ):
                    raise ValueError(
                        f"expression references table with a different universe: "
                        f"column {e.name!r}; use <table>.ix(...) or join instead"
                    )
                found[id(t)] = t
        for d in e._deps():
            walk(d)

    for e in exprs:
        walk(e)
    return list(found.values())


def _tables_of(e: Any) -> list[Table]:
    tables: dict[int, Table] = {}

    def walk(node):
        if isinstance(node, ColumnReference) and node.table is not None:
            tables[id(node.table)] = node.table
        for d in node._deps():
            walk(d)

    if isinstance(e, ColumnExpression):
        walk(e)
    return list(tables.values())


def _common_schema(tables: list[Table]) -> SchemaMetaclass:
    names = tables[0].column_names()
    for t in tables[1:]:
        if t.column_names() != names:
            raise ValueError(
                f"tables have different columns: {names} vs {t.column_names()}"
            )
    columns = {}
    for n in names:
        dtype = dt.types_lcm(*[t._schema[n].dtype for t in tables])
        columns[n] = ColumnSchema(name=n, dtype=dtype)
    return _schema_from_columns(columns)
