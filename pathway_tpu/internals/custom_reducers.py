"""Public custom-accumulator base for stateful reducers.

reference: python/pathway/internals/custom_reducers.py:174
(``BaseCustomAccumulator``) — subclasses implement ``from_row``,
``update`` and ``compute_result`` (optionally ``neutral`` / ``retract``)
and are turned into reducers with ``pw.reducers.udf_reducer``:

>>> import pathway_tpu as pw
>>> class CustomAvg(pw.BaseCustomAccumulator):
...     def __init__(self, sum, cnt):
...         self.sum, self.cnt = sum, cnt
...     @classmethod
...     def from_row(cls, row):
...         [val] = row
...         return cls(val, 1)
...     def update(self, other):
...         self.sum += other.sum
...         self.cnt += other.cnt
...     def compute_result(self) -> float:
...         return self.sum / self.cnt
>>> custom_avg = pw.reducers.udf_reducer(CustomAvg)
>>> t = pw.debug.table_from_markdown('''
... owner | price
... Alice | 100
... Bob   | 80
... Alice | 90
... Bob   | 70
... ''')
>>> r = t.groupby(t.owner).reduce(t.owner, avg=custom_avg(t.price))
>>> pw.debug.compute_and_print(r, include_id=False)
owner | avg
Alice | 95.0
Bob   | 75.0
"""

from __future__ import annotations

import pickle
from abc import ABC, abstractmethod
from typing import Any

__all__ = ["BaseCustomAccumulator"]


class BaseCustomAccumulator(ABC):
    """Base for custom reducer accumulators (see module docstring).

    ``serialize``/``deserialize`` default to pickle and are used when the
    accumulator state lands in operator snapshots (persistence/)."""

    @classmethod
    def neutral(cls) -> "BaseCustomAccumulator":
        """Accumulator of an empty group (optional)."""
        raise NotImplementedError

    @classmethod
    @abstractmethod
    def from_row(cls, row: list[Any]) -> "BaseCustomAccumulator":
        """Accumulator of a single row; ``row`` lists the reducer's
        positional argument values."""

    @abstractmethod
    def update(self, other: "BaseCustomAccumulator") -> None:
        """Fold ``other`` (a later accumulator) into self."""

    def retract(self, other: "BaseCustomAccumulator") -> None:
        """Remove ``other`` from self (optional; enables incremental
        deletion handling instead of group recomputation)."""
        raise NotImplementedError

    @abstractmethod
    def compute_result(self) -> Any:
        """Final reduced value for the group."""

    def serialize(self) -> Any:
        return pickle.dumps(self)

    @classmethod
    def deserialize(cls, data: Any) -> "BaseCustomAccumulator":
        return pickle.loads(data)

    # -- adapters to the engine's fold protocol (reducers.udf_reducer) --
    def __add__(self, other: "BaseCustomAccumulator") -> "BaseCustomAccumulator":
        self.update(other)
        return self

    def __sub__(self, other: "BaseCustomAccumulator") -> "BaseCustomAccumulator":
        self.retract(other)
        return self

    def retrieve(self) -> Any:
        return self.compute_result()
