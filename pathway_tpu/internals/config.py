"""Env-driven runtime config.

reference: python/pathway/internals/config.py (``PathwayConfig``) +
src/engine/dataflow/config.rs:88 (``Config::from_env`` — PATHWAY_THREADS /
PATHWAY_PROCESSES / PATHWAY_PROCESS_ID / PATHWAY_FIRST_PORT; free tier
caps 8 workers, config.rs:7-11).

The same variables drive this runtime: threads size the host-side engine
pools, processes/process_id shard sources across cooperating processes
(``pathway spawn``, cli.py), and on the device plane the mesh shape comes
from ``jax.device_count`` (parallel/mesh.py) rather than env vars.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass, field

__all__ = [
    "PathwayConfig",
    "get_pathway_config",
    "MAX_WORKERS",
    "env_int",
    "env_float",
]


def env_int(name: str, default: int, lo: int | None = None) -> int:
    """``int(os.environ[name])`` with the repo-wide garbage idiom: unset
    or blank reads the default, garbage warns loudly and falls back to
    the default (never raises at import/serve time), ``lo`` clamps."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError:
        warnings.warn(
            f"{name}={raw!r} is not an integer; using {default}", stacklevel=2
        )
        return default
    return val if lo is None else max(val, lo)


def env_float(name: str, default: float, lo: float | None = None) -> float:
    """Float twin of :func:`env_int` (same warn-and-default contract)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError:
        warnings.warn(
            f"{name}={raw!r} is not a number; using {default}", stacklevel=2
        )
        return default
    return val if lo is None else max(val, lo)

# reference caps non-enterprise runs at 8 workers (config.rs:7-11); kept as
# a constant for parity, not enforced as a license gate
MAX_WORKERS = 8


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class PathwayConfig:
    threads: int = 1
    processes: int = 1
    process_id: int = 0
    first_port: int = 10000
    #: multi-host cluster address list "host:port,host:port,..." — one entry
    #: per process in id order (timely Cluster hostfile,
    #: reference src/engine/dataflow/config.rs:108-120); None = single host
    #: at 127.0.0.1:first_port+id
    addresses: str | None = None
    run_id: str | None = None
    persistent_storage: str | None = None
    monitoring_http_port: int | None = None
    ignore_asserts: bool = False
    skip_start_log: bool = False
    license_key: str | None = None
    #: OTLP endpoint for telemetry push (reference: config.py:66
    #: ``monitoring_server`` / PATHWAY_MONITORING_SERVER)
    monitoring_server: str | None = None

    @classmethod
    def from_env(cls) -> "PathwayConfig":
        port = os.environ.get("PATHWAY_MONITORING_HTTP_PORT")
        cfg = cls(
            threads=_env_int("PATHWAY_THREADS", 1),
            processes=_env_int("PATHWAY_PROCESSES", 1),
            process_id=_env_int("PATHWAY_PROCESS_ID", 0),
            first_port=_env_int("PATHWAY_FIRST_PORT", 10000),
            addresses=os.environ.get("PATHWAY_ADDRESSES") or None,
            run_id=os.environ.get("PATHWAY_RUN_ID"),
            persistent_storage=os.environ.get("PATHWAY_PERSISTENT_STORAGE"),
            monitoring_http_port=int(port) if port else None,
            ignore_asserts=os.environ.get("PATHWAY_IGNORE_ASSERTS", "").lower()
            in ("1", "true", "yes"),
            skip_start_log=os.environ.get("PATHWAY_SKIP_START_LOG", "").lower()
            in ("1", "true", "yes"),
            license_key=os.environ.get("PATHWAY_LICENSE_KEY") or None,
            monitoring_server=os.environ.get("PATHWAY_MONITORING_SERVER")
            or None,
        )
        cfg._apply_worker_cap()
        return cfg

    def _apply_worker_cap(self) -> None:
        """Free-tier worker ceiling (reference: config.rs:98-107 — reduce
        threads, then processes, warning rather than failing; a license
        key lifts the cap the way the unlimited-workers feature does)."""
        if self.license_key is not None:
            return
        if self.total_workers > MAX_WORKERS:
            import warnings

            warnings.warn(
                f"{self.total_workers} workers exceeds the maximum allowed "
                f"({MAX_WORKERS}), reducing (set PATHWAY_LICENSE_KEY to lift)",
                stacklevel=3,
            )
            self.threads = max(MAX_WORKERS // self.processes, 0)
            if self.threads == 0:
                self.threads = 1
                if self.process_id >= MAX_WORKERS:
                    # this process is beyond the capped cluster: exiting
                    # loudly beats shrinking `processes` under it — the
                    # shrunken plane would have no address slot for us and
                    # owner hashing would no longer match the peers
                    raise RuntimeError(
                        f"process id {self.process_id} exceeds the free-tier "
                        f"worker cap ({MAX_WORKERS}); set PATHWAY_LICENSE_KEY "
                        "or launch at most "
                        f"{MAX_WORKERS} processes"
                    )
                self.processes = MAX_WORKERS

    @property
    def total_workers(self) -> int:
        return self.threads * self.processes


_config: PathwayConfig | None = None


def get_pathway_config(refresh: bool = False) -> PathwayConfig:
    global _config
    if _config is None or refresh:
        _config = PathwayConfig.from_env()
    return _config


def set_monitoring_config(*, server_endpoint: str | None) -> None:
    """Set the OTLP telemetry endpoint programmatically (reference:
    python/pathway/internals/config.py:141 ``set_monitoring_config``)."""
    if server_endpoint is None:
        os.environ.pop("PATHWAY_MONITORING_SERVER", None)
    else:
        os.environ["PATHWAY_MONITORING_SERVER"] = server_endpoint
    get_pathway_config(refresh=True)


def set_license_key(key: str | None) -> None:
    """Set the license key programmatically (reference:
    python/pathway/internals/config.py:125 ``set_license_key`` — lifts the
    free-tier worker cap the way PATHWAY_LICENSE_KEY does)."""
    if key is None:
        os.environ.pop("PATHWAY_LICENSE_KEY", None)
    else:
        os.environ["PATHWAY_LICENSE_KEY"] = key
    get_pathway_config(refresh=True)
