"""GroupedTable: ``table.groupby(...).reduce(...)``.

reference: python/pathway/internals/groupbys.py (402 LoC) + GroupedContext
(internals/column.py:498).
"""

from __future__ import annotations

from typing import Any, TYPE_CHECKING

from . import dtype as dt
from .expression import (
    ColumnExpression,
    ColumnReference,
    IdExpression,
    ReducerExpression,
    smart_wrap,
)
from .desugaring import expand_select_args, resolve_expression
from .graph import Operator
from .schema import ColumnSchema, _schema_from_columns
from .universe import Universe

if TYPE_CHECKING:
    from .table import Table


class _GroupColExpression(ColumnExpression):
    """Internal: slot reference to a grouping column in reduce output."""

    def __init__(self, slot: int, dtype: dt.DType):
        super().__init__()
        self.slot = slot
        self._slot_dtype = dtype

    def _compute_dtype(self) -> dt.DType:
        return self._slot_dtype


class _ReducerSlotExpression(ColumnExpression):
    """Internal: slot reference to a computed reducer value."""

    def __init__(self, slot: int, dtype: dt.DType):
        super().__init__()
        self.slot = slot
        self._slot_dtype = dtype

    def _compute_dtype(self) -> dt.DType:
        return self._slot_dtype


class GroupedTable:
    def __init__(
        self,
        table: "Table",
        grouping: list[ColumnExpression],
        *,
        set_id: bool = False,
        sort_by: ColumnExpression | None = None,
        instance: ColumnExpression | None = None,
        persistent_id: str | None = None,
    ):
        self._table = table
        self._grouping = grouping
        self._set_id = set_id
        self._sort_by = sort_by
        self._instance = instance
        self._persistent_id = persistent_id

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        from .table import Table

        table = self._table
        exprs = expand_select_args(args, kwargs, table)
        # map grouping expressions to slots, keyed by structural identity of refs
        group_slots: dict[Any, int] = {}
        for i, g in enumerate(self._grouping):
            group_slots[_expr_token(g)] = i

        reducer_slots: list[ReducerExpression] = []

        def substitute(node: ColumnExpression) -> ColumnExpression | None:
            tok = _expr_token(node)
            if tok is not None and tok in group_slots:
                return _GroupColExpression(group_slots[tok], node._dtype)
            if isinstance(node, ReducerExpression):
                slot = len(reducer_slots)
                reducer_slots.append(node)
                return _ReducerSlotExpression(slot, node._dtype)
            if isinstance(node, IdExpression):
                raise ValueError(
                    "cannot use .id inside reduce(); group ids are derived from "
                    "grouping columns"
                )
            if isinstance(node, ColumnReference):
                raise ValueError(
                    f"column {node.name!r} used in reduce() without a reducer "
                    "and is not a grouping column"
                )
            return None

        out_exprs: dict[str, ColumnExpression] = {}
        columns: dict[str, ColumnSchema] = {}
        for name, e in exprs.items():
            sub = e._substitute(substitute)
            out_exprs[name] = sub
            columns[name] = ColumnSchema(name=name, dtype=sub._dtype)

        schema = _schema_from_columns(columns)
        op = Operator(
            "groupby",
            [table],
            params=dict(
                grouping=self._grouping,
                out_exprs=out_exprs,
                reducers=reducer_slots,
                instance=self._instance,
                sort_by=self._sort_by,
                set_id=self._set_id,
                persistent_id=self._persistent_id,
            ),
        )
        return Table._new(op, schema, Universe())


def _expr_token(e: ColumnExpression):
    """Structural identity for matching grouping exprs inside reduce args."""
    if isinstance(e, IdExpression):
        return ("id", id(e.table))
    if isinstance(e, ColumnReference):
        return ("col", id(e.table), e.name)
    return None
