"""``pw.this`` / ``pw.left`` / ``pw.right`` sentinels.

reference: python/pathway/internals/thisclass.py.  Attribute access on the
sentinels builds unbound :class:`ThisColumnReference`s that the desugaring
pass (``internals/desugaring.py``) substitutes with real table references.
"""

from __future__ import annotations

from typing import Any

from .expression import ColumnExpression, ColumnReference

__all__ = ["this", "left", "right", "ThisSentinel", "ThisColumnReference", "ThisWithout"]


class ThisColumnReference(ColumnReference):
    def __init__(self, sentinel: "ThisSentinel", name: str):
        ColumnExpression.__init__(self)
        self._table = None  # type: ignore[assignment]
        self._sentinel = sentinel
        self._name = name

    @property
    def sentinel(self) -> "ThisSentinel":
        return self._sentinel

    def _compute_dtype(self):
        raise RuntimeError(
            f"pw.{self._sentinel.kind}.{self._name} used outside of a table context"
        )

    def __repr__(self):
        return f"pw.{self._sentinel.kind}.{self._name}"


class ThisWithout:
    """``pw.this.without('a', this.b)`` marker expanded by select desugaring."""

    def __init__(self, sentinel: "ThisSentinel", names: tuple[str, ...]):
        self.sentinel = sentinel
        self.names = names


class ThisNamespace:
    """``pw.this.C.<name>`` — column accessor immune to sentinel
    method-name collisions (mirrors ``Table.C``; reference repo:
    python/pathway/internals/thisclass.py,
    python/pathway/tests/test_colnamespace.py)."""

    __slots__ = ("_sentinel",)

    def __init__(self, sentinel: "ThisSentinel"):
        object.__setattr__(self, "_sentinel", sentinel)

    def __getattr__(self, name: str) -> Any:
        # underscore names: protocol probes (notebook display, hasattr
        # feature checks), never columns — bracket access is the escape
        # hatch, same stance as ColumnNamespace
        if name.startswith("_"):
            raise AttributeError(name)
        return ThisColumnReference(self._sentinel, name)

    def __getitem__(self, name) -> Any:
        if isinstance(name, ColumnReference):
            name = name.name
        return ThisColumnReference(self._sentinel, name)


class ThisSentinel:
    __slots__ = ("kind",)

    def __init__(self, kind: str):
        object.__setattr__(self, "kind", kind)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        if name == "C":
            return ThisNamespace(self)
        if name == "id":
            return ThisColumnReference(self, "id")
        return ThisColumnReference(self, name)

    def __getitem__(self, name) -> Any:
        if isinstance(name, ColumnReference):
            name = name.name
        return ThisColumnReference(self, name)

    def without(self, *names) -> ThisWithout:
        resolved = tuple(n.name if isinstance(n, ColumnReference) else n for n in names)
        return ThisWithout(self, resolved)

    def __iter__(self):
        # ``select(*pw.this)`` — expanded during desugaring; yield the marker
        yield ThisWithout(self, ())

    def pointer_from(self, *args, **kwargs):
        from .expression import PointerExpression

        return PointerExpression(None, *args, **kwargs)  # bound at desugar time

    def __repr__(self):
        return f"pw.{self.kind}"


this = ThisSentinel("this")
left = ThisSentinel("left")
right = ThisSentinel("right")
