"""Dtype lattice for schema columns.

reference: python/pathway/internals/dtype.py (979 LoC) — this is a leaner
re-design keeping the parts the API surface needs: scalar singletons,
Optional/Tuple/List/Array/Json/Pointer/Callable/Future composites, python
type wrapping, lattice operations (``dtype_issubclass``, ``types_lcm``).
"""

from __future__ import annotations

import datetime
from typing import Any, Callable as TCallable, Optional as TOptional, Union, get_args, get_origin

import numpy as np

from . import value as _v

__all__ = [
    "DType",
    "ANY",
    "NONE",
    "INT",
    "FLOAT",
    "BOOL",
    "STR",
    "BYTES",
    "POINTER",
    "JSON",
    "DATE_TIME_NAIVE",
    "DATE_TIME_UTC",
    "DURATION",
    "ANY_ARRAY",
    "INT_ARRAY",
    "FLOAT_ARRAY",
    "Optional",
    "Tuple",
    "List",
    "Array",
    "Callable",
    "Future",
    "Pointer",
    "wrap",
    "unoptionalize",
    "dtype_issubclass",
    "types_lcm",
    "coerce_arithmetic",
]


class DType:
    """Base class; scalar dtypes are singletons."""

    name: str = "DType"

    def __repr__(self) -> str:
        return self.name

    def is_value_compatible(self, value: Any) -> bool:
        return True

    @property
    def typehint(self) -> Any:
        return Any

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self) -> int:
        return hash(type(self).__name__)


class _Simple(DType):
    def __init__(self, name: str, pytypes: tuple, typehint: Any):
        self.name = name
        self._pytypes = pytypes
        self._typehint = typehint

    def is_value_compatible(self, value: Any) -> bool:
        if self is FLOAT and isinstance(value, int) and not isinstance(value, bool):
            return True
        if isinstance(value, bool) and self is not BOOL and self is not ANY:
            return False
        if self is ANY:
            return True
        return isinstance(value, self._pytypes)

    @property
    def typehint(self) -> Any:
        return self._typehint

    def __eq__(self, other: Any) -> bool:
        return self is other

    def __hash__(self) -> int:
        return hash(self.name)


ANY = _Simple("ANY", (object,), Any)
NONE = _Simple("NONE", (type(None),), type(None))
INT = _Simple("INT", (int, np.integer), int)
FLOAT = _Simple("FLOAT", (float, np.floating), float)
BOOL = _Simple("BOOL", (bool, np.bool_), bool)
STR = _Simple("STR", (str,), str)
BYTES = _Simple("BYTES", (bytes,), bytes)
JSON = _Simple("JSON", (_v.Json,), _v.Json)
DATE_TIME_NAIVE = _Simple("DATE_TIME_NAIVE", (_v.DateTimeNaive,), _v.DateTimeNaive)
DATE_TIME_UTC = _Simple("DATE_TIME_UTC", (_v.DateTimeUtc,), _v.DateTimeUtc)
DURATION = _Simple("DURATION", (_v.Duration,), _v.Duration)


class Pointer(DType):
    """Pointer dtype, optionally typed by target schema
    (reference: dtype.py ``Pointer``)."""

    def __init__(self, *args):
        self.args = args
        self.name = "POINTER"

    def is_value_compatible(self, value: Any) -> bool:
        return isinstance(value, _v.Pointer)

    @property
    def typehint(self):
        return _v.Pointer

    def __eq__(self, other):
        return isinstance(other, Pointer)

    def __hash__(self):
        return hash("POINTER")

    def __repr__(self):
        return "POINTER"


POINTER = Pointer()


class Optional(DType):
    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, Optional) or wrapped in (ANY, NONE):
            return wrapped
        self = object.__new__(cls)
        self.wrapped = wrapped
        self.name = f"Optional({wrapped!r})"
        return self

    def __init__(self, wrapped: DType):
        pass

    def is_value_compatible(self, value: Any) -> bool:
        return value is None or self.wrapped.is_value_compatible(value)

    @property
    def typehint(self):
        return TOptional[self.wrapped.typehint]

    def __eq__(self, other):
        return isinstance(other, Optional) and self.wrapped == other.wrapped

    def __hash__(self):
        return hash(("Optional", self.wrapped))


class Tuple(DType):
    def __init__(self, *args: DType):
        self.args = tuple(args)
        self.name = f"Tuple{self.args!r}"

    def is_value_compatible(self, value: Any) -> bool:
        return isinstance(value, tuple) and len(value) == len(self.args) and all(
            a.is_value_compatible(v) for a, v in zip(self.args, value)
        )

    @property
    def typehint(self):
        return tuple

    def __eq__(self, other):
        return isinstance(other, Tuple) and self.args == other.args

    def __hash__(self):
        return hash(("Tuple", self.args))


class List(DType):
    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        self.name = f"List({wrapped!r})"

    def is_value_compatible(self, value: Any) -> bool:
        return isinstance(value, tuple) and all(
            self.wrapped.is_value_compatible(v) for v in value
        )

    @property
    def typehint(self):
        return tuple

    def __eq__(self, other):
        return isinstance(other, List) and self.wrapped == other.wrapped

    def __hash__(self):
        return hash(("List", self.wrapped))


class Array(DType):
    """ndarray dtype (reference: dtype.py ``Array``/``ANY_ARRAY``;
    engine IntArray/FloatArray value.rs:207)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = ANY):
        self.n_dim = n_dim
        self.wrapped = wrapped
        self.name = f"Array({n_dim}, {wrapped!r})"

    def is_value_compatible(self, value: Any) -> bool:
        if not isinstance(value, np.ndarray):
            return False
        if self.n_dim is not None and value.ndim != self.n_dim:
            return False
        return True

    @property
    def typehint(self):
        return np.ndarray

    def __eq__(self, other):
        return isinstance(other, Array) and (self.n_dim, self.wrapped) == (
            other.n_dim,
            other.wrapped,
        )

    def __hash__(self):
        return hash(("Array", self.n_dim, self.wrapped))


ANY_ARRAY = Array()
INT_ARRAY = Array(wrapped=INT)
FLOAT_ARRAY = Array(wrapped=FLOAT)


class Callable(DType):
    def __init__(self, arg_types=..., return_type: DType = ANY):
        self.arg_types = arg_types
        self.return_type = return_type
        self.name = f"Callable(..., {return_type!r})"

    def is_value_compatible(self, value: Any) -> bool:
        return callable(value)

    def __eq__(self, other):
        return isinstance(other, Callable) and self.return_type == other.return_type

    def __hash__(self):
        return hash(("Callable", self.return_type))


class Future(DType):
    """Column whose values may still be PENDING
    (reference: dtype.py ``Future``, used by fully-async UDFs)."""

    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, Future):
            return wrapped
        self = object.__new__(cls)
        self.wrapped = wrapped
        self.name = f"Future({wrapped!r})"
        return self

    def __init__(self, wrapped: DType):
        pass

    def is_value_compatible(self, value: Any) -> bool:
        return value is _v.PENDING or self.wrapped.is_value_compatible(value)

    def __eq__(self, other):
        return isinstance(other, Future) and self.wrapped == other.wrapped

    def __hash__(self):
        return hash(("Future", self.wrapped))


_SIMPLE_FROM_PY: dict[Any, DType] = {
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    Any: ANY,
    _v.Json: JSON,
    _v.Pointer: POINTER,
    _v.DateTimeNaive: DATE_TIME_NAIVE,
    _v.DateTimeUtc: DATE_TIME_UTC,
    _v.Duration: DURATION,
    np.ndarray: ANY_ARRAY,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    list: List(ANY),
    tuple: Tuple(),
    dict: JSON,
}


def wrap(t: Any) -> DType:
    """Convert a python type annotation into a DType
    (reference: dtype.py ``wrap``)."""
    if isinstance(t, DType):
        return t
    if t is None:
        return NONE
    if t in _SIMPLE_FROM_PY:
        return _SIMPLE_FROM_PY[t]
    origin = get_origin(t)
    if origin is Union:
        args = get_args(t)
        non_none = [a for a in args if a is not type(None)]
        inner = types_lcm(*[wrap(a) for a in non_none]) if non_none else NONE
        if type(None) in args:
            return Optional(inner)
        return inner
    if origin in (tuple,):
        args = get_args(t)
        if len(args) == 2 and args[1] is Ellipsis:
            return List(wrap(args[0]))
        return Tuple(*[wrap(a) for a in args])
    if origin in (list,):
        args = get_args(t)
        return List(wrap(args[0]) if args else ANY)
    if origin is TCallable or t is TCallable:
        return Callable()
    if origin is np.ndarray:
        args = get_args(t)
        if len(args) == 2:
            return Array(wrapped=wrap(get_args(args[1])[0]) if get_args(args[1]) else ANY)
        return ANY_ARRAY
    if isinstance(t, type) and issubclass(t, _v.Pointer):
        return POINTER
    return ANY


def unoptionalize(dtype: DType) -> DType:
    if isinstance(dtype, Optional):
        return dtype.wrapped
    return dtype


def dtype_issubclass(sub: DType, sup: DType) -> bool:
    """Lattice order (reference: dtype.py ``dtype_issubclass``)."""
    if sup is ANY or sub == sup:
        return True
    if sub is ANY:
        return False
    if isinstance(sup, Optional):
        if sub is NONE:
            return True
        return dtype_issubclass(unoptionalize(sub), sup.wrapped)
    if isinstance(sub, Optional):
        return False
    if sub is INT and sup is FLOAT:
        return True
    if isinstance(sub, Tuple) and isinstance(sup, Tuple):
        return len(sub.args) == len(sup.args) and all(
            dtype_issubclass(a, b) for a, b in zip(sub.args, sup.args)
        )
    if isinstance(sub, Tuple) and isinstance(sup, List):
        return all(dtype_issubclass(a, sup.wrapped) for a in sub.args)
    if isinstance(sub, List) and isinstance(sup, List):
        return dtype_issubclass(sub.wrapped, sup.wrapped)
    if isinstance(sub, Array) and isinstance(sup, Array):
        return sup.n_dim is None or sub.n_dim == sup.n_dim
    if isinstance(sub, Pointer) and isinstance(sup, Pointer):
        return True
    return False


def types_lcm(*dtypes: DType) -> DType:
    """Least common supertype (reference: dtype.py ``types_lcm``)."""
    if not dtypes:
        return ANY
    result = dtypes[0]
    for d in dtypes[1:]:
        result = _lcm2(result, d)
    return result


def _lcm2(a: DType, b: DType) -> DType:
    if a == b:
        return a
    if dtype_issubclass(a, b):
        return b
    if dtype_issubclass(b, a):
        return a
    if a is NONE:
        return Optional(b)
    if b is NONE:
        return Optional(a)
    ua, ub = unoptionalize(a), unoptionalize(b)
    opt = isinstance(a, Optional) or isinstance(b, Optional)
    if ua == ub:
        inner = ua
    elif {ua, ub} == {INT, FLOAT}:
        inner = FLOAT
    else:
        return ANY
    return Optional(inner) if opt else inner


def coerce_arithmetic(a: DType, b: DType) -> DType | None:
    """Result dtype of +,-,* between numeric dtypes; None if invalid."""
    if a is INT and b is INT:
        return INT
    if a in (INT, FLOAT) and b in (INT, FLOAT):
        return FLOAT
    return None
