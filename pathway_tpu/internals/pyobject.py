"""Opaque Python-object cells.

reference: python/pathway/internals/api.py:228-300 (``PyObjectWrapper``,
``wrap_py_object``, serializer protocol).  There the wrapper ferries
arbitrary Python objects across the PyO3 boundary into the Rust engine;
here the engine is single-language, so the wrapper is a plain value
class — its (de)serialization hooks matter for persistence snapshots,
UDF caches, and sinks, and its hash feeds key derivation
(internals/keys.py) like any other value.
"""

from __future__ import annotations

import pickle
from typing import Any, Generic, TypeVar

T = TypeVar("T")

__all__ = [
    "PyObjectWrapper",
    "PyObjectWrapperSerializer",
    "wrap_py_object",
    "wrap_serializer",
]


class PyObjectWrapperSerializer:
    """Adapter keeping only ``dumps``/``loads`` from a serializer-like
    object (which may be a whole module, e.g. ``dill``)."""

    def __init__(self, serializer: Any) -> None:
        self._loads = serializer.loads
        self._dumps = serializer.dumps

    def dumps(self, object: Any) -> bytes:
        return self._dumps(object)

    def loads(self, data: bytes) -> Any:
        return self._loads(data)


def wrap_serializer(serializer: Any) -> PyObjectWrapperSerializer:
    return PyObjectWrapperSerializer(serializer)


class PyObjectWrapper(Generic[T]):
    """A cell holding an arbitrary Python object (reference: api.py:256
    ``wrap_py_object`` docs).  Construct via :func:`wrap_py_object`.

    >>> import pathway_tpu as pw
    >>> w = pw.wrap_py_object({"a": 1})
    >>> w.value
    {'a': 1}
    """

    __slots__ = ("value", "_serializer")

    def __init__(self, value: T, *, serializer: Any | None = None) -> None:
        self.value = value
        self._serializer = serializer

    def __repr__(self) -> str:
        return f"pw.wrap_py_object({self.value!r})"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PyObjectWrapper) and self.value == other.value

    def __hash__(self) -> int:
        try:
            return hash(("PyObjectWrapper", self.value))
        except TypeError:
            return hash(("PyObjectWrapper", self.dumps()))

    def dumps(self) -> bytes:
        ser = self._serializer or pickle
        return ser.dumps(self.value)

    @classmethod
    def loads(cls, data: bytes, *, serializer: Any | None = None) -> "PyObjectWrapper":
        ser = serializer or pickle
        return cls(ser.loads(data), serializer=serializer)


def wrap_py_object(
    object: T, *, serializer: Any | None = None
) -> PyObjectWrapper[T]:
    """Wrap any Python object so it can live in a table cell
    (reference: api.py:256).  ``serializer`` must expose
    ``dumps``/``loads``; ``pickle`` is used when not given."""
    ser = wrap_serializer(serializer) if serializer is not None else None
    return PyObjectWrapper(object, serializer=ser)
