"""Column-set views over a table.

reference: python/pathway/internals/table_slice.py — ``t.slice`` yields a
mapping-like view of the table's columns supporting ``without``,
``rename``, ``with_prefix``/``with_suffix`` and splatting into
``select``/``with_columns``:

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_markdown('''
... a | b
... 1 | 2
... ''')
>>> pw.debug.compute_and_print(
...     t.select(*t.slice.with_suffix("_new")), include_id=False)
a_new | b_new
1     | 2
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Iterator

from .expression import ColumnReference

if TYPE_CHECKING:
    from .table import Table

__all__ = ["TableSlice", "NamedExpr"]


class NamedExpr:
    """A (output_name, expression) pair understood by ``select``
    (desugaring.py) — lets a slice give a column a new output name while
    the underlying ColumnReference keeps resolving its source column."""

    __slots__ = ("name", "expr")

    def __init__(self, name: str, expr: ColumnReference) -> None:
        self.name = name
        self.expr = expr

    def __repr__(self) -> str:
        return f"NamedExpr({self.name}={self.expr!r})"


class TableSlice:
    """reference: table_slice.py:16."""

    def __init__(self, mapping: dict[str, ColumnReference], table: "Table"):
        self._mapping = mapping
        self._table = table

    def __iter__(self) -> Iterator[NamedExpr]:
        return iter(
            NamedExpr(name, ref) for name, ref in self._mapping.items()
        )

    def __repr__(self) -> str:
        return f"TableSlice({list(self._mapping.keys())})"

    def keys(self) -> list[str]:
        return list(self._mapping.keys())

    def __getitem__(self, args: Any):
        if isinstance(args, (list, tuple)):
            names = [self._normalize(a) for a in args]
            return TableSlice(
                {n: self._mapping[n] for n in names}, self._table
            )
        return self._mapping[self._normalize(args)]

    def __getattr__(self, name: str) -> ColumnReference:
        mapping = object.__getattribute__(self, "_mapping")
        if name in mapping:
            return mapping[name]
        raise AttributeError(f"no column {name!r} in this slice")

    def without(self, *cols: str | ColumnReference) -> "TableSlice":
        drop = {self._normalize(c) for c in cols}
        for name in drop:
            if name not in self._mapping:
                raise KeyError(f"column {name!r} not in this slice")
        return TableSlice(
            {n: r for n, r in self._mapping.items() if n not in drop},
            self._table,
        )

    def rename(
        self, mapping: dict[str | ColumnReference, str | ColumnReference]
    ) -> "TableSlice":
        renames = {
            self._normalize(old): self._normalize(new)
            for old, new in mapping.items()
        }
        for old in renames:
            if old not in self._mapping:
                raise KeyError(f"column {old!r} not in this slice")
        out: dict[str, ColumnReference] = {}
        for n, r in self._mapping.items():
            new = renames.get(n, n)
            if new in out or (
                new != n and new in self._mapping and new not in renames
            ):
                # a rename landing on a still-present column would
                # silently drop one of the two — refuse instead
                raise ValueError(f"rename collides on column {new!r}")
            out[new] = r
        return TableSlice(out, self._table)

    def with_prefix(self, prefix: str) -> "TableSlice":
        return TableSlice(
            {prefix + n: r for n, r in self._mapping.items()}, self._table
        )

    def with_suffix(self, suffix: str) -> "TableSlice":
        return TableSlice(
            {n + suffix: r for n, r in self._mapping.items()}, self._table
        )

    def ix(self, expression, *, optional: bool = False, context=None) -> "TableSlice":
        ixed = self._table.ix(expression, optional=optional)
        return TableSlice(
            {n: ixed[r.name] for n, r in self._mapping.items()}, ixed
        )

    def ix_ref(self, *args, optional: bool = False, context=None) -> "TableSlice":
        ixed = self._table.ix_ref(*args, optional=optional)
        return TableSlice(
            {n: ixed[r.name] for n, r in self._mapping.items()}, ixed
        )

    @property
    def slice(self) -> "TableSlice":
        return self

    def _normalize(self, arg: str | ColumnReference) -> str:
        if isinstance(arg, ColumnReference):
            tab = arg.table
            # accept refs of this table or of pw.this
            from .thisclass import ThisColumnReference

            if not isinstance(arg, ThisColumnReference) and tab is not self._table:
                raise ValueError(
                    "columns used in TableSlice operations must belong to "
                    "the sliced table"
                )
            return arg.name
        return str(arg)
