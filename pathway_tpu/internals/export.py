"""Cross-graph table handoff.

reference: src/engine/dataflow/export.rs (``ExportedTable``:205,
``export_table`` dataflow.rs:3871) + the Python ``Table._export`` /
``Scope.import_table`` pair — one running graph exposes a table, another
graph (typically a second ``pw.run`` loop in the same process) consumes
it live, snapshot first, then diffs.
"""

from __future__ import annotations

import threading
from typing import Any

from .schema import SchemaMetaclass
from .table import Table

__all__ = ["ExportedTable", "export_table", "import_table"]


class ExportedTable:
    """Thread-safe snapshot + diff fan-out between engine loops."""

    def __init__(self, schema: SchemaMetaclass):
        self.schema = schema
        self._lock = threading.Lock()
        self._snapshot: dict[Any, tuple] = {}
        self._subscribers: list = []  # ConnectorSubjects of importing graphs
        self._closed = False

    # -- producer side --
    def _push(self, key, values: tuple, is_addition: bool) -> None:
        # notification stays under the lock: otherwise a subscriber attaching
        # between the snapshot mutation and the notify would see the row
        # twice (once replayed, once as a live diff)
        with self._lock:
            if is_addition:
                self._snapshot[key] = values
            else:
                self._snapshot.pop(key, None)
            for subject in self._subscribers:
                if is_addition:
                    subject._add_inner(key, values)
                else:
                    subject._remove(key, values)
                subject.commit()

    def _close(self) -> None:
        with self._lock:
            self._closed = True
            subscribers = list(self._subscribers)
        for subject in subscribers:
            subject.close()

    # -- consumer side --
    def _attach_and_replay(self, subject) -> None:
        """Replay the snapshot into ``subject`` and register it for live
        diffs — atomically, so no diff is seen twice or out of order."""
        with self._lock:
            for key, values in self._snapshot.items():
                subject._add_inner(key, values)
            subject.commit()
            closed = self._closed
            if not closed:
                self._subscribers.append(subject)
        if closed:
            subject.close()

    @property
    def failed(self) -> bool:  # reference: ExportedTable::failed
        return False

    def snapshot_at_now(self) -> list[tuple[Any, tuple]]:
        with self._lock:
            return list(self._snapshot.items())


def export_table(table: Table) -> ExportedTable:
    """Register ``table`` for export; drive the graph with ``pw.run``
    (threaded for live handoff)."""
    from ..io._subscribe import subscribe

    exported = ExportedTable(table.schema)
    names = table.column_names()

    def on_change(key, row: dict, time: int, is_addition: bool) -> None:
        exported._push(key, tuple(row[n] for n in names), is_addition)

    subscribe(
        table, on_change=on_change, on_end=exported._close,
        name="export_table",
    )
    return exported


def import_table(exported: ExportedTable) -> Table:
    """Materialize an exported table in the current graph: snapshot replay,
    then live diffs until the exporting graph closes."""
    from ..io._utils import input_table
    from ..io.streaming import ConnectorSubject

    class _ImportSubject(ConnectorSubject):
        def run(self) -> None:
            exported._attach_and_replay(self)
            # live diffs arrive via _push; block until the exporter closes
            self._closed.wait()

    subject = _ImportSubject(datasource_name="import_table")
    subject._configure(exported.schema, None)
    return input_table(exported.schema, subject=subject)
