"""Process-global health registry backing the ``/v1/health`` endpoint.

reference inspiration: the reference engine exposes per-connector monitor
state and an OpenMetrics endpoint but no liveness/readiness contract; a
live RAG service needs one (EdgeRAG, arXiv 2412.21023: degrade gracefully
under resource failure instead of failing closed).  Components across the
stack register here:

* the streaming driver registers the ``engine`` component and heartbeats
  it every loop iteration (an engine watchdog: a wedged engine thread
  stops beating and readiness drops);
* the connector supervisor (``io/streaming.py``) registers one
  ``connector:<name>`` component per source with its supervision state
  (``running`` / ``backoff`` / ``failed`` / ``finished``);
* serving circuit breakers (``xpacks/llm/_breaker.py``) register
  ``breaker:<name>`` components — an OPEN breaker marks the process
  *degraded* (still serving, via fallbacks) rather than unready;
* the distributed driver registers ``ingest_thread`` and flips it to
  ``leaked`` if the thread survives its join timeout.

Readiness = every *critical* component is ready AND the engine heartbeat
(when an engine is registered and running) is fresher than
``engine_stall_s``.  Degraded = ready, but at least one component flags
itself degraded (tripped breaker, connector in backoff).

Scope note: the registry assumes ONE live engine per process (the
deployment shape of every server here; multi-process scale-out gives
each process its own registry).  Starting a second concurrent ``pw.run``
in the same process re-claims the run-scoped components — the last run
owns ``/v1/health``.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any

__all__ = ["HealthRegistry", "get_health", "reset_health"]


def _attach_module_block(
    snap: dict, key: str, module_name: str, fn_name: str
) -> None:
    """Read-only status block gated on ``module_name`` ALREADY being
    imported — a health probe must never pull in jax state just by
    probing, and a subsystem that was never used contributes nothing.
    Any failure is swallowed: health must never raise."""
    try:
        import sys as _sys

        mod = _sys.modules.get(module_name)
        if mod is not None:
            block = getattr(mod, fn_name)()
            if block:
                snap[key] = block
    except Exception:  # noqa: BLE001 — health must never raise
        pass


class HealthRegistry:
    """Thread-safe component/heartbeat registry (see module docstring)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {state, ready, degraded, critical, detail, since, scope}
        self._components: dict[str, dict] = {}
        self._beats: dict[str, float] = {}
        self.started_at = time.time()
        #: process epoch: a fresh registry = a fresh process (or a test
        #: reset — the same trust boundary).  ``id`` is the identity, and
        #: ``start_seq`` (ns wall clock at creation) orders epochs, so a
        #: fleet router / external LB can tell a RESTARTED replica from a
        #: long-lived one and re-verify its snapshot watermark instead of
        #: trusting capacity history from the previous process.
        self._epoch = {
            "id": uuid.uuid4().hex[:12],
            "start_seq": time.time_ns(),
        }
        self.engine_stall_s = float(
            os.environ.get("PATHWAY_HEALTH_STALL_S", "10")
        )
        #: wall clock of the last durable commit record (streaming driver)
        self._last_commit_at: float | None = None
        #: per-index restore progress (warm-restart health gate):
        #: pid -> {state, chunks_replayed, rows_restored, duration_ms}
        self._restores: dict[str, dict] = {}

    # -- recovery plane -------------------------------------------------
    def note_commit(self) -> None:
        """Stamp a durable commit record; ``/v1/health`` reports the age
        so operators can tell a quiescent pipeline from a stalled one."""
        self._last_commit_at = time.time()

    def set_restore(self, name: str, **info: Any) -> None:
        """Merge warm-restart progress for one index keyspace
        (``state`` restoring/ok/failed, ``chunks_replayed``,
        ``rows_restored``, ``duration_ms``) into the health snapshot's
        ``index_restore`` map — the observable that distinguishes
        "warming" from "stalled"."""
        with self._lock:
            self._restores.setdefault(name, {}).update(info)

    def set_component(
        self,
        name: str,
        state: str,
        *,
        ready: bool = True,
        degraded: bool = False,
        critical: bool = True,
        detail: str = "",
        scope: str = "run",
    ) -> None:
        """``scope="run"`` components are cleared by :meth:`begin_run`
        (driver-owned: engine, connectors); ``scope="process"`` ones
        persist (breakers, serving planes)."""
        with self._lock:
            self._components[name] = {
                "state": state,
                "ready": bool(ready),
                "degraded": bool(degraded),
                "critical": bool(critical),
                "detail": detail,
                "since": time.time(),
                "scope": scope,
            }

    def remove_component(self, name: str) -> None:
        with self._lock:
            self._components.pop(name, None)
            self._beats.pop(name, None)

    def beat(self, name: str = "engine") -> None:
        # plain float store is GIL-atomic; no lock on the hot path
        self._beats[name] = time.monotonic()

    def heartbeat_age(self, name: str = "engine") -> float | None:
        t = self._beats.get(name)
        return None if t is None else time.monotonic() - t

    def begin_run(self) -> None:
        """Called by the streaming driver at run start: a fresh run owns
        the run-scoped components (a previous run's finished connectors
        must not linger in the snapshot)."""
        with self._lock:
            self._components = {
                n: c
                for n, c in self._components.items()
                if c.get("scope") != "run"
            }
            self._beats.pop("engine", None)
            self._restores.clear()
            # run-scoped like the engine heartbeat: a fresh run must not
            # inherit the previous run's commit freshness
            self._last_commit_at = None

    def epoch(self) -> dict[str, Any]:
        """Monotonic process-epoch block (see ``_epoch``)."""
        return {
            **self._epoch,
            "started_at": round(self.started_at, 3),
            "uptime_s": round(time.time() - self.started_at, 3),
        }

    # -- snapshot / readiness ------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            components = {n: dict(c) for n, c in self._components.items()}
        engine = components.get("engine")
        engine_age = self.heartbeat_age("engine")
        if (
            engine is not None
            and engine["state"] == "running"
            and engine_age is not None
            and engine_age > self.engine_stall_s
        ):
            engine["state"] = "stalled"
            engine["ready"] = False
            engine["detail"] = (
                f"no heartbeat for {engine_age:.1f}s "
                f"(threshold {self.engine_stall_s:g}s)"
            )
        for name, comp in components.items():
            comp.pop("scope", None)
            comp["since"] = round(time.time() - comp["since"], 3)
        if engine is None:
            # warmup: the webserver can be up before the engine loop is —
            # report unready instead of guessing
            ready = False
            status = "starting"
        else:
            ready = all(
                c["ready"] for c in components.values() if c["critical"]
            )
            degraded = any(c["degraded"] for c in components.values())
            status = "ready" if ready else "unready"
            if ready and degraded:
                status = "degraded"
        snap: dict[str, Any] = {
            "status": status,
            "ready": ready,
            "epoch": self.epoch(),
            "components": components,
        }
        if engine_age is not None:
            snap["engine_heartbeat_age_s"] = round(engine_age, 3)
        if self._last_commit_at is not None:
            snap["last_commit_age_s"] = round(
                time.time() - self._last_commit_at, 3
            )
        with self._lock:
            if self._restores:
                snap["index_restore"] = {
                    n: dict(info) for n, info in self._restores.items()
                }
        from .errors import error_stats

        snap["errors"] = error_stats()
        # observability plane: ring-buffer fill + freshness watermarks ride
        # the health snapshot so one curl shows "how stale and how traced"
        try:
            from .flight_recorder import get_recorder, tracing_settings
            from .monitoring import get_freshness

            snap["tracing"] = {
                **tracing_settings(),
                "flight_recorder": get_recorder().stats(),
            }
            freshness = get_freshness().stats()
            if freshness:
                snap["freshness"] = freshness
        except Exception:  # noqa: BLE001 — health must never raise
            pass
        # unified device-tick runtime: per-QoS-class queue/tick state —
        # read-only (a health probe must never spawn the runtime thread)
        try:
            from ..runtime import runtime_stats_if_active

            runtime_stats = runtime_stats_if_active()
            if runtime_stats is not None:
                snap["runtime"] = runtime_stats
        except Exception:  # noqa: BLE001 — health must never raise
            pass
        # sys.modules-gated subsystem blocks (see _attach_module_block):
        # mesh shape/shard rows, quantization dtype/footprint, tiered
        # rows/migrations, serving query-cache counters, SLO burn-rate
        # verdicts (the middleware imports slo on the first request — a
        # bare probe never mints empty series), the capacity payload a
        # least-loaded fleet router places load on (HBM ledger totals +
        # free HBM + runtime occupancy, ROADMAP item 4), and paged-KV
        # generation counters — whose "faults" sub-block (launch-retry /
        # containment / replay counters, per-session breaker states and
        # recovering flags) is what an operator reads first during a
        # generation-plane incident
        _attach_module_block(
            snap, "mesh", "pathway_tpu.parallel.index", "mesh_status"
        )
        _attach_module_block(
            snap, "quantization", "pathway_tpu.ops.knn", "quantization_status"
        )
        _attach_module_block(
            snap, "tiering", "pathway_tpu.tiering.index", "tiering_status"
        )
        _attach_module_block(
            snap,
            "query_cache",
            "pathway_tpu.xpacks.llm._query_cache",
            "query_cache_status",
        )
        _attach_module_block(
            snap, "slo", "pathway_tpu.observability.slo", "slo_status"
        )
        _attach_module_block(
            snap,
            "capacity",
            "pathway_tpu.observability.hbm_ledger",
            "capacity_status",
        )
        _attach_module_block(
            snap,
            "generation",
            "pathway_tpu.generation.engine",
            "generation_status",
        )
        # fleet membership: replica identity, drain state, and the
        # ingest/queryable watermarks the router's convergence probe and
        # epoch re-verification read
        _attach_module_block(
            snap, "fleet", "pathway_tpu.fleet.member", "fleet_status"
        )
        try:
            from ..testing import faults

            if faults.enabled:
                snap["faults"] = faults.stats()
        except Exception:  # noqa: BLE001 — health must never raise
            pass
        return snap


_health_lock = threading.Lock()
_health: HealthRegistry | None = None


def get_health() -> HealthRegistry:
    global _health
    with _health_lock:
        if _health is None:
            _health = HealthRegistry()
        return _health


def reset_health() -> None:
    """Test isolation hook: drop the process-global registry."""
    global _health
    with _health_lock:
        _health = None
