"""Persistent background event loop for async operators.

reference: the engine keeps one tokio runtime alive for all async_apply
operators (src/engine/dataflow.rs YieldingRuntime / graph.rs:723
``async_apply_table``) instead of spinning a runtime per batch.  This is
the same contract for the host engine: one daemon thread runs a single
asyncio loop for the process; nodes submit coroutines and receive
concurrent futures.  On TPU this is what lets device dispatch (an async
embed/score batch) run while the engine keeps flushing host dataflow —
the host/device overlap a TPU framework must get right.
"""

from __future__ import annotations

import asyncio
import atexit
import threading
from concurrent.futures import Future
from typing import Any, Coroutine

__all__ = ["get_loop", "submit"]

_lock = threading.Lock()
_loop: asyncio.AbstractEventLoop | None = None
_thread: threading.Thread | None = None


def get_loop() -> asyncio.AbstractEventLoop:
    """The process-wide background event loop (started on first use)."""
    global _loop, _thread
    with _lock:
        if _loop is None:
            loop = asyncio.new_event_loop()
            started = threading.Event()

            def _run() -> None:
                asyncio.set_event_loop(loop)
                loop.call_soon(started.set)
                loop.run_forever()

            th = threading.Thread(
                target=_run, name="pathway-aio", daemon=True
            )
            th.start()
            started.wait()
            _loop, _thread = loop, th
            atexit.register(_shutdown)
        return _loop


def submit(coro: Coroutine[Any, Any, Any]) -> Future:
    """Schedule ``coro`` on the persistent loop; returns a concurrent
    Future resolvable from any thread."""
    return asyncio.run_coroutine_threadsafe(coro, get_loop())


def _shutdown() -> None:
    global _loop
    with _lock:
        if _loop is not None and _loop.is_running():
            _loop.call_soon_threadsafe(_loop.stop)
        _loop = None
