"""UDF system: ``@pw.udf``, executors, retries, caches.

reference: python/pathway/internals/udfs/__init__.py:68 (``UDF`` base),
executors.py:36,92,132 (auto/sync/async executors w/ capacity+timeout),
retries.py:58 (ExponentialBackoffRetryStrategy), caches.py:35,120
(DiskCache/InMemoryCache).
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import inspect
import os
import pickle
import random
import time
from typing import Any, Callable

from . import dtype as dt
from .expression import (
    ApplyExpression,
    AsyncApplyExpression,
    FullyAsyncApplyExpression,
    ColumnExpression,
    smart_wrap,
)

__all__ = [
    "UDF",
    "udf",
    "auto_executor",
    "sync_executor",
    "async_executor",
    "fully_async_executor",
    "NoRetryStrategy",
    "FixedDelayRetryStrategy",
    "ExponentialBackoffRetryStrategy",
    "CacheStrategy",
    "InMemoryCache",
    "DiskCache",
    "DefaultCache",
    "async_options",
    "coerce_async",
    "with_cache_strategy",
    "with_retry_strategy",
    "with_capacity",
    "with_timeout",
]


# ---------------------------------------------------------------------------
# retry strategies (reference: internals/udfs/retries.py)
# ---------------------------------------------------------------------------


class AsyncRetryStrategy:
    async def invoke(self, fun: Callable, /, *args, **kwargs):
        raise NotImplementedError


class NoRetryStrategy(AsyncRetryStrategy):
    async def invoke(self, fun, /, *args, **kwargs):
        return await fun(*args, **kwargs)


class FixedDelayRetryStrategy(AsyncRetryStrategy):
    """reference: retries.py ``FixedDelayRetryStrategy``"""

    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        self.max_retries = max_retries
        self.delay_ms = delay_ms

    def _next_delay(self, attempt: int) -> float:
        return self.delay_ms / 1000

    async def invoke(self, fun, /, *args, **kwargs):
        last: Exception | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return await fun(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001
                last = exc
                if attempt == self.max_retries:
                    break
                await asyncio.sleep(self._next_delay(attempt))
        # annotate exhaustion so the error-log entry distinguishes a
        # retried-to-death call from a first-shot failure
        try:
            last.retries_exhausted = self.max_retries  # type: ignore[union-attr]
        except Exception:  # noqa: BLE001 — slots-only exception classes
            pass
        raise last  # type: ignore[misc]


class ExponentialBackoffRetryStrategy(FixedDelayRetryStrategy):
    """reference: retries.py:58"""

    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1000,
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
    ):
        super().__init__(max_retries=max_retries, delay_ms=initial_delay)
        self.backoff_factor = backoff_factor
        self.jitter_ms = jitter_ms

    def _next_delay(self, attempt: int) -> float:
        base = self.delay_ms * (self.backoff_factor**attempt)
        return (base + random.uniform(0, self.jitter_ms)) / 1000


# ---------------------------------------------------------------------------
# cache strategies (reference: internals/udfs/caches.py)
# ---------------------------------------------------------------------------


class CacheStrategy:
    def wrap_async(self, fun: Callable) -> Callable:
        raise NotImplementedError

    @staticmethod
    def _cache_key(name: str, args, kwargs) -> str:
        payload = pickle.dumps((name, args, tuple(sorted(kwargs.items()))))
        return hashlib.blake2b(payload, digest_size=16).hexdigest()


class InMemoryCache(CacheStrategy):
    """reference: caches.py:120"""

    def __init__(self):
        self._store: dict[str, Any] = {}

    def wrap_async(self, fun):
        name = getattr(fun, "__name__", "udf")

        @functools.wraps(fun)
        async def wrapper(*args, **kwargs):
            key = self._cache_key(name, args, kwargs)
            if key in self._store:
                return self._store[key]
            result = await fun(*args, **kwargs)
            self._store[key] = result
            return result

        return wrapper


class DiskCache(CacheStrategy):
    """Pickle-per-key cache directory
    (reference: caches.py:35 DiskCache via the diskcache lib; here a plain
    directory of pickles under PATHWAY_PERSISTENT_STORAGE)."""

    def __init__(self, name: str | None = None, directory: str | None = None):
        self._name = name
        self._dir = directory

    def _resolve_dir(self, fun_name: str) -> str:
        base = self._dir or os.environ.get(
            "PATHWAY_PERSISTENT_STORAGE", os.path.join(os.getcwd(), ".pathway-cache")
        )
        d = os.path.join(base, "udf-cache", self._name or fun_name)
        os.makedirs(d, exist_ok=True)
        return d

    def wrap_async(self, fun):
        name = getattr(fun, "__name__", "udf")
        directory = self._resolve_dir(name)

        @functools.wraps(fun)
        async def wrapper(*args, **kwargs):
            key = self._cache_key(name, args, kwargs)
            path = os.path.join(directory, key)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    return pickle.load(f)
            result = await fun(*args, **kwargs)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(result, f)
            os.replace(tmp, path)
            return result

        return wrapper


class DefaultCache(DiskCache):
    """reference: caches.py DefaultCache — uses the persistence layer when
    a run with UDF_CACHING is active (vector_store.py:564-567), a disk
    cache otherwise.  The backend is looked up per call so the same UDF
    object works across runs with different persistence configs."""

    def wrap_async(self, fun):
        name = getattr(fun, "__name__", "udf")
        disk_wrapped = super().wrap_async(fun)

        @functools.wraps(fun)
        async def wrapper(*args, **kwargs):
            from ..persistence import udf_cache_storage

            storage = udf_cache_storage()
            if storage is None:
                return await disk_wrapped(*args, **kwargs)
            key = "udfcache/" + self._cache_key(self._name or name, args, kwargs)
            hit = storage.get(key)
            if hit is not None:
                return pickle.loads(hit)
            result = await fun(*args, **kwargs)
            storage.put(key, pickle.dumps(result))
            return result

        return wrapper


# ---------------------------------------------------------------------------
# executors (reference: internals/udfs/executors.py)
# ---------------------------------------------------------------------------


class Executor:
    kind = "auto"
    capacity: int | None = None
    timeout: float | None = None
    retry_strategy: AsyncRetryStrategy | None = None


class AutoExecutor(Executor):
    kind = "auto"


class SyncExecutor(Executor):
    kind = "sync"


class AsyncExecutor(Executor):
    kind = "async"

    def __init__(
        self,
        *,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
    ):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy


class FullyAsyncExecutor(AsyncExecutor):
    kind = "fully_async"


def auto_executor() -> Executor:
    return AutoExecutor()


def sync_executor() -> Executor:
    return SyncExecutor()


def async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
) -> Executor:
    return AsyncExecutor(capacity=capacity, timeout=timeout, retry_strategy=retry_strategy)


def fully_async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
) -> Executor:
    return FullyAsyncExecutor(capacity=capacity, timeout=timeout, retry_strategy=retry_strategy)


# ---------------------------------------------------------------------------
# function wrappers
# ---------------------------------------------------------------------------


def coerce_async(fun: Callable) -> Callable:
    """Wrap a sync callable into an async one (reference: udfs/utils.py)."""
    if asyncio.iscoroutinefunction(fun):
        return fun

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return fun(*args, **kwargs)

    return wrapper


def with_retry_strategy(fun: Callable, retry_strategy: AsyncRetryStrategy) -> Callable:
    fun = coerce_async(fun)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await retry_strategy.invoke(fun, *args, **kwargs)

    return wrapper


def with_timeout(fun: Callable, timeout: float) -> Callable:
    fun = coerce_async(fun)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await asyncio.wait_for(fun(*args, **kwargs), timeout=timeout)

    return wrapper


def with_capacity(fun: Callable, capacity: int) -> Callable:
    fun = coerce_async(fun)
    sem = asyncio.Semaphore(capacity)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        async with sem:
            return await fun(*args, **kwargs)

    return wrapper


def with_cache_strategy(fun: Callable, cache_strategy: CacheStrategy) -> Callable:
    return cache_strategy.wrap_async(coerce_async(fun))


def async_options(
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    cache_strategy: CacheStrategy | None = None,
):
    """Decorator applying async options to a raw (non-UDF) async callable
    (reference: udfs/__init__.py ``async_options``)."""

    def decorate(fun):
        fun = coerce_async(fun)
        if retry_strategy is not None:
            fun = with_retry_strategy(fun, retry_strategy)
        if timeout is not None:
            fun = with_timeout(fun, timeout)
        if cache_strategy is not None:
            fun = with_cache_strategy(fun, cache_strategy)
        return fun

    return decorate


# ---------------------------------------------------------------------------
# UDF base (reference: internals/udfs/__init__.py:68)
# ---------------------------------------------------------------------------


class UDF:
    """Subclass and override ``__wrapped__``, or use the ``@pw.udf``
    decorator.  Calling the UDF on column expressions builds an apply node."""

    func: Callable | None = None

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        max_batch_size: int | None = None,
    ):
        self.return_type = return_type
        self.deterministic = deterministic
        self.propagate_none = propagate_none
        self.executor = executor or auto_executor()
        self.cache_strategy = cache_strategy
        self.max_batch_size = max_batch_size

    def __wrapped__(self, *args, **kwargs):
        if self.func is None:
            raise NotImplementedError("override __wrapped__ in a UDF subclass")
        return self.func(*args, **kwargs)

    def _resolved_return_type(self) -> Any:
        if self.return_type is not None:
            return self.return_type
        target = self.func or type(self).__wrapped__
        try:
            hints = inspect.get_annotations(target, eval_str=True)
        except Exception:
            hints = getattr(target, "__annotations__", {})
        if "return" in hints:
            return hints["return"]
        return Any

    def _is_async(self) -> bool:
        target = self.func or type(self).__wrapped__
        if self.executor.kind in ("async", "fully_async"):
            return True
        if self.executor.kind == "sync":
            return False
        return asyncio.iscoroutinefunction(target)

    def async_callable(self) -> Callable:
        """The fully-wrapped async callable this UDF executes per row —
        retry strategy, timeout and cache applied in executor order.  Lets
        supervision layers (e.g. the circuit-breaker-guarded LLM path in
        ``xpacks/llm/question_answering.py``) invoke the UDF's semantics
        outside an expression context without losing its resilience
        config."""
        afun = coerce_async(self.__wrapped__)
        if self.executor.retry_strategy is not None:
            afun = with_retry_strategy(afun, self.executor.retry_strategy)
        if self.executor.timeout is not None:
            afun = with_timeout(afun, self.executor.timeout)
        if self.cache_strategy is not None:
            afun = with_cache_strategy(afun, self.cache_strategy)
        return afun

    def __call__(self, *args, **kwargs) -> ColumnExpression:
        fun: Callable = self.__wrapped__
        return_type = self._resolved_return_type()
        if self._is_async():
            afun = self.async_callable()
            expr_cls = (
                FullyAsyncApplyExpression
                if self.executor.kind == "fully_async"
                else AsyncApplyExpression
            )
            expr = expr_cls(
                afun,
                return_type,
                *args,
                propagate_none=self.propagate_none,
                deterministic=self.deterministic,
                **kwargs,
            )
            expr.capacity = self.executor.capacity  # type: ignore[attr-defined]
            return expr
        if self.cache_strategy is not None:
            cached = with_cache_strategy(fun, self.cache_strategy)

            def fun_sync(*a, **kw):
                return asyncio.run(cached(*a, **kw))

            fun = fun_sync
        return ApplyExpression(
            fun,
            return_type,
            *args,
            propagate_none=self.propagate_none,
            deterministic=self.deterministic,
            max_batch_size=self.max_batch_size,
            **kwargs,
        )


class _FunctionUDF(UDF):
    def __init__(self, fun: Callable, **kwargs):
        super().__init__(**kwargs)
        self.func = fun
        functools.update_wrapper(self, fun)

    def __wrapped__(self, *args, **kwargs):
        return self.func(*args, **kwargs)


def udf(
    fun: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
    max_batch_size: int | None = None,
):
    """``@pw.udf`` decorator (reference: udfs/__init__.py ``udf``).

    Example:

    >>> import pathway_tpu as pw
    >>> @pw.udf
    ... def shout(s: str) -> str:
    ...     return s.upper() + "!"
    >>> t = pw.debug.table_from_markdown('''
    ... word
    ... hi
    ... there
    ... ''')
    >>> pw.debug.compute_and_print(t.select(loud=shout(t.word)), include_id=False)
    loud
    HI!
    THERE!
    """

    def wrap(f: Callable) -> UDF:
        return _FunctionUDF(
            f,
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            max_batch_size=max_batch_size,
        )

    if fun is not None:
        return wrap(fun)
    return wrap


def udf_async(fun: Callable | None = None, /, **kwargs):
    """Deprecated alias of ``@pw.udf`` with the async executor
    (reference: pathway/__init__.py ``udf_async``)."""
    if "executor" not in kwargs:
        kwargs["executor"] = async_executor()
    return udf(fun, **kwargs) if fun is not None else udf(**kwargs)


class UDFSync(UDF):
    """Deprecated alias of :class:`UDF` (reference parity)."""


class UDFAsync(UDF):
    """Deprecated alias of :class:`UDF` with the async executor."""

    def __init__(self, *args, **kwargs):
        if "executor" not in kwargs:
            kwargs["executor"] = async_executor()
        super().__init__(*args, **kwargs)
