"""Multi-process data plane: record exchange at stateful operator
boundaries.

reference: timely's ``CommunicationConfig::Cluster`` TCP transport
(vendored external/timely-dataflow/communication, wired by
src/engine/dataflow/config.rs:71-120 from PATHWAY_PROCESSES/PROCESS_ID/
FIRST_PORT) and its Exchange pacts hashing ``Key`` to a worker
(value.rs:38-99 shard semantics).

Design here: every process runs the identical engine graph on its shard
of records.  Shared sources (fs/kafka/s3 scanners that every process can
see) apply an ownership filter at ingestion — a record enters the system
on exactly one process — and :class:`ExchangeNode`s spliced before every
stateful operator re-partition records by that operator's key (group key,
join key, instance, …) over a TCP full mesh.

Progress is asynchronous, not lockstep: a round's stage 1 — drain
sources, flush the ingest-safe subgraph, partition + ``send`` first-hop
exchange batches (``prepare``) — may run up to ``PATHWAY_EXCHANGE_LOOKAHEAD``
rounds ahead of the oldest unfinished round, so one worker's slow round
overlaps the others' later ingest instead of serializing the cluster
(the role timely's frontier-based progress tracking plays in the
reference).  Stage 2 (``recv`` + stateful flush) completes rounds
strictly in order, which is what keeps the engine's per-timestamp
consistency global; the bounded lookahead doubles as flow control —
peer inboxes hold at most W unpopped batches per (channel, sender).

TPU mapping: this is the host/DCN plane.  Device-plane collectives
(all-gather top-k of the sharded HBM index, psum stats) ride ICI inside
jit — see ``pathway_tpu/parallel``.
"""

from __future__ import annotations

import hashlib
import hmac
import pickle
import socket
import struct
import threading
import time as _time
from typing import Any, Callable

from .engine import Entry, Node, consolidate, freeze_value
from .wire import decode_frame, encode_frame

__all__ = [
    "ExchangePlane",
    "ExchangeNode",
    "owner_of",
    "insert_exchanges",
    "parse_addresses",
]

_HDR = struct.Struct("<Q")

_digest_eq = hmac.compare_digest


def parse_addresses(spec: str) -> list[tuple[str, int]]:
    """Parse a ``host:port,host:port,...`` cluster address list
    (reference: timely ``CommunicationConfig::Cluster`` hostfile entries,
    src/engine/dataflow/config.rs:108-120)."""
    out: list[tuple[str, int]] = []
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        if not host:
            raise ValueError(f"address {part!r} must be host:port")
        out.append((host, int(port)))
    return out


def owner_of(value: Any, n: int) -> int:
    """Deterministic shard owner of a (frozen) key value."""
    payload = pickle.dumps(freeze_value(value))
    h = int.from_bytes(hashlib.blake2b(payload, digest_size=8).digest(), "little")
    return h % n


class ExchangePlane:
    """TCP full mesh between the PATHWAY_PROCESSES processes.

    Addressing: by default processes live on one host at
    ``127.0.0.1:first_port+id`` (reference single-node cluster,
    config.rs:113-116); pass ``addresses`` (or set ``PATHWAY_ADDRESSES``
    to ``host:port,host:port,...``, one entry per process in id order) to
    span hosts — the multi-host form of timely's
    ``CommunicationConfig::Cluster`` hostfile.

    Frames are the length-prefixed binary wire format of
    :mod:`pathway_tpu.internals.wire`, not pickle.  Flow control is
    end-to-end by protocol: every ``exchange`` is a barrier per
    (channel, time), so a peer cannot race more than one unpopped batch
    ahead on any (channel, sender) queue and the whole inbox is bounded
    by the channel count of one engine round — no unbounded buffering is
    reachable from a well-behaved peer, the role timely's progress
    tracking plays in the reference.

    Peers authenticate on connect with a mutual challenge-response
    keyed by ``PATHWAY_EXCHANGE_TOKEN`` (empty default): each side proves
    knowledge of the token by MACing the other side's fresh nonce, so an
    observer of one handshake cannot replay anything (the old static
    token digest was replayable).  Stray connections (port scanners,
    wrong cluster) are dropped without consuming a peer slot and without
    ever reaching frame decoding — set a strong token on any shared
    network (a passive observer can brute-force weak tokens offline from
    a captured nonce/MAC pair).
    """

    #: connection preamble: magic + sender id + client nonce
    _HELLO_MAGIC = b"PWXCHG02"

    def __init__(self, processes: int, process_id: int, first_port: int,
                 host: str = "127.0.0.1",
                 addresses: list[tuple[str, int]] | None = None,
                 token: str | None = None):
        self.n = processes
        self.me = process_id
        self.first_port = first_port
        self.host = host
        if addresses is not None and len(addresses) != processes:
            raise ValueError(
                f"PATHWAY_ADDRESSES lists {len(addresses)} entries for "
                f"{processes} processes"
            )
        self.addresses = addresses or [
            (host, first_port + i) for i in range(processes)
        ]
        if token is None:
            import os

            token = os.environ.get("PATHWAY_EXCHANGE_TOKEN", "")
        self._has_token = bool(token)
        #: MAC key: fixed-size derivation of the (arbitrary-length) token
        self._token_key = hashlib.blake2b(
            token.encode("utf-8"), digest_size=32
        ).digest()
        self._send: dict[int, socket.socket] = {}
        self._send_locks: dict[int, threading.Lock] = {
            p: threading.Lock() for p in range(processes)
        }
        self._inbox: dict[tuple, list] = {}  # (channel, time, from) -> payload
        self._cv = threading.Condition()
        #: max seconds a barrier waits for a peer before declaring it dead —
        #: generous, because a peer may legitimately sit in long local
        #: compute (first jit compile) between barriers
        self.barrier_timeout = 600.0
        self._server: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._closed = False
        #: sender ids whose inbound connection dropped (peer crashed or
        #: closed): barriers abort promptly instead of timing out
        self._down: set[int] = set()
        #: last decode/transport error per dropped peer (surfaced in the
        #: barrier's ConnectionError so misconfigurations are actionable)
        self._peer_errors: dict[int, str] = {}

    # -- wiring --
    def start(self, timeout: float | None = None) -> None:
        if timeout is None:
            # overridable for loaded hosts where a peer may take far
            # longer than 30s just to import its runtime (observed in
            # full-suite CI: the slow peer's partner timed out here, died
            # on its daemon thread, and the run hung silently)
            import os as _os

            timeout = float(_os.environ.get("PATHWAY_CONNECT_TIMEOUT_S", "30"))
        # the wire format's tagged pickle escape hatch means an
        # authenticated frame can execute code: spanning real hosts
        # without a shared secret would leave the port open to anyone who
        # can compute blake2b("") — refuse instead of warn
        if not self._has_token and any(
            h not in ("127.0.0.1", "localhost", "::1")
            for h, _ in self.addresses
        ):
            raise ValueError(
                "PATHWAY_ADDRESSES spans non-loopback hosts: set "
                "PATHWAY_EXCHANGE_TOKEN (shared secret) on every process"
            )
        my_host, my_port = self.addresses[self.me]
        # bind the advertised name when it resolves locally (pod DNS
        # resolves to the pod's own ip); fall back to all interfaces only
        # if it doesn't — never silently for loopback setups
        try:
            self._server = socket.create_server(
                (my_host, my_port), backlog=self.n
            )
        except OSError:
            if my_host in ("127.0.0.1", "localhost"):
                raise
            self._server = socket.create_server(("", my_port), backlog=self.n)
        accept_th = threading.Thread(target=self._accept_loop, daemon=True)
        accept_th.start()
        self._threads.append(accept_th)
        deadline = _time.monotonic() + timeout
        for peer in range(self.n):
            if peer == self.me:
                continue
            while True:
                try:
                    import os as _os

                    s = socket.create_connection(
                        self.addresses[peer], timeout=2.0
                    )
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # mutual challenge-response: send a fresh nonce, check
                    # the server MACs it, then answer the server's nonce
                    my_nonce = _os.urandom(16)
                    s.sendall(
                        self._HELLO_MAGIC
                        + struct.pack("<H", self.me)
                        + my_nonce
                    )
                    s.settimeout(5.0)
                    resp = self._recv_exact(s, 32)
                    if resp is None or not _digest_eq(
                        resp[16:], self._mac(my_nonce, b"srv")
                    ):
                        s.close()
                        raise RuntimeError(
                            f"process {self.me}: peer {peer} failed the "
                            "exchange challenge (PATHWAY_EXCHANGE_TOKEN "
                            "mismatch?)"
                        )
                    s.sendall(self._mac(resp[:16], b"cli"))
                    # wait for the acceptor's 1-byte ack: a token mismatch
                    # fails fast at startup, not as a barrier timeout later
                    ack = self._recv_exact(s, 1)
                    s.settimeout(None)
                    if ack != b"\x01":
                        s.close()
                        # deliberately not an OSError: must escape the
                        # connect-retry loop below
                        raise RuntimeError(
                            f"process {self.me}: peer {peer} rejected the "
                            "exchange handshake (PATHWAY_EXCHANGE_TOKEN "
                            "mismatch?)"
                        )
                    self._send[peer] = s
                    break
                except OSError:
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"process {self.me}: peer {peer} did not come up"
                        )
                    _time.sleep(0.1)

    _HELLO_LEN = len(_HELLO_MAGIC) + 2 + 16

    def _mac(self, *parts: bytes) -> bytes:
        return hashlib.blake2b(
            b"".join(parts), key=self._token_key, digest_size=16
        ).digest()

    def _accept_loop(self) -> None:
        # handshakes run per-connection so a byte-dribbling stray cannot
        # stall acceptance of legitimate peers behind it
        while not self._closed:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return
            th = threading.Thread(
                target=self._handshake, args=(conn,), daemon=True
            )
            th.start()
            self._threads.append(th)

    def _handshake(self, conn: socket.socket) -> None:
        """Authenticate one inbound connection; a stray connection is
        closed without ever reaching frame decoding."""
        import os as _os

        def _read_exact(n: int, deadline: float) -> bytes | None:
            buf = b""
            while len(buf) < n:
                if _time.monotonic() > deadline:
                    return None
                chunk = conn.recv(n - len(buf))
                if not chunk:
                    return None
                buf += chunk
            return buf

        magic_len = len(self._HELLO_MAGIC)
        try:
            # overall deadline for the whole exchange, not per recv call
            conn.settimeout(5.0)
            deadline = _time.monotonic() + 5.0
            hello = _read_exact(self._HELLO_LEN, deadline)
            if hello is None or hello[:magic_len] != self._HELLO_MAGIC:
                raise OSError("bad hello")
            client_nonce = hello[magic_len + 2 :]
            # challenge-response: prove we know the token by MACing the
            # client's nonce, then demand a MAC over a nonce of ours — a
            # captured handshake gives an observer nothing replayable
            server_nonce = _os.urandom(16)
            conn.sendall(server_nonce + self._mac(client_nonce, b"srv"))
            answer = _read_exact(16, deadline)
            if answer is None or not _digest_eq(
                answer, self._mac(server_nonce, b"cli")
            ):
                raise OSError("bad challenge answer")
            conn.settimeout(None)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        (peer_id,) = struct.unpack_from("<H", hello, magic_len)
        try:
            conn.sendall(b"\x01")  # handshake ack — peer fails fast if absent
        except OSError:
            return
        self._recv_loop(conn, peer_id)

    def _recv_loop(self, conn: socket.socket, peer_id: int) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, _HDR.size)
                if hdr is None:
                    break
                (length,) = _HDR.unpack(hdr)
                body = self._recv_exact(conn, length)
                if body is None:
                    break
                channel, time, sender, entries = decode_frame(body)
                with self._cv:
                    # a queue per key: identical schedules may exchange the
                    # same (channel, time) more than once back-to-back, and
                    # both batches must survive until popped (depth is
                    # bounded by the sender's lookahead window W — see the
                    # class docstring's flow-control note)
                    self._inbox.setdefault((channel, time, sender), []).append(
                        entries
                    )
                    self._cv.notify_all()
        except Exception as exc:
            # decode errors (version mismatch, pickle gate, corrupt frame)
            # count as a dead peer too — never die silently leaving
            # barriers to hang; keep the reason so the barrier's error
            # points at the actual misconfiguration
            with self._cv:
                self._peer_errors[peer_id] = f"{type(exc).__name__}: {exc}"
        finally:
            # EOF / socket error / decode error: the peer is gone — wake
            # any barrier blocked on it so failures abort promptly
            with self._cv:
                self._down.add(peer_id)
                self._cv.notify_all()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- the exchange protocol: decoupled send / receive --
    def send(
        self,
        channel: str,
        time: int,
        outgoing: dict[int, list],
        is_entries: bool = True,
    ) -> None:
        """Ship per-destination batches for (channel, time) WITHOUT
        waiting for anything: the asynchronous-progress half that lets a
        fast worker run ahead of a straggler.  Bounded by the caller's
        lookahead window (io/streaming.py), so peer inboxes hold at most
        W unpopped batches per (channel, sender)."""
        for peer in range(self.n):
            if peer == self.me:
                continue
            payload = encode_frame(
                channel, time, self.me, outgoing.get(peer, []),
                is_entries=is_entries,
            )
            # per-peer send locks: the ingest thread (ctl + first-hop
            # batches) and the engine thread (eager prepares) send
            # concurrently; a lock shared across peer sockets would let
            # one stalled peer's TCP window block sends to every other
            # peer, so each socket locks independently
            with self._send_locks[peer]:
                self._send[peer].sendall(_HDR.pack(len(payload)) + payload)

    def exchange(
        self,
        channel: str,
        time: int,
        outgoing: dict[int, list],
        is_entries: bool = True,
    ) -> list:
        """``send`` + ``recv``: ship batches, then block until every
        peer's batch for (channel, time) arrived and return the merged
        remote payloads.  ``is_entries=False`` marks control payloads
        (arbitrary values rather than (key, row, diff) entries)."""
        self.send(channel, time, outgoing, is_entries=is_entries)
        return self.recv(channel, time)

    def poll(self, channel: str, time: int) -> bool:
        """Non-blocking: True when :meth:`recv` for (channel, time) would
        not block — every live peer's batch arrived (a down peer or a
        closed plane also returns True so the flush proceeds into recv
        and raises its descriptive error there)."""
        with self._cv:
            if self._closed:
                return True
            for peer in range(self.n):
                if peer == self.me:
                    continue
                if peer in self._down:
                    return True
                if not self._inbox.get((channel, time, peer)):
                    return False
        return True

    def wait_any(self, timeout: float) -> None:
        """Block until any inbox activity (or timeout) — the wavefront
        scheduler's parking primitive when every round is blocked."""
        with self._cv:
            self._cv.wait(timeout=timeout)

    def recv(self, channel: str, time: int) -> list:
        """Collect every peer's batch for (channel, time); blocks until
        each has arrived (they arrive in time order per sender)."""
        merged: list = []
        deadline = _time.monotonic() + self.barrier_timeout
        with self._cv:
            for peer in range(self.n):
                if peer == self.me:
                    continue
                key = (channel, time, peer)
                while not self._inbox.get(key):
                    if self._closed:
                        raise RuntimeError(
                            f"exchange {channel}@{time}: plane closed while "
                            f"waiting for peer {peer}"
                        )
                    if peer in self._down:
                        why = self._peer_errors.get(peer)
                        raise ConnectionError(
                            f"exchange {channel}@{time}: peer {peer} "
                            "disconnected"
                            + (f" ({why})" if why else " (crashed or shut down)")
                        )
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0 or not self._cv.wait(timeout=remaining):
                        raise TimeoutError(
                            f"exchange {channel}@{time}: no data from peer "
                            f"{peer} within {self.barrier_timeout}s"
                        )
                queue = self._inbox[key]
                merged.extend(queue.pop(0))
                if not queue:
                    del self._inbox[key]
        return merged

    def close(self) -> None:
        self._closed = True
        with self._cv:
            self._cv.notify_all()
        for s in self._send.values():
            try:
                s.close()
            except OSError:
                pass
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass


class ExchangeNode(Node):
    """Re-partitions its input by ``key_fn`` across the plane; spliced in
    front of stateful operators (timely's Exchange pact)."""

    def __init__(
        self,
        plane: ExchangePlane,
        channel: str,
        key_fn: Callable[[Any, tuple], Any] | None,
        broadcast: bool = False,
        name: str = "exchange",
    ):
        super().__init__(n_inputs=1, name=name)
        self.plane = plane
        self.channel = channel
        self.key_fn = key_fn  # None = partition by row key
        self.broadcast = broadcast
        #: rounds already exchanged — a SET, not a scalar: wavefront
        #: rounds overlap, so round t+1 flushing must not make round t
        #: look pending again (that double-fired exchanges per round)
        self._exchanged: set[int] = set()
        #: rounds whose partition+send already ran (driver lookahead);
        #: flush() then only has to receive
        self._prepared: dict[int, list[Entry]] = {}

    # participates in every timestamp: peers may send even when this
    # process has nothing local
    late = True
    #: engine.step_iter suspension marker (duck-typed: engine cannot
    #: import this module)
    is_exchange = True

    def has_pending(self, time: int) -> bool:
        # exactly one exchange per timestamp, *independent of local data* —
        # peers run identical schedules, so a data-dependent flush count
        # would deadlock the barrier.  Node-list position is topological,
        # so all local inputs have settled by the time this node fires.
        return time not in self._exchanged

    def prepare(self, time: int) -> None:
        """Stage 1 of a round: partition the settled local input and SEND
        it — without waiting for peers.  The driver calls this up to W
        rounds ahead of the oldest unfinished round (asynchronous
        progress); ``flush`` later only has to receive."""
        if time in self._prepared:
            return
        local = self.take(0)
        outgoing: dict[int, list] = {}
        mine: list[Entry] = []
        if self.broadcast:
            for peer in range(self.plane.n):
                if peer != self.plane.me:
                    outgoing[peer] = local
            mine = list(local)
        else:
            for key, row, diff in local:
                part_key = self.key_fn(key, row) if self.key_fn else key
                dest = owner_of(part_key, self.plane.n)
                if dest == self.plane.me:
                    mine.append((key, row, diff))
                else:
                    outgoing.setdefault(dest, []).append((key, row, diff))
        self.plane.send(self.channel, time, outgoing)
        self._prepared[time] = mine

    def flush(self, time: int) -> list[Entry]:
        # stage 2: wait for every peer's batch for this round.  When the
        # driver did not run stage 1 ahead (lockstep paths), prepare()
        # here degenerates to the old send+recv flush.  Note: pending may
        # legitimately hold YOUNGER rounds' rows here — the wavefront
        # scheduler lets round t+1's guarded segments deliver after this
        # round's prepare() drained its input (io/streaming.py).
        self.prepare(time)
        mine = self._prepared.pop(time)
        remote = self.plane.recv(self.channel, time)
        self._exchanged.add(time)
        if len(self._exchanged) > 64:
            # rounds are monotone; anything far below the newest can no
            # longer be asked about (bounded by the lookahead window)
            floor = max(self._exchanged) - 32
            self._exchanged = {t for t in self._exchanged if t >= floor}
        return consolidate(mine + list(remote))


def wavefront_requirements(engine, safe_ids: set):
    """Static schedule metadata for the cross-round wavefront
    (VERDICT r3 #4 — lift chained-exchange lockstep).

    ``engine.step_iter(t)`` yields once per ExchangeNode, in a firing
    order that is identical every round (exchanges fire exactly once per
    round, picked in node-list order).  Between two yields a round's work
    runs atomically.  Round ``t+1`` may therefore overlap round ``t`` as
    long as, before ``t+1`` executes a code stretch that DELIVERS into
    some node's (timeless) pending buffer, round ``t`` is guaranteed to
    never read that buffer again — otherwise ``t``'s flush would swallow
    ``t+1``'s rows into the wrong timestamp.

    Returns ``(ex_list, req_start, reqs, ups)``.  ``req_start`` and the
    per-exchange ``reqs[k]`` are ``(req_prepared, req_passed)`` pairs;
    ``ups[k]`` is exchange ``k``'s *settlement threshold*: once a round
    has PASSED that many exchanges, ``k``'s input can no longer grow, so
    the driver may ``prepare()`` (snapshot + send) its batch for the
    round eagerly, before the round's own yield reaches it.  Round
    ``t+1``:

    * may start its generator (segment 0: flush the non-ingest-safe
      pre-exchange subgraph) once round ``t`` satisfies ``req_start``;
    * may resume past its ``k``-th yield (flush exchange ``k`` and run
      the following segment) once round ``t`` satisfies ``reqs[k]`` —
      whose passed component is always ``>= k+1``, so rounds also flush
      each exchange in timestamp order.

    A round satisfies ``(p, q)`` when it has PREPARED ``>= p`` exchanges
    (prepare runs at yield arrival, so prepared = passed + 1 while
    suspended) and PASSED (resumed beyond) ``>= q``.

    The requirement for delivering into a node ``n``:

    * exchange: prepared component ``idx(n)+1`` — ``t``'s ``prepare(t)``
      at the yield drained the buffer, even if its flush still blocks on
      peers (this distinction is what lets round ``t+1`` run the groupby
      segment and SEND its join-exchange batches while ``t`` still waits
      for the join exchange's remote data);
    * regular node: passed component = highest-index exchange in ``n``'s
      upstream closure + 1 — after that atomic segment, ``t`` has
      delivered and flushed everything it ever will through ``n``;
    * late non-exchange node (e.g. as-of-now index): passed component =
      first exchange AFTER ``n`` in node-list order + 1 (the late pass
      is list-ordered, so by then ``n``'s round-``t`` flush ran); with
      no later exchange, ``inf`` — round ``t`` must fully finish
      (lockstep for that tail, the round-3 behavior).
    """
    nodes = engine.nodes
    pos = {n.id: i for i, n in enumerate(nodes)}
    ex_list = [n for n in nodes if isinstance(n, ExchangeNode)]
    ex_idx = {n.id: k for k, n in enumerate(ex_list)}
    inf = float("inf")

    producers: dict[int, list] = {}
    for n in nodes:
        for c, _p in n.downstream:
            producers.setdefault(c.id, []).append(n)

    up_memo: dict[int, float] = {}

    def up_req(n) -> float:
        """1 + max exchange index in n's upstream closure (0 if none)."""
        if n.id in up_memo:
            return up_memo[n.id]
        up_memo[n.id] = 0  # cycle guard (pw.iterate)
        best: float = 0
        for p in producers.get(n.id, ()):
            if isinstance(p, ExchangeNode):
                best = max(best, ex_idx[p.id] + 1)
            else:
                r = up_req(p)
                if p.late:
                    # a late producer flushes in the list-ordered late
                    # pass, not when its inputs settle — anything fed by
                    # it (including an exchange's eager-prepare `ups`
                    # threshold) must wait for the exchange AFTER it
                    r = max(r, late_guard(p))
                best = max(best, r)
        up_memo[n.id] = best
        return best

    ex_pos = sorted((pos[e.id], ex_idx[e.id]) for e in ex_list)

    def late_guard(n) -> float:
        p = pos[n.id]
        for q, k in ex_pos:
            if q > p:
                return k + 1
        return inf

    def delivered_req(starts, skip_safe: bool = False) -> tuple:
        req_prepared: float = 0
        req_passed: float = 0
        seen: set[int] = set()
        stack = list(starts)
        while stack:
            n = stack.pop()
            if n.id in seen:
                continue
            seen.add(n.id)
            if skip_safe and n.id in safe_ids:
                # flushed in stage 1 (step_ingest), which prepares its
                # first-hop exchanges immediately and in round order
                continue
            if isinstance(n, ExchangeNode):
                req_prepared = max(req_prepared, ex_idx[n.id] + 1)
                continue  # deliveries stop at the (prepared) buffer
            r = up_req(n)
            if n.late:
                r = max(r, late_guard(n))
            req_passed = max(req_passed, r)
            stack.extend(c for c, _p in n.downstream)
        return req_prepared, req_passed

    req_start = delivered_req(
        [c for s in engine.sources for c, _p in s.downstream], skip_safe=True
    )
    reqs = [
        delivered_req([c for c, _p in e.downstream]) for e in ex_list
    ]
    # settlement threshold per exchange: once a round has PASSED this many
    # exchanges, E's input can no longer grow — the driver may prepare()
    # (snapshot + send) E's batch for the round EAGERLY, long before the
    # round's own yield reaches it.  This is what ships a downstream
    # exchange's round-t batches while the round still blocks upstream.
    ups = []
    for e in ex_list:
        best: float = 0
        for p in producers.get(e.id, ()):
            if isinstance(p, ExchangeNode):
                best = max(best, ex_idx[p.id] + 1)
            else:
                r = up_req(p)
                if p.late:
                    # a DIRECT late producer delivers during the late
                    # pass; E's input settles only after the exchange
                    # following it in node order (same guard up_req
                    # applies to transitive late producers)
                    r = max(r, late_guard(p))
                best = max(best, r)
        ups.append(best)
    return ex_list, req_start, reqs, ups


def ingest_safe_nodes(engine) -> tuple[set[int], list["ExchangeNode"]]:
    """Nodes the driver may flush AHEAD of the oldest unfinished round.

    A node is ingest-safe when (a) it sits strictly BEFORE every
    exchange — nothing in its transitive upstream is an ExchangeNode, so
    running it early never consumes another round's remote data — and
    (b) every output path terminates in an ExchangeNode input, so its
    early output only feeds exchange ``prepare`` buffers, never sinks or
    stateful state that must observe rounds in order.

    A first-hop exchange is one whose ENTIRE transitive upstream closure
    is ingest-safe: by prepare time its input for the round has fully
    settled.  (A merely one-hop check would let a partially-flushed
    chain lose the late-settling entries.)"""
    from .engine import OutputNode

    producers: dict[int, list] = {}
    for n in engine.nodes:
        for c, _port in n.downstream:
            producers.setdefault(c.id, []).append(n)

    # nodes with an exchange anywhere upstream (post-exchange set)
    post: dict[int, bool] = {}

    def post_exchange(node) -> bool:
        if node.id in post:
            return post[node.id]
        post[node.id] = False  # cycle guard (pw.iterate loops)
        res = any(
            isinstance(p, ExchangeNode) or post_exchange(p)
            for p in producers.get(node.id, ())
        )
        post[node.id] = res
        return res

    memo: dict[int, bool] = {}

    def sinks_into_exchanges(node) -> bool:
        if node.id in memo:
            return memo[node.id]
        if not node.downstream:
            memo[node.id] = False
            return False
        memo[node.id] = False  # cycle guard
        res = all(
            isinstance(c, ExchangeNode) or sinks_into_exchanges(c)
            for c, _ in node.downstream
        )
        memo[node.id] = res
        return res

    safe_ids = {
        n.id
        for n in engine.nodes
        if not isinstance(n, (ExchangeNode, OutputNode))
        and not post_exchange(n)
        and sinks_into_exchanges(n)
    }

    def closure_safe(node) -> bool:
        stack = list(producers.get(node.id, ()))
        seen: set[int] = set()
        while stack:
            p = stack.pop()
            if p.id in seen:
                continue
            seen.add(p.id)
            if p.id not in safe_ids:
                return False
            stack.extend(producers.get(p.id, ()))
        return True

    first_hop = [
        n
        for n in engine.nodes
        if isinstance(n, ExchangeNode) and closure_safe(n)
    ]
    return safe_ids, first_hop


def insert_exchanges(engine, plane: ExchangePlane) -> None:
    """Splice ExchangeNodes before every stateful node's keyed inputs —
    the post-pass equivalent of timely's per-operator Exchange pacts."""
    from .engine import (
        ConcatNode,
        DeduplicateNode,
        GroupByNode,
        JoinNode,
        SemiJoinNode,
        UpdateCellsNode,
        UpdateRowsNode,
        ZipNode,
    )

    def key_fns_for(node) -> dict[int, Callable | None] | None:
        if isinstance(node, GroupByNode):
            return {0: lambda key, row: node.group_fn(key, row)}
        if isinstance(node, JoinNode):
            return {
                0: lambda key, row: node.left_key_fn(key, row),
                1: lambda key, row: node.right_key_fn(key, row),
            }
        if isinstance(node, SemiJoinNode):
            return {
                0: lambda key, row: node.mask_key_fn(key, row),
                1: lambda key, row: node.right_key_fn(key, row),
            }
        if isinstance(node, DeduplicateNode):
            return {0: lambda key, row: node.instance_fn(key, row)}
        if isinstance(node, (ZipNode, UpdateRowsNode, UpdateCellsNode, ConcatNode)):
            return {port: None for port in range(node.n_inputs)}
        return None

    # index serving: docs broadcast to every process (each keeps a full
    # replica, reference external_index.rs:95-98); queries stay local
    from ..stdlib.indexing.lowering import ExternalIndexNode

    counter = 0
    for node in list(engine.nodes):
        broadcast_ports: set[int] = set()
        if isinstance(node, ExternalIndexNode):
            key_map: dict[int, Callable | None] | None = {0: None}
            broadcast_ports = {0}
        else:
            key_map = key_fns_for(node)
        if key_map is None:
            continue
        exchange_of_port: dict[int, ExchangeNode] = {}
        for port, key_fn in key_map.items():
            counter += 1
            ex = ExchangeNode(
                plane,
                channel=f"ch{counter}",
                key_fn=key_fn,
                broadcast=port in broadcast_ports,
                name=f"exchange#{counter}->{node.name}.{port}",
            )
            engine.add(ex)
            # late nodes run in list order: the exchange must fire before
            # its consumer (e.g. the index node's updates-before-queries
            # barrier depends on the docs broadcast landing first)
            engine.nodes.remove(ex)
            engine.nodes.insert(engine.nodes.index(node), ex)
            ex.downstream.append((node, port))
            exchange_of_port[port] = ex
        # rewire producers that fed the node directly
        for producer in engine.nodes:
            if producer in exchange_of_port.values():
                continue
            new_edges = []
            for consumer, port in producer.downstream:
                if consumer is node and port in exchange_of_port:
                    new_edges.append((exchange_of_port[port], 0))
                else:
                    new_edges.append((consumer, port))
            producer.downstream = new_edges
