"""Expression compiler: ColumnExpression tree -> Python closure.

reference: python/pathway/internals/graph_runner/expression_evaluator.py:211
(RowwiseEvaluator lowering the AST to engine expressions) + the row-wise
interpreter src/engine/expression.rs.  Here the lowering target is a Python
closure ``fn(ctx) -> value``; the caller supplies a resolver mapping
ColumnReference nodes to accessors over its row context.

Error semantics follow the reference (src/engine/error.rs): if any operand is
``ERROR`` the result is ``ERROR``; exceptions raise unless the run was started
with ``terminate_on_error=False`` in which case they produce ``ERROR`` rows.
"""

from __future__ import annotations

from typing import Any, Callable

from . import expression as expr_mod
from .value import ERROR, Json, Pointer
from .keys import ref_scalar
from . import dtype as dt
from ..testing import faults

__all__ = ["compile_expression", "compile_vector_expression", "EvalContext"]


class EvalContext:
    """Runtime switches shared across compiled closures."""

    terminate_on_error: bool = True

    @classmethod
    def handle(cls, exc: Exception, kind: str = "eval", operator: str = ""):
        if cls.terminate_on_error:
            raise exc
        from .errors import register_error

        retries = getattr(exc, "retries_exhausted", None)
        suffix = "" if retries is None else f" (after {retries} retries)"
        register_error(
            f"{type(exc).__name__}: {exc}{suffix}", kind=kind, operator=operator
        )
        return ERROR


def compile_expression(
    e: expr_mod.ColumnExpression,
    resolve_ref: Callable[[expr_mod.ColumnReference], Callable[[Any], Any]],
) -> Callable[[Any], Any]:
    """Compile ``e`` into ``fn(ctx) -> value``."""

    def rec(node: expr_mod.ColumnExpression) -> Callable[[Any], Any]:
        return compile_expression(node, resolve_ref)

    if isinstance(e, expr_mod.ColumnConstExpression):
        v = e._value
        return lambda ctx: v

    if isinstance(e, expr_mod.ColumnReference):
        return resolve_ref(e)

    if isinstance(e, expr_mod.ColumnBinaryOpExpression):
        lf, rf = rec(e.left), rec(e.right)
        impl = expr_mod.binary_op_impl(e.op)
        # branch on the operator once at compile time, not per row
        if e.op == "==":

            def run_eq(ctx):
                a = lf(ctx)
                if a is ERROR:
                    return ERROR
                b = rf(ctx)
                return ERROR if b is ERROR else a == b

            return run_eq
        if e.op == "!=":

            def run_ne(ctx):
                a = lf(ctx)
                if a is ERROR:
                    return ERROR
                b = rf(ctx)
                return ERROR if b is ERROR else a != b

            return run_ne

        def run_binary(ctx):
            a = lf(ctx)
            if a is ERROR:
                return ERROR
            b = rf(ctx)
            if b is ERROR:
                return ERROR
            if a is None or b is None:
                return None
            try:
                return impl(a, b)
            except Exception as exc:
                return EvalContext.handle(exc)

        return run_binary

    if isinstance(e, expr_mod.ColumnUnaryOpExpression):
        f = rec(e.expr)
        op = e.op

        def run_unary(ctx):
            v = f(ctx)
            if v is ERROR:
                return ERROR
            if v is None:
                return None
            try:
                if op == "-":
                    return -v
                if op == "~":
                    return not v if isinstance(v, bool) else ~v
                if op == "abs":
                    return abs(v)
            except Exception as exc:
                return EvalContext.handle(exc)
            raise ValueError(f"unknown unary op {op}")

        return run_unary

    if isinstance(e, (expr_mod.ApplyExpression,)):
        # Async applies are handled at the operator level (AsyncMapNode);
        # when reached here they run synchronously via the event loop.
        arg_fns = [rec(a) for a in e.args]
        kwarg_fns = {k: rec(v) for k, v in e.kwargs.items()}
        fun = e.fun
        propagate_none = e.propagate_none
        is_async = isinstance(e, expr_mod.AsyncApplyExpression)

        def run_apply(ctx):
            args = [f(ctx) for f in arg_fns]
            kwargs = {k: f(ctx) for k, f in kwarg_fns.items()}
            if any(a is ERROR for a in args) or any(v is ERROR for v in kwargs.values()):
                return ERROR
            if propagate_none and (
                any(a is None for a in args) or any(v is None for v in kwargs.values())
            ):
                return None
            try:
                if faults.enabled:
                    faults.perturb("udf")
                if is_async:
                    import asyncio

                    return asyncio.run(fun(*args, **kwargs))
                return fun(*args, **kwargs)
            except Exception as exc:
                return EvalContext.handle(exc, kind="udf")

        return run_apply

    if isinstance(e, expr_mod.CastExpression):
        f = rec(e.expr)
        target = e.return_type

        def run_cast(ctx):
            v = f(ctx)
            if v is ERROR:
                return ERROR
            if v is None:
                return None
            try:
                return _cast(v, target)
            except Exception as exc:
                return EvalContext.handle(exc)

        return run_cast

    if isinstance(e, expr_mod.ConvertExpression):
        f = rec(e.expr)
        target = e.return_type
        unwrap = e.unwrap

        def run_convert(ctx):
            v = f(ctx)
            if v is ERROR:
                return ERROR
            if v is None:
                return None
            if isinstance(v, Json):
                res = {
                    dt.INT: v.as_int,
                    dt.FLOAT: v.as_float,
                    dt.STR: v.as_str,
                    dt.BOOL: v.as_bool,
                }[target]()
            else:
                res = _cast(v, target)
            if res is None and unwrap:
                return EvalContext.handle(ValueError(f"cannot convert {v!r}"))
            return res

        return run_convert

    if isinstance(e, expr_mod.DeclareTypeExpression):
        return rec(e.expr)

    if isinstance(e, expr_mod.CoalesceExpression):
        fns = [rec(a) for a in e.args]

        def run_coalesce(ctx):
            for f in fns:
                v = f(ctx)
                if v is not None:
                    return v
            return None

        return run_coalesce

    if isinstance(e, expr_mod.RequireExpression):
        vf = rec(e.val)
        fns = [rec(a) for a in e.args]

        def run_require(ctx):
            for f in fns:
                if f(ctx) is None:
                    return None
            return vf(ctx)

        return run_require

    if isinstance(e, expr_mod.IfElseExpression):
        cf, tf, ef = rec(e.if_), rec(e.then), rec(e.else_)

        def run_ifelse(ctx):
            c = cf(ctx)
            if c is ERROR:
                return ERROR
            return tf(ctx) if c else ef(ctx)

        return run_ifelse

    if isinstance(e, expr_mod.IsNotNoneExpression):
        f = rec(e.expr)
        return lambda ctx: f(ctx) is not None

    if isinstance(e, expr_mod.IsNoneExpression):
        f = rec(e.expr)
        return lambda ctx: f(ctx) is None

    if isinstance(e, expr_mod.MakeTupleExpression):
        fns = [rec(a) for a in e.args]
        return lambda ctx: tuple(f(ctx) for f in fns)

    if isinstance(e, expr_mod.GetExpression):
        of, idxf, df = rec(e.obj), rec(e.index), rec(e.default)
        checked = e.check_if_exists

        def run_get(ctx):
            obj = of(ctx)
            if obj is ERROR:
                return ERROR
            idx = idxf(ctx)
            try:
                if isinstance(obj, Json):
                    inner = obj.value
                    res = inner[idx]
                    return Json(res)
                return obj[idx]
            except (KeyError, IndexError, TypeError) as exc:
                if checked:
                    return df(ctx)
                return EvalContext.handle(exc)

        return run_get

    if isinstance(e, expr_mod.MethodCallExpression):
        fns = [rec(a) for a in e.args]
        fun = e.fun
        propagate_none = e.propagate_none

        def run_method(ctx):
            args = [f(ctx) for f in fns]
            if any(a is ERROR for a in args):
                return ERROR
            if propagate_none and args and args[0] is None:
                return None
            try:
                return fun(*args)
            except Exception as exc:
                return EvalContext.handle(exc)

        return run_method

    if isinstance(e, expr_mod.UnwrapExpression):
        f = rec(e.expr)

        def run_unwrap(ctx):
            v = f(ctx)
            if v is None:
                return EvalContext.handle(ValueError("unwrap() on None"))
            return v

        return run_unwrap

    if isinstance(e, expr_mod.FillErrorExpression):
        f, rf = rec(e.expr), rec(e.replacement)

        def run_fill(ctx):
            try:
                v = f(ctx)
            except Exception:
                return rf(ctx)
            if v is ERROR:
                return rf(ctx)
            return v

        return run_fill

    if isinstance(e, expr_mod.PointerExpression):
        fns = [rec(a) for a in e.args]
        inst_fn = rec(e.instance) if e.instance is not None else None
        optional = e.optional

        def run_pointer(ctx):
            vals = [f(ctx) for f in fns]
            if any(v is ERROR for v in vals):
                return ERROR
            if optional and any(v is None for v in vals):
                return None
            key = ref_scalar(*vals)
            if inst_fn is not None:
                inst_key = ref_scalar(inst_fn(ctx))
                key = key.with_shard(inst_key.value >> (128 - Pointer.SHARD_BITS))
            return key

        return run_pointer

    if isinstance(e, expr_mod.ReducerExpression):
        raise TypeError(
            "reducer expression used outside of reduce() context"
        )

    # unknown node kinds (internal slot references etc.) resolve like refs
    try:
        return resolve_ref(e)  # type: ignore[arg-type]
    except Exception:
        pass
    raise TypeError(f"cannot compile expression of type {type(e).__name__}")


def _cast(v: Any, target: dt.DType) -> Any:
    target = dt.unoptionalize(target)
    if target is dt.INT:
        return int(v)
    if target is dt.FLOAT:
        return float(v)
    if target is dt.BOOL:
        return bool(v)
    if target is dt.STR:
        if isinstance(v, bool):
            return "True" if v else "False"
        return str(v)
    if target is dt.BYTES:
        return v.encode() if isinstance(v, str) else bytes(v)
    if target is dt.JSON:
        return v if isinstance(v, Json) else Json(v)
    return v


# ---------------------------------------------------------------------------
# columnar (batch) compilation — the TPU-first engine direction: evaluate a
# whole micro-batch of rows as numpy column arrays instead of per-row
# closures.  reference parity note: the Rust engine evaluates per row over
# i64/f64 (src/engine/expression.rs); this path keeps those numeric
# semantics (int64 arithmetic) and falls back to the row path whenever a
# batch contains anything non-numeric (None/ERROR/strings → object dtype).
# ---------------------------------------------------------------------------

#: binary ops safe to vectorize: no zero-divide (numpy warns + returns
#: inf/nan where the row path raises/routes ERROR), no Python-only
#: semantics
_VECTOR_BIN_OPS: dict | None = None

#: runtime magnitude bound for int columns on the vector path: with
#: |inputs| < 2^31 the compile-time bit-growth analysis below guarantees
#: no intermediate exceeds int64, so numpy can never silently wrap where
#: the row path's Python bignums would keep going
VECTOR_INT_BOUND = 1 << 31


def _vector_bin_ops():
    global _VECTOR_BIN_OPS
    if _VECTOR_BIN_OPS is None:
        import operator

        _VECTOR_BIN_OPS = {
            "+": operator.add,
            "-": operator.sub,
            "*": operator.mul,
            "<": operator.lt,
            "<=": operator.le,
            ">": operator.gt,
            ">=": operator.ge,
            "==": operator.eq,
            "!=": operator.ne,
            "&": operator.and_,
            "|": operator.or_,
            "^": operator.xor,
        }
    return _VECTOR_BIN_OPS


#: worst-case result bit width assumed for an int column reference
#: (enforced at runtime by _materialize_cols against VECTOR_INT_BOUND)
_REF_BITS = 31
#: int64 headroom the analysis must stay within (sign bit reserved)
_MAX_BITS = 62


def compile_vector_expression(
    e: expr_mod.ColumnExpression,
    slot_of_ref,
) -> Callable | None:
    """Compile ``e`` into ``fn(cols) -> ndarray`` over numpy column arrays,
    or return None when the expression isn't vectorizable.

    ``slot_of_ref(ref) -> int | None`` maps a ColumnReference (or internal
    slot expression) to its input-column index.  Integer expressions carry
    a compile-time worst-case bit-width (inputs bounded by
    ``VECTOR_INT_BOUND`` at runtime); anything that could exceed int64
    stays on the row path, so wraparound can never diverge from the
    Python-int row semantics.
    """
    import operator

    numeric = (dt.INT, dt.FLOAT, dt.BOOL)

    def rec(node):
        """Returns (fn, kind, bits) or None; kind in {'int','float','bool'}."""
        if isinstance(node, expr_mod.ColumnConstExpression):
            v = node._value
            if type(v) is bool:
                return (lambda cols: v), "bool", 1
            if type(v) is int:
                return (lambda cols: v), "int", max(v.bit_length(), 1)
            if type(v) is float:
                return (lambda cols: v), "float", 0
            return None
        if isinstance(node, expr_mod.ColumnBinaryOpExpression):
            impl = _vector_bin_ops().get(node.op)
            if impl is None:
                # division-family ops are safe when the divisor is a
                # non-zero constant (no zero-divide can occur, so numpy
                # and the row path agree)
                if node.op in ("//", "%", "/") and isinstance(
                    node.right, expr_mod.ColumnConstExpression
                ):
                    d = node.right._value
                    if type(d) in (int, float) and d != 0:
                        left = rec(node.left)
                        if left is None:
                            return None
                        lf, lkind, lbits = left
                        impl2 = {
                            "//": operator.floordiv,
                            "%": operator.mod,
                            "/": operator.truediv,
                        }[node.op]
                        if node.op == "/" or lkind == "float":
                            kind, bits = "float", 0
                        elif node.op == "%":
                            kind = "int"
                            bits = (
                                abs(d).bit_length() if type(d) is int else lbits
                            )
                        else:
                            kind, bits = "int", lbits
                        if kind == "int" and bits > _MAX_BITS:
                            return None
                        return (lambda cols: impl2(lf(cols), d)), kind, bits
                return None
            left, right = rec(node.left), rec(node.right)
            if left is None or right is None:
                return None
            lf, lkind, lbits = left
            rf, rkind, rbits = right
            if node.op in ("<", "<=", ">", ">=", "==", "!="):
                kind, bits = "bool", 1
            elif node.op in ("&", "|", "^"):
                kind = "bool" if lkind == rkind == "bool" else "int"
                bits = max(lbits, rbits)
            elif "float" in (lkind, rkind):
                kind, bits = "float", 0
            elif node.op == "*":
                kind, bits = "int", lbits + rbits
            else:  # + -
                kind, bits = "int", max(lbits, rbits) + 1
            if kind == "int" and bits > _MAX_BITS:
                return None
            return (lambda cols: impl(lf(cols), rf(cols))), kind, bits
        if isinstance(node, expr_mod.ColumnUnaryOpExpression):
            inner = rec(node.expr)
            if inner is None:
                return None
            f, kind, bits = inner
            if node.op == "-":
                if kind == "bool":
                    # numpy forbids - on bool arrays; the row path returns
                    # -True == -1 — keep that on the row path
                    return None
                return (lambda cols: -f(cols)), kind, bits
            if node.op == "~" and kind in ("bool", "int"):
                return (lambda cols: ~f(cols)), kind, bits + 1
            return None
        # column references / internal slots: only non-optional numerics —
        # an Optional column may carry None, which the object-dtype guard
        # catches anyway, but excluding it here avoids wasted conversions
        slot = slot_of_ref(node)
        if slot is None:
            return None
        d = getattr(node, "_dtype", None)
        if d not in numeric:
            return None
        kind = {dt.INT: "int", dt.FLOAT: "float", dt.BOOL: "bool"}[d]
        bits = _REF_BITS if kind == "int" else (1 if kind == "bool" else 0)
        return (lambda cols: cols[slot]), kind, bits

    if getattr(e, "_dtype", None) not in numeric:
        return None
    compiled = rec(e)
    return None if compiled is None else compiled[0]


def _collect_slots(e, slot_of_ref) -> dict:
    """Slots referenced by ``e`` mapped to their declared dtype."""
    out: dict = {}

    def walk(node):
        slot = slot_of_ref(node)
        if slot is not None:
            out[slot] = getattr(node, "_dtype", None)
            return
        for d in getattr(node, "_deps", lambda: ())() or ():
            walk(d)

    walk(e)
    return out


def _materialize_cols(rows, slots, int_slots=()):
    """Column arrays for ``slots``; None if any column is non-numeric
    (object dtype: None/ERROR/strings present in the batch) or an int
    column exceeds the wraparound-safety bound the compile-time analysis
    assumed.  A declared-INT column whose batch happens to be all Python
    bools (bool subclasses int, so the row path accepts them) widens to
    int64 so arithmetic stays numeric — numpy bool ops are logical
    (True+True == True) and unary ``-`` raises."""
    import numpy as np

    cols = {}
    for s in slots:
        vals = [r[s] for r in rows]
        arr = np.asarray(vals)
        if arr.dtype == object:
            return None
        if arr.dtype.kind == "b" and s in int_slots:
            arr = arr.astype(np.int64)
        if arr.dtype.kind in "iu" and (
            arr.max(initial=0) >= VECTOR_INT_BOUND
            or arr.min(initial=0) <= -VECTOR_INT_BOUND
        ):
            # kind 'u': a batch of all-huge positive ints coerces to
            # uint64 and would otherwise bypass the wraparound bound
            return None
        if arr.dtype.kind == "f" and not _float_col_exact(arr, vals):
            # float64 coerced from huge Python ints (declared-INT column
            # mixing magnitudes, or optional numerics): values beyond
            # 2**53 already lost precision vs the exact bigint row path
            return None
        cols[s] = arr
    return cols


#: largest magnitude exactly representable in float64 — int-sourced
#: values beyond this lose precision when numpy coerces a mixed batch
FLOAT_EXACT_BOUND = 1 << 53


def _float_col_exact(arr, vals) -> bool:
    """True iff coercing ``vals`` to the float64 array ``arr`` was
    value-preserving.  Vectorized precheck: if every magnitude is below
    2**53 the coercion of any int source was exact; only when huge (or
    NaN) values are present do we scan source types."""
    import numpy as np

    if bool((np.abs(arr) < FLOAT_EXACT_BOUND).all()):
        return True
    return all(isinstance(v, float) for v in vals)


def build_vector_select(exprs, slot_of_ref):
    """``fn(rows) -> list[tuple] | None`` evaluating a whole select batch
    over numpy columns; returns None at build time unless every output
    column is a pass-through reference or a vectorizable expression (and
    at least one actually computes)."""
    fns = []
    pass_slots = {}
    for i, e in enumerate(exprs):
        slot = slot_of_ref(e)
        if slot is not None:
            pass_slots[i] = slot
            fns.append(None)
            continue
        f = compile_vector_expression(e, slot_of_ref)
        if f is None:
            return None
        fns.append(f)
    if all(f is None for f in fns):
        return None  # pure projection — build_projection_entries covers it

    slot_dtypes: dict = {}
    for f, e in zip(fns, exprs):
        if f is not None:
            slot_dtypes.update(_collect_slots(e, slot_of_ref))
    compute_slots = sorted(slot_dtypes)
    int_slots = frozenset(
        s for s, d in slot_dtypes.items() if d is dt.INT
    )

    def run(rows):
        cols = _materialize_cols(rows, compute_slots, int_slots)
        if cols is None:
            return None
        n = len(rows)
        out_cols = []
        for i, f in enumerate(fns):
            if f is None:
                s = pass_slots[i]
                out_cols.append([r[s] for r in rows])
            else:
                res = f(cols)
                # const-only expressions yield Python scalars — broadcast
                out_cols.append(
                    res.tolist() if hasattr(res, "tolist") else [res] * n
                )
        # C-level transpose into row tuples
        return list(zip(*out_cols))

    return run


def build_projection_entries(exprs, slot_of_ref):
    """Entry-level fast path for pure-projection selects:
    ``fn(entries) -> list[Entry]`` rebuilding ``(key, out_row, diff)`` in a
    single comprehension — no numpy, no intermediate row lists.  Returns
    None unless every output column is a plain slot reference."""
    import operator as _op

    if not exprs:
        return None  # id-only select — row path emits empty tuples
    slots = []
    for e in exprs:
        s = slot_of_ref(e)
        if s is None:
            return None
        slots.append(s)
    # three column sweeps + one C-level zip beat a single row-tuple
    # comprehension by ~20% at big batch sizes
    if len(slots) == 1:
        s0 = slots[0]

        def run_single(entries):
            return list(
                zip(
                    [e[0] for e in entries],
                    [(e[1][s0],) for e in entries],
                    [e[2] for e in entries],
                )
            )

        return run_single
    getter = _op.itemgetter(*slots)

    def run_multi(entries):
        return list(
            zip(
                [e[0] for e in entries],
                [getter(e[1]) for e in entries],
                [e[2] for e in entries],
            )
        )

    return run_multi


def build_vector_filter(cond, slot_of_ref):
    """``fn(rows) -> list[bool] | None`` evaluating a filter predicate
    over numpy columns; None at build time if not vectorizable."""
    f = compile_vector_expression(cond, slot_of_ref)
    if f is None:
        return None
    slot_dtypes = _collect_slots(cond, slot_of_ref)
    slots = sorted(slot_dtypes)
    if not slots:
        return None
    int_slots = frozenset(s for s, d in slot_dtypes.items() if d is dt.INT)

    def run(rows):
        cols = _materialize_cols(rows, slots, int_slots)
        if cols is None:
            return None
        return f(cols).tolist()

    return run
