"""Declarative YAML app templates with ``!pw`` tags.

reference: python/pathway/internals/yaml_loader.py:74
(``PathwayYamlLoader``) — app templates like the reference's
``integration_tests/rag_evals/app.yaml`` instantiate framework classes
straight from YAML::

    $llm: !pw.xpacks.llm.mocks.IdentityMockChat {}
    store: !pw.xpacks.llm.vector_store.VectorStoreServer
      docs: ...
      embedder: !pw.xpacks.llm.mocks.FakeEmbedder
        dim: 8

Tags: ``!pw.<dotted.path>`` resolves inside the ``pathway_tpu`` package
(``!pw.io.fs.read`` etc.); a mapping node calls the object with kwargs, a
sequence node with positional args, a scalar node with the single value
(empty scalar = attribute access only).  ``$name`` keys define reusable
anchored values referenced as ``$name`` (reference's variable convention).
"""

from __future__ import annotations

import importlib
from typing import Any, IO

import yaml

__all__ = ["PathwayYamlLoader", "load_yaml"]


def _resolve(dotted: str) -> Any:
    """Resolve ``io.fs.read``-style paths inside pathway_tpu, importing
    submodules as needed."""
    import pathway_tpu as pw

    obj: Any = pw
    parts = dotted.split(".")
    for i, part in enumerate(parts):
        try:
            obj = getattr(obj, part)
        except AttributeError:
            module_path = "pathway_tpu." + ".".join(parts[: i + 1])
            try:
                obj = importlib.import_module(module_path)
            except ImportError as exc:
                raise ValueError(
                    f"cannot resolve !pw.{dotted}: no attribute or module "
                    f"{part!r}"
                ) from exc
    return obj


class _DeferredCall:
    """A ``!pw`` node parsed but not yet instantiated — construction happens
    after ``$var`` substitution so variables can reference earlier objects."""

    def __init__(self, dotted: str, args: list, kwargs: dict):
        self.dotted = dotted
        self.args = args
        self.kwargs = kwargs

    def materialize(self, variables: dict[str, Any]) -> Any:
        target = _resolve(self.dotted)
        args = [_materialize(a, variables) for a in self.args]
        kwargs = {k: _materialize(v, variables) for k, v in self.kwargs.items()}
        # mapping nodes can pass positionals through the __args__ key
        # (star-arg constructors like VectorStoreServer(*docs))
        extra = kwargs.pop("__args__", None)
        if extra is not None:
            args = [*args, *(extra if isinstance(extra, list) else [extra])]
        if not args and not kwargs and not callable(target):
            return target
        if args and len(args) == 1 and args[0] in (None, "") and not kwargs:
            return target()
        return target(*args, **kwargs)


class PathwayYamlLoader(yaml.SafeLoader):
    """reference: yaml_loader.py:74"""


def _pw_multi_constructor(loader: PathwayYamlLoader, tag_suffix: str, node):
    dotted = tag_suffix.lstrip(".")
    if isinstance(node, yaml.MappingNode):
        return _DeferredCall(dotted, [], loader.construct_mapping(node, deep=True))
    if isinstance(node, yaml.SequenceNode):
        return _DeferredCall(dotted, loader.construct_sequence(node, deep=True), {})
    value = loader.construct_scalar(node)
    if value in (None, ""):
        return _DeferredCall(dotted, [None], {})
    return _DeferredCall(dotted, [value], {})


PathwayYamlLoader.add_multi_constructor("!pw", _pw_multi_constructor)


def _materialize(obj: Any, variables: dict[str, Any]) -> Any:
    if isinstance(obj, _DeferredCall):
        return obj.materialize(variables)
    if isinstance(obj, str) and obj.startswith("$") and obj[1:] in variables:
        return variables[obj[1:]]
    if isinstance(obj, dict):
        return {k: _materialize(v, variables) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_materialize(v, variables) for v in obj]
    return obj


def load_yaml(stream: str | IO) -> Any:
    """Parse a template; ``$name:`` entries become variables usable as
    ``$name`` in later entries (reference: yaml_loader variables).
    Instantiation order follows document order, so a variable can hold a
    table/component consumed by later components."""
    data = yaml.load(stream, Loader=PathwayYamlLoader)
    if not isinstance(data, dict):
        return _materialize(data, {})
    variables: dict[str, Any] = {}
    out: dict[str, Any] = {}
    for key, value in data.items():
        value = _materialize(value, variables)
        if isinstance(key, str) and key.startswith("$"):
            variables[key[1:]] = value
        else:
            out[key] = value
    return out
