"""Joins: ``t1.join(t2, t1.a == t2.b).select(...)``.

reference: python/pathway/internals/joins.py (1422 LoC), join_mode.py,
JoinContext (internals/column.py:931); engine side differential
``join_core`` via src/engine/dataflow.rs join operators.
"""

from __future__ import annotations

import enum
from typing import Any, TYPE_CHECKING

from .expression import (
    ColumnBinaryOpExpression,
    ColumnExpression,
    ColumnReference,
    IdExpression,
    smart_wrap,
)
from .desugaring import expand_select_args, resolve_expression
from .graph import Operator
from .schema import ColumnSchema, _schema_from_columns
from . import dtype as dt
from .universe import Universe

if TYPE_CHECKING:
    from .table import Table

__all__ = ["JoinMode", "JoinResult"]


class JoinMode(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class JoinResult:
    """Deferred join; finalized by ``.select``/``.reduce``
    (reference: joins.py JoinResult)."""

    def __init__(
        self,
        left: "Table",
        right: "Table",
        on: tuple,
        mode: JoinMode,
        id_expr: ColumnExpression | None = None,
        exact_match: bool = False,
    ):
        self._left = left
        self._right = right
        self._mode = mode
        self._id_expr = id_expr
        self._exact_match = exact_match
        self._on: list[tuple[ColumnExpression, ColumnExpression]] = []
        for cond in on:
            self._on.append(self._split_condition(cond))

    def _split_condition(self, cond) -> tuple[ColumnExpression, ColumnExpression]:
        if not isinstance(cond, ColumnBinaryOpExpression) or cond.op != "==":
            raise ValueError(
                "join conditions must be of the form <left expr> == <right expr>"
            )
        lexpr = resolve_expression(cond.left, self._left, self._left, self._right)
        rexpr = resolve_expression(cond.right, self._left, self._left, self._right)
        lside = self._side_of(lexpr)
        rside = self._side_of(rexpr)
        if lside == "right" and rside == "left":
            lexpr, rexpr = rexpr, lexpr
        elif not (lside in ("left", "const") and rside in ("right", "const")):
            if lside == "left" and rside == "left":
                raise ValueError("both sides of a join condition refer to the left table")
            if lside == "right" and rside == "right":
                raise ValueError("both sides of a join condition refer to the right table")
        return lexpr, rexpr

    def _side_of(self, e: ColumnExpression) -> str:
        tables = set()

        def walk(node):
            if isinstance(node, ColumnReference) and node.table is not None:
                tables.add(id(node.table))
            for d in node._deps():
                walk(d)

        walk(e)
        if not tables:
            return "const"
        left_ids = {id(self._left)}
        right_ids = {id(self._right)}
        if tables <= left_ids:
            return "left"
        if tables <= right_ids:
            return "right"
        # fall back on universe identity
        return "mixed"

    def __getitem__(self, name: str) -> ColumnReference:
        """Column lookup over both sides, left side winning on name
        conflicts (the same substitution priority ``_flat`` applies)."""
        if name == "id" or name in self._left.column_names():
            return self._left[name]
        if name in self._right.column_names():
            return self._right[name]
        raise KeyError(
            f"join has no column {name!r}; columns: "
            f"{sorted(set(self._left.column_names()) | set(self._right.column_names()))}"
        )

    @property
    def C(self) -> "_JoinColumnNamespace":
        """Column accessor on the pending join (reference: Joinable.C,
        joins.py:106) — ``t.join(u, ...).C.col`` resolves like the
        sentinels do: the left side wins on name conflicts."""
        return _JoinColumnNamespace(self)

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        from .table import Table

        exprs = expand_select_args(
            args, kwargs, self._left, self._left, self._right
        )
        columns: dict[str, ColumnSchema] = {}
        for name, e in exprs.items():
            dtype = e._dtype
            if self._mode in (JoinMode.LEFT, JoinMode.OUTER) and _refers_to(
                e, self._right
            ):
                dtype = dt.Optional(dtype)
            if self._mode in (JoinMode.RIGHT, JoinMode.OUTER) and _refers_to(
                e, self._left
            ):
                dtype = dt.Optional(dtype)
            columns[name] = ColumnSchema(name=name, dtype=dtype)
        schema = _schema_from_columns(columns)

        universe = Universe()
        op = Operator(
            "join",
            [self._left, self._right],
            params=dict(
                on=self._on,
                mode=self._mode,
                out_exprs=exprs,
                id_expr=self._id_expr,
                exact_match=self._exact_match,
            ),
        )
        return Table._new(op, schema, universe)

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        return self._flat().reduce(*args, **kwargs)

    def groupby(self, *args: Any, **kwargs: Any):
        return self._flat().groupby(*args, **kwargs)

    def filter(self, condition) -> "Table":
        return self._flat_with_condition(condition)

    def _flat(self) -> "Table":
        """Materialize the join with all columns of both sides (left wins on
        name conflicts, mirroring the reference's substitution rules)."""
        exprs: dict[str, Any] = {}
        for name in self._right.column_names():
            exprs[name] = self._right[name]
        for name in self._left.column_names():
            exprs[name] = self._left[name]
        return self.select(**exprs)

    def _flat_with_condition(self, condition) -> "Table":
        flat = self._flat()
        cond = resolve_expression(condition, flat, flat, flat)
        return flat.filter(cond)


class _JoinColumnNamespace:
    """``join_result.C.<name>`` / ``join_result.C[<name>]`` — mirrors
    ``table.ColumnNamespace`` (same leading-underscore guard so notebook
    protocol probes don't resolve as columns; bracket access is the
    escape hatch)."""

    __slots__ = ("_join",)

    def __init__(self, join: JoinResult):
        object.__setattr__(self, "_join", join)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._join[name]
        except KeyError as exc:
            raise AttributeError(str(exc)) from None

    def __getitem__(self, name):
        return self._join[name]


def _refers_to(e: ColumnExpression, table: "Table") -> bool:
    if isinstance(e, ColumnReference) and e.table is table:
        return True
    return any(_refers_to(d, table) for d in e._deps())


# ---------------------------------------------------------------------------
# free functions + public aliases (reference: joins.py:1105-1310,
# exported from pathway/__init__.py)
# ---------------------------------------------------------------------------


def join(
    left: "Table",
    right: "Table",
    *on,
    id=None,
    how: JoinMode = JoinMode.INNER,
    left_instance=None,
    right_instance=None,
) -> JoinResult:
    """``pw.join(a, b, ...)`` == ``a.join(b, ...)`` (reference: joins.py:1105)."""
    return left.join(
        right, *on, id=id, how=how,
        left_instance=left_instance, right_instance=right_instance,
    )


def join_inner(left: "Table", right: "Table", *on, **kwargs) -> JoinResult:
    return left.join(right, *on, how=JoinMode.INNER, **kwargs)


def join_left(left: "Table", right: "Table", *on, **kwargs) -> JoinResult:
    return left.join(right, *on, how=JoinMode.LEFT, **kwargs)


def join_right(left: "Table", right: "Table", *on, **kwargs) -> JoinResult:
    return left.join(right, *on, how=JoinMode.RIGHT, **kwargs)


def join_outer(left: "Table", right: "Table", *on, **kwargs) -> JoinResult:
    return left.join(right, *on, how=JoinMode.OUTER, **kwargs)


# reference type names kept importable for isinstance checks / signatures:
# outer-mode joins return the same deferred JoinResult here, and anything
# joinable is a TableLike
OuterJoinResult = JoinResult
