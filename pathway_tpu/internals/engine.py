"""Micro-batch incremental diff engine — the host-side dataflow runtime.

TPU-native re-design of the reference's Rust engine
(src/engine/dataflow.rs:757 ``DataflowGraphInner`` over vendored
timely/differential).  The *semantics* are kept — tables are streams of
``(key, values, time, diff)`` updates, operators maintain state and emit
retraction/insertion deltas, consistency is per-timestamp — but the
implementation is a lean single-pass topological micro-batch scheduler
instead of a general progress-tracking dataflow:

* every logical timestamp ``t`` forms one micro-batch;
* nodes are flushed in topological order, so all inputs for ``t`` are
  delivered before a node runs (the reference gets this from timely
  frontiers; a total order over a DAG gives it for free — the reference's
  outer scope is also totally ordered, src/engine/dataflow.rs MaybeTotalScope);
* stateful operators (groupby/join/...) recompute only dirty keys and emit
  diffs, mirroring differential's ``reduce``/``join_core``;
* numeric batch work (embedding, KNN search) is *not* done per-row here — it
  escapes to JAX/Pallas device ops at dedicated nodes (see
  ``pathway_tpu/stdlib/indexing`` and ``pathway_tpu/ops``).

Within one timestamp the engine preserves the updates-before-queries
invariant needed by as-of-now index serving
(reference: src/engine/dataflow/operators/external_index.rs:129-160) by
flushing a node's input ports in ascending port order.
"""

from __future__ import annotations

import asyncio
import contextlib
import gc
import itertools
from collections import Counter, defaultdict
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .keys import derive_subkey, ref_scalar
from .value import ERROR, Json, Pointer

__all__ = [
    "Entry",
    "consolidate",
    "freeze_value",
    "Node",
    "SourceNode",
    "RowwiseNode",
    "GroupByNode",
    "JoinNode",
    "ConcatNode",
    "UpdateRowsNode",
    "UpdateCellsNode",
    "SemiJoinNode",
    "DeduplicateNode",
    "OutputNode",
    "AsyncMapNode",
    "BufferNode",
    "Engine",
]

# An entry is (key, values_tuple, diff)
Entry = tuple[Pointer, tuple, int]

#: hashable stand-in for a None cell on the join fast path (None itself is
#: the slow path's "key function returned no key" sentinel)
_NULL_CELL = ("__pw_null_cell__",)


def freeze_value(v: Any) -> Any:
    """Hashable representative of a value (ndarrays/Json are unhashable)."""
    if isinstance(v, np.ndarray):
        return (b"__nd__", v.dtype.str, v.shape, v.tobytes())
    if isinstance(v, Json):
        return (b"__json__", v.to_string())
    if isinstance(v, tuple):
        return tuple(freeze_value(x) for x in v)
    if isinstance(v, dict):
        return (b"__dict__", tuple(sorted((k, freeze_value(x)) for k, x in v.items())))
    if isinstance(v, list):
        return (b"__list__", tuple(freeze_value(x) for x in v))
    return v


def freeze_row(row: tuple) -> tuple:
    # fast path: rows are overwhelmingly tuples of hashable scalars —
    # hashing probes that in C instead of a Python isinstance walk
    try:
        hash(row)
        return row
    except TypeError:
        return tuple(freeze_value(v) for v in row)


_gc_mode_depth = 0


@contextlib.contextmanager
def gc_batch_mode():
    """Tame the cyclic GC during engine flush loops.

    The engine's state (group dicts, pending rows, parsed tuples) is
    large, long-lived and acyclic; default gen-2 collections re-traverse
    all of it every few thousand allocations and were measured at ~60%
    of wordcount flush wall time (300k → 730k rows/s with gc off).
    Freezing existing objects into the permanent generation and raising
    the thresholds keeps those scans off the hot loop while still
    collecting genuinely-cyclic garbage (user UDFs may create cycles),
    unlike a blanket ``gc.disable``.  reference analogue: the Rust
    engine has no tracing GC to fight — this recovers the same property
    for the Python host plane."""
    # reentrant: pw.iterate runs an inner engine.run_all() inside the
    # outer engine's step — only the OUTERMOST enter/exit may touch gc
    # state, or the inner exit would unfreeze the outer run's heap
    global _gc_mode_depth
    _gc_mode_depth += 1
    if _gc_mode_depth > 1:
        try:
            yield
        finally:
            _gc_mode_depth -= 1
        return
    old = gc.get_threshold()
    # freeze WITHOUT a preceding collect: a full collection here would
    # re-traverse the just-built graph (often inside a caller's timed
    # window); freezing a handful of pending garbage objects permanently
    # is the cheaper trade
    gc.freeze()
    gc.set_threshold(100_000, 50, 25)
    try:
        yield
    finally:
        _gc_mode_depth -= 1
        gc.set_threshold(*old)
        gc.unfreeze()


def net_row_changes(entries: Iterable[Entry]) -> dict:
    """Fold one port's batch into the net per-key row change,
    order-independently: ``{key: new_row | None}`` where a row means the
    key's single net-inserted row and ``None`` means net-removed; keys
    whose diffs cancel exactly are absent (no change).

    Slot-per-key nodes (Zip/UpdateRows/UpdateCells) must NOT apply
    entries last-wins: upstream nodes don't promise retract-before-insert
    within a batch (e.g. JoinNode emits new matches in ``_process`` but
    outer-padding retractions later in ``_reconcile_padding``), so an
    (insert new, retract old) arrival order would otherwise null the slot
    and silently drop the key until its next touch."""
    changes: dict = {}
    # consolidate is the canonical fold (freeze_row keying, diff summing,
    # zero-dropping); a surviving positive diff is the key's net-live row
    # — universe invariant says at most one, keep the last on anomalies —
    # and surviving negatives alone mean net-removed
    for key, row, diff in consolidate(entries):
        if diff > 0:
            changes[key] = row
        else:
            changes.setdefault(key, None)
    return changes


def consolidate(entries: Iterable[Entry]) -> list[Entry]:
    """Merge entries with equal (key, values), summing diffs, dropping zeros
    (differential's ``consolidate``)."""
    acc: dict[tuple, list] = {}
    get = acc.get
    for key, row, diff in entries:
        try:
            k = (key, row)
            slot = get(k)
        except TypeError:  # unhashable cell (ndarray/Json/list/dict)
            k = (key, freeze_row(row))
            slot = get(k)
        if slot is None:
            acc[k] = [key, row, diff]
        else:
            slot[2] += diff
    return [(k, r, d) for k, r, d in acc.values() if d != 0]


class Node:
    """Runtime dataflow node."""

    # late nodes flush only after the rest of the graph is quiescent for the
    # timestamp — the global updates-before-queries barrier that the
    # reference gets from batch_by_time (external_index.rs:129)
    late: bool = False
    # local error-log subjects active when this node's operator was built
    # (errors.local_error_log); () for the common case
    error_logs: tuple = ()

    def __init__(self, n_inputs: int = 1, name: str = ""):
        self.n_inputs = n_inputs
        self.name = name or type(self).__name__
        self.pending: dict[int, list[Entry]] = defaultdict(list)
        self.downstream: list[tuple["Node", int]] = []
        self.id: int = -1

    def subscribe_to(self, node: "Node", port: int = 0) -> None:
        node.downstream.append((self, port))

    def receive(self, port: int, entries: list[Entry]) -> None:
        if entries:
            self.pending[port].extend(entries)

    def flush(self, time: int) -> list[Entry]:
        """Consume pending inputs for this timestamp, return output entries."""
        raise NotImplementedError

    def has_pending(self, time: int) -> bool:
        return any(self.pending.values())

    def end_of_step(self, time: int) -> None:
        """Called once per timestamp after the whole graph is quiescent."""

    def on_end(self) -> list[Entry]:
        """Called once when all sources are exhausted; may emit final entries."""
        return []

    def on_stream_close(self) -> None:
        """Called after all final emissions have propagated."""

    def take(self, port: int = 0) -> list[Entry]:
        entries = self.pending.pop(port, [])
        return entries


class SourceNode(Node):
    """Input: a queue of (time, entries) fed by connectors or static data."""

    def __init__(self, name: str = "source"):
        super().__init__(n_inputs=0, name=name)
        self.queue: dict[int, list[Entry]] = defaultdict(list)

    def push(self, time: int, entries: list[Entry]) -> None:
        self.queue[time].extend(entries)

    def flush(self, time: int) -> list[Entry]:
        # raw entries, no consolidation: every stateful consumer absorbs
        # diff streams (multiset counts), DeduplicateNode and OutputNode
        # consolidate their own input, and push order is preserved — the
        # same reasoning that dropped consolidation from row-wise maps
        return self.queue.pop(time, [])

    def has_pending(self, time: int) -> bool:
        return time in self.queue

    def pending_times(self) -> list[int]:
        return sorted(self.queue.keys())


class RowwiseNode(Node):
    """Stateless per-entry map (select/filter/flatten/reindex).

    ``fn(key, row, diff) -> iterable[(key', row', diff')]`` must be a
    deterministic function of (key, row); non-deterministic mappers set
    ``memoize=True`` so retractions replay the memoized result
    (reference: deterministic flag on UDFs, internals/udfs/__init__.py)."""

    def __init__(self, fn: Callable, memoize: bool = False, name: str = "rowwise"):
        super().__init__(n_inputs=1, name=name)
        self.fn = fn
        self.memoize = memoize
        self._memo: dict[tuple, list] = {}
        #: columnar fast path (set by the lowering when the select/filter
        #: vectorizes): big batches evaluate as numpy columns and fall
        #: back to the row path when a batch holds non-numeric values
        self.vector_fn = None  # rows -> list[out_row] | None
        self.vector_mask = None  # rows -> list[bool] | None
        self.vector_entries_fn = None  # entries -> list[Entry] (projections)
        self.filter_width = 0

    #: below this batch size the pool's dispatch overhead beats the win
    PARALLEL_MIN_ROWS = 64
    #: below this batch size numpy conversion overhead beats the win
    VECTOR_MIN_ROWS = 256

    def flush(self, time: int) -> list[Entry]:
        entries = self.take(0)
        if self.vector_entries_fn is not None and entries:
            # pure projection: always total (no numpy involved, so no
            # dtype fallback needed) and cheaper than per-row dispatch at
            # every batch size
            return self.vector_entries_fn(entries)
        if len(entries) >= self.VECTOR_MIN_ROWS:
            if self.vector_fn is not None:
                rows = [e[1] for e in entries]
                out_rows = self.vector_fn(rows)
                if out_rows is not None:
                    return [
                        (e[0], row, e[2])
                        for e, row in zip(entries, out_rows)
                    ]
            elif self.vector_mask is not None:
                rows = [e[1] for e in entries]
                mask = self.vector_mask(rows)
                if mask is not None:
                    w = self.filter_width
                    return [
                        (k, r[:w], d)
                        for (k, r, d), keep in zip(entries, mask)
                        if keep
                    ]
        pool = getattr(getattr(self, "engine", None), "host_pool", None)
        # no consolidation here: row-wise maps are the hottest nodes and
        # every stateful consumer (groupby/join multisets, output,
        # exchange) absorbs raw diff streams; DeduplicateNode — the one
        # consumer whose semantics need per-timestamp consolidation —
        # consolidates its own input
        if (
            pool is not None
            and not self.memoize
            and len(entries) >= self.PARALLEL_MIN_ROWS
        ):
            return self._flush_parallel(pool, entries)
        out: list[Entry] = []
        for key, row, diff in entries:
            if self.memoize:
                mk = (key, freeze_row(row))
                if mk in self._memo:
                    results = self._memo[mk]
                else:
                    results = list(self.fn(key, row, 1))
                    self._memo[mk] = results
                out.extend((k, r, d * diff) for k, r, d in results)
            else:
                out.extend(
                    (k, r, d * diff) for k, r, d in self.fn(key, row, 1)
                )
        return out

    def _flush_parallel(self, pool, entries: list[Entry]) -> list[Entry]:
        """Split the batch across the host worker pool; chunk order is
        preserved so output is identical to the serial path (timely's
        worker shards, but within one operator's batch)."""
        n = self.engine.threads
        chunk_size = (len(entries) + n - 1) // n
        chunks = [
            entries[i : i + chunk_size]
            for i in range(0, len(entries), chunk_size)
        ]

        def run_chunk(chunk):
            part: list[Entry] = []
            for key, row, diff in chunk:
                part.extend(
                    (k, r, d * diff) for k, r, d in self.fn(key, row, 1)
                )
            return part

        out: list[Entry] = []
        for part in pool.map(run_chunk, chunks):
            out.extend(part)
        return out


class ZipNode(Node):
    """N-ary key-aligned combine: rows from same-universe tables are merged
    and mapped through ``fn(key, rows_per_port) -> row``.

    Covers the reference's same-universe cross-table column references in
    ``select`` (internals/column.py RowwiseContext over multiple tables).
    Emits once all ports have the key; updates retract the previous output."""

    def __init__(self, n_inputs: int, fn: Callable, name: str = "zip"):
        super().__init__(n_inputs=n_inputs, name=name)
        self.fn = fn
        self.state: dict[Pointer, list] = {}
        self.last_out: dict[Pointer, tuple] = {}
        # chunked operator-snapshot plane (OPERATOR_PERSISTING): the
        # per-key port slots are cross-step state — restarting them empty
        # would swallow one side's post-restart retractions.  The lowering
        # assigns a deterministic persistent_id; the streaming driver
        # attaches the snapshot and restores before data flows.
        self.persistent_id: str | None = None
        self._op_snapshot = None
        self._snap_dirty: set = set()

    def flush(self, time: int) -> list[Entry]:
        touched: set[Pointer] = set()
        for port in range(self.n_inputs):
            # order-independent fold: see net_row_changes — last-wins
            # application would drop keys on (insert, retract) arrival
            # order from upstreams like JoinNode's padding reconciler
            for key, new_row in net_row_changes(self.take(port)).items():
                slot = self.state.setdefault(key, [None] * self.n_inputs)
                slot[port] = new_row
                touched.add(key)
        out: list[Entry] = []
        for key in touched:
            slot = self.state.get(key)
            prev = self.last_out.pop(key, None)
            if prev is not None:
                out.append((key, prev, -1))
            if slot is not None and all(r is not None for r in slot):
                row = self.fn(key, slot)
                self.last_out[key] = row
                out.append((key, row, 1))
            elif slot is not None and all(r is None for r in slot):
                del self.state[key]
        if self.persistent_id and self._op_snapshot is not None:
            self._snap_dirty |= touched
        return consolidate(out)

    def end_of_step(self, time: int) -> None:
        if not (
            self._snap_dirty
            and self._op_snapshot is not None
            and self.persistent_id
        ):
            self._snap_dirty.clear()
            return
        upserts = {}
        deletes = []
        for key in self._snap_dirty:
            if key in self.state:
                upserts[key] = (list(self.state[key]), self.last_out.get(key))
            else:
                deletes.append(key)
        self._op_snapshot.save_delta(
            self.persistent_id,
            time,
            upserts,
            deletes,
            live_entries=len(self.state),
        )
        self._snap_dirty.clear()

    def restore_snapshot(self, snapshot: dict) -> None:
        for key, (slot, last) in snapshot.items():
            self.state[key] = list(slot)
            if last is not None:
                self.last_out[key] = last


class GroupByNode(Node):
    """Incremental grouped reduction (reference: differential ``reduce``;
    src/engine/dataflow.rs group/reduce operators + src/engine/reduce.rs).

    State per group: multiset of per-row reducer argument tuples; dirty
    groups are recomputed wholesale and output deltas emitted."""

    def __init__(
        self,
        group_fn: Callable[[Pointer, tuple], tuple],
        instance_fn: Callable[[Pointer, tuple], Any] | None,
        args_fn: Callable[[Pointer, tuple], tuple],
        out_fn: Callable[[tuple, list], tuple],
        key_fn: Callable[[tuple, Any], Pointer] | None = None,
        reducers: Sequence[Any] = (),
        sort_by_fn: Callable[[Pointer, tuple], Any] | None = None,
        name: str = "groupby",
        persistent_id: str | None = None,
    ):
        super().__init__(n_inputs=1, name=name)
        self.group_fn = group_fn
        self.instance_fn = instance_fn
        self.args_fn = args_fn
        self.out_fn = out_fn
        self.key_fn = key_fn
        self.reducers = list(reducers)
        self.sort_by_fn = sort_by_fn
        # group_frozen -> {frozen_args: [count, raw_args, key, sort_key, seq]}
        self.state: dict[tuple, dict] = defaultdict(dict)
        # C-level counter: slot creation happens from pool threads in the
        # sharded columnar ingest, and `self._seq += 1` would race
        self._seq = itertools.count(1)
        self.group_raw: dict[tuple, tuple] = {}
        self.group_instance: dict[tuple, Any] = {}
        self.last_out: dict[tuple, Entry] = {}
        #: O(1) running aggregates per group for decomposable reducers
        #: (count/sum/avg) — a touched group emits from these instead of
        #: recomputing over its whole multiset; a state whose exactness
        #: flag (last element) dropped falls back to recompute
        self._inc_idx = [
            i for i, r in enumerate(self.reducers) if r.incremental
        ]
        self.red_state: dict[tuple, dict[int, list]] = {}
        #: columnar ingest (set by the lowering when grouping columns and
        #: reducer args are plain slot projections and every reducer is
        #: vector-safe): ``(group_slots, arg_slots_per_reducer)``
        self.vector_spec = None
        #: chunked operator-snapshot plane (streaming driver attaches it in
        #: OPERATOR_PERSISTING mode when a persistent_id is set): dirty
        #: groups accumulate per finalized time and emit as delta chunks
        self.persistent_id = persistent_id
        self._op_snapshot = None
        self._snap_dirty: set = set()

    #: below this batch size numpy conversion overhead beats the win
    VECTOR_MIN_ROWS = 512
    #: below this batch size per-thread partitioning overhead beats the
    #: win (PATHWAY_THREADS stateful scaling)
    PARALLEL_MIN_ROWS = 16_384

    def flush(self, time: int) -> list[Entry]:
        entries = self.take(0)
        dirty = None
        if self.vector_spec is not None and len(entries) >= self.VECTOR_MIN_ROWS:
            engine = getattr(self, "engine", None)
            pool = getattr(engine, "host_pool", None)
            if (
                pool is not None
                and getattr(engine, "shard_stateful", False)
                and len(entries) >= self.PARALLEL_MIN_ROWS
            ):
                dirty = self._ingest_vector_parallel(entries, pool)
            if dirty is None:
                dirty = self._ingest_vector(entries)
        if dirty is None:
            dirty = self._ingest_rows(entries)
        if self.persistent_id and self._op_snapshot is not None:
            self._snap_dirty |= dirty
        return self._emit(dirty)

    def _ingest_vector_parallel(self, entries: list[Entry], pool) -> set | None:
        """PATHWAY_THREADS scaling for the stateful hot path (reference:
        timely worker threads, src/engine/dataflow/config.rs:63-70):
        shard the batch by a hash of its FIRST grouping column so each
        thread owns a disjoint set of groups — disjoint ``state``/
        ``red_state``/``group_raw`` keys, so no locks — and run the
        columnar ingest per shard.  The np.unique/argsort inside release
        the GIL, so shards overlap on multi-core hosts.  Seq numbers are
        allocated per shard (seq-order-sensitive reducers are excluded
        from the vector gate).  Returns None to fall back when the batch
        cannot be sharded at all (object dtype / ndarray cells)."""
        group_slots, _arg_slots = self.vector_spec
        if not group_slots:
            return None  # global reduce: one group — nothing to shard
        import pandas as pd

        threads = self.engine.threads
        s0 = group_slots[0]
        vals0 = [e[1][s0] for e in entries]
        col0 = np.asarray(vals0)
        if col0.dtype == object or col0.ndim != 1:
            return None
        if col0.dtype.kind == "f":
            from .evaluator import _float_col_exact

            if not _float_col_exact(col0, vals0):
                # same guard as _ingest_vector: huge int-sourced values
                # collapse to identical floats under coercion; don't even
                # shard on a lossy identity
                return None
            # bitwise hashing must not split -0.0 / 0.0 (equal dict keys)
            # across shards — same normalization as _ingest_vector
            col0 = col0 + 0.0
        owners = pd.util.hash_array(col0) % threads
        shards: list[list[Entry]] = [[] for _ in range(threads)]
        for e, o in zip(entries, owners.tolist()):
            shards[o].append(e)
        results = list(pool.map(self._ingest_vector, shards))
        dirty: set = set()
        for i, r in enumerate(results):
            if r is None:
                # this shard's batch was columnar-unsafe (NaN/mixed):
                # none of its rows were ingested — replay it on the row
                # path (state keys stay disjoint per shard)
                r = self._ingest_rows(shards[i])
            dirty |= r
        return dirty

    def _ingest_rows(self, entries: list[Entry]) -> set:
        dirty: set[tuple] = set()
        for key, row, diff in entries:
            gvals = self.group_fn(key, row)
            args = self.args_fn(key, row)
            sort_key = self.sort_by_fn(key, row) if self.sort_by_fn else None
            # ERROR-row guard (reference: src/engine/error.rs — rows whose
            # grouping, reducer or sort inputs are ERROR go to the error
            # log and never poison the aggregate: an ERROR sort key would
            # blow up the sorted() at emission).  Symmetric across diff
            # signs: the retraction of a skipped addition skips identically.
            if (
                any(v is ERROR for v in gvals)
                or any(v is ERROR for t in args for v in t)
                or sort_key is ERROR
            ):
                if diff > 0:
                    from .errors import register_error

                    register_error(
                        "row with ERROR excluded from aggregation",
                        kind="groupby",
                        operator=self.name,
                    )
                continue
            gfrozen = freeze_row(gvals)
            self.group_raw[gfrozen] = gvals
            if self.instance_fn is not None:
                self.group_instance[gfrozen] = self.instance_fn(key, row)
            afrozen = (freeze_row(args), key if self._needs_key() else None)
            slot = self.state[gfrozen].get(afrozen)
            if slot is None:
                slot = self.state[gfrozen][afrozen] = [
                    0, args, key, sort_key, next(self._seq)
                ]
            slot[0] += diff
            if slot[0] == 0:
                del self.state[gfrozen][afrozen]
            if self._inc_idx:
                states = self.red_state.get(gfrozen)
                if states is None:
                    states = self.red_state[gfrozen] = {
                        i: self.reducers[i].init_state() for i in self._inc_idx
                    }
                for i in self._inc_idx:
                    self.reducers[i].update(states[i], args[i], diff)
            dirty.add(gfrozen)
        return dirty

    def _ingest_vector(self, entries: list[Entry]) -> set | None:
        """Columnar ingest: group the batch by its (grouping, reducer-args)
        identity with one ``np.unique`` pass, then apply ONE state update
        per distinct slot instead of one per row.  State layout and seq
        assignment match `_ingest_rows` exactly (slots are read back from
        the original Python rows, not numpy casts), so vector and row
        batches interleave freely on the same node.  Returns None to fall
        back when the batch isn't columnar-safe (object dtype, NaN)."""
        group_slots, arg_slots = self.vector_spec
        rows = [e[1] for e in entries]
        # an arg is either an int slot or a ("const", value) placeholder
        # (count()'s Const(0)); constants are identical across rows, so
        # they join the args tuples but not the identity columns
        needed = sorted(
            {*group_slots}
            | {s for sl in arg_slots for s in sl if not isinstance(s, tuple)}
        )
        cols = []
        for s in needed:
            vals = [r[s] for r in rows]
            arr = np.asarray(vals)
            if arr.dtype == object:
                return None  # None/ERROR/mixed types — row path handles
            if arr.ndim != 1:
                return None  # ndarray-valued column — row path handles
            if arr.dtype.kind in "US":
                # numpy silently coerces mixed batches (int+str, bytes+str)
                # to one string dtype, merging values Python dict identity
                # keeps distinct; numeric mixes (int/float/bool) are safe
                # because Python == agrees with the coercion
                t0 = type(vals[0])
                if t0 not in (str, bytes) or any(
                    t is not t0 for t in map(type, vals)
                ):
                    return None
            if arr.dtype.kind == "f":
                if np.isnan(arr).any():
                    # dict identity for NaN is per-object; np.unique would
                    # merge them — keep row-path semantics
                    return None
                from .evaluator import _float_col_exact

                if not _float_col_exact(arr, vals):
                    # float64 coerced from huge Python ints (e.g. an INT
                    # column mixing 2**63 with smaller numerics): distinct
                    # ints beyond 2**53 become byte-identical floats, so
                    # np.unique would merge groups the row path keeps
                    # distinct — silent wrong aggregates.  The "numeric
                    # mixes are safe" reasoning only holds within float53
                    return None
                # byte-wise rec-array identity must not split -0.0 / 0.0
                # (Python dict keys treat them equal)
                arr = arr + 0.0
            cols.append(arr)
        diffs = np.fromiter(
            (e[2] for e in entries), np.int64, count=len(entries)
        )
        if not cols:
            # global reduce with const-only args: every row shares one
            # identity — one slot, net = sum of diffs
            first_idx = np.zeros(1, np.int64)
            net = np.asarray([diffs.sum()])
        else:
            if len(cols) == 1:
                ident = cols[0]
            else:
                ident = np.rec.fromarrays(cols)
            _, first_idx, sinv = np.unique(
                ident, return_index=True, return_inverse=True
            )
            net = np.bincount(sinv, weights=diffs, minlength=len(first_idx))
        # first-occurrence order keeps slot seq numbers identical to the
        # row path (earliest/latest-style reducers are excluded from the
        # vector gate, but state must stay bit-compatible regardless)
        order = np.argsort(first_idx, kind="stable")
        dirty: set[tuple] = set()
        state = self.state
        for u in order.tolist():
            d = int(net[u])
            if d == 0:
                # add+retract cancelling within the batch: the row path's
                # create-then-delete leaves the same state, and its
                # retract+re-add emission cancels in consolidate()
                continue
            i = int(first_idx[u])
            row = rows[i]
            gvals = tuple(row[s] for s in group_slots)
            gfrozen = gvals  # scalars from non-object columns — hashable
            self.group_raw[gfrozen] = gvals
            args = tuple(
                tuple(
                    s[1] if isinstance(s, tuple) else row[s] for s in sl
                )
                for sl in arg_slots
            )
            afrozen = (args, None)
            bucket = state[gfrozen]
            slot = bucket.get(afrozen)
            if slot is None:
                slot = bucket[afrozen] = [
                    0, args, entries[i][0], None, next(self._seq)
                ]
            slot[0] += d
            if slot[0] == 0:
                del bucket[afrozen]
            if self._inc_idx:
                states = self.red_state.get(gfrozen)
                if states is None:
                    states = self.red_state[gfrozen] = {
                        j: self.reducers[j].init_state() for j in self._inc_idx
                    }
                for j in self._inc_idx:
                    self.reducers[j].update(states[j], args[j], d)
            dirty.add(gfrozen)
        return dirty

    def _emit(self, dirty: set) -> list[Entry]:
        out: list[Entry] = []
        for gfrozen in dirty:
            group_state = self.state.get(gfrozen)
            prev = self.last_out.pop(gfrozen, None)
            if prev is not None:
                out.append((prev[0], prev[1], -1))
            if not group_state:
                self.state.pop(gfrozen, None)
                self.red_state.pop(gfrozen, None)
                continue
            gvals = self.group_raw[gfrozen]
            instance = self.group_instance.get(gfrozen)
            rows = None
            inc_states = self.red_state.get(gfrozen, {})
            values = []
            for i, red in enumerate(self.reducers):
                st = inc_states.get(i)
                if st is not None and st[-1]:
                    values.append(red.current(st))
                    continue
                if rows is None:
                    rows = list(group_state.values())  # [count,args,key,sk,seq]
                    if self.sort_by_fn is not None:
                        # None sort keys (outer-join padding) order last
                        rows.sort(key=lambda s: (s[3] is None, s[3]))
                values.append(
                    red.compute([(s[1][i], s[0], s[2], s[4]) for s in rows])
                )
            if self.key_fn is not None:
                out_key = self.key_fn(gvals, instance)
            else:
                out_key = ref_scalar(*gvals)
            row = self.out_fn(gvals, values)
            entry = (out_key, row, 1)
            self.last_out[gfrozen] = entry
            out.append(entry)
        return consolidate(out)

    def _needs_key(self) -> bool:
        return any(getattr(r, "distinguish_by_key", False) for r in self.reducers)

    # -- operator snapshots (reference: operator_snapshot.rs) --
    def end_of_step(self, time: int) -> None:
        if not (
            self._snap_dirty
            and self._op_snapshot is not None
            and self.persistent_id
        ):
            self._snap_dirty.clear()
            return
        upserts: dict = {}
        deletes: list = []
        for g in self._snap_dirty:
            if g in self.state:
                upserts[g] = (
                    dict(self.state[g]),
                    self.red_state.get(g),
                    self.group_raw.get(g),
                    self.group_instance.get(g),
                    self.last_out.get(g),
                )
            else:
                deletes.append(g)
        self._op_snapshot.save_delta(
            self.persistent_id,
            time,
            upserts,
            deletes,
            live_entries=len(self.state),
        )
        self._snap_dirty.clear()

    def restore_snapshot(self, snapshot: dict) -> None:
        """Adopt restored per-group records (state, incremental reducer
        states, raw group values, instance, last emitted entry); the slot
        seq counter resumes past every restored slot so seq-sensitive
        reducers keep a total order across the restart."""
        max_seq = 0
        for g, (slots, red, graw, ginst, last) in snapshot.items():
            self.state[g] = dict(slots)
            if red is not None:
                self.red_state[g] = red
            self.group_raw[g] = graw
            if ginst is not None:
                self.group_instance[g] = ginst
            if last is not None:
                self.last_out[g] = last
            for slot in slots.values():
                max_seq = max(max_seq, slot[4])
        # past the snapshot AND the live counter: static sources may have
        # handed out seqs before restore runs, and a duplicate seq would
        # make seq-tie-broken reducers pick a different winner than the
        # pre-restart run (gaps are harmless, collisions are not)
        self._seq = itertools.count(max(max_seq, next(self._seq)) + 1)


class JoinNode(Node):
    """Incremental binary join, all modes (reference: differential
    ``join_core``; python/pathway/internals/joins.py desugaring).

    Port 0 = left, port 1 = right.  Also covers ``ix`` and ``having`` via
    custom key/out functions."""

    def __init__(
        self,
        left_key_fn: Callable[[Pointer, tuple], Any],
        right_key_fn: Callable[[Pointer, tuple], Any],
        out_fn: Callable[[Pointer | None, tuple | None, Pointer | None, tuple | None], tuple],
        out_key_fn: Callable[[Pointer | None, tuple | None, Pointer | None, tuple | None], Pointer],
        left_outer: bool = False,
        right_outer: bool = False,
        exact_match: bool = False,
        name: str = "join",
    ):
        super().__init__(n_inputs=2, name=name)
        self.left_key_fn = left_key_fn
        self.right_key_fn = right_key_fn
        self.out_fn = out_fn
        self.out_key_fn = out_key_fn
        self.left_outer = left_outer
        self.right_outer = right_outer
        self.exact_match = exact_match
        # jk_frozen -> {(key, frozen_row): [count, key, row]}
        self.left_state: dict[Any, dict] = defaultdict(dict)
        self.right_state: dict[Any, dict] = defaultdict(dict)
        self.left_count: Counter = Counter()
        self.right_count: Counter = Counter()
        # padded rows currently emitted, per side: jk -> {slot: [count,key,row]}
        self.left_padded: dict[Any, dict] = defaultdict(dict)
        self.right_padded: dict[Any, dict] = defaultdict(dict)
        #: single-column equi-join fast path (set by the lowering): probe
        #: with the raw cell — no 1-tuple build, no freeze_value walk.
        #: Both sides must be set together so bucket identities agree.
        self.left_key_slot: int | None = None
        self.right_key_slot: int | None = None

    @staticmethod
    def _apply(state: dict, jk, key, row, diff) -> None:
        slot_key = (key, freeze_row(row))
        bucket = state[jk]
        slot = bucket.get(slot_key)
        if slot is None:
            slot = bucket[slot_key] = [0, key, row]
        slot[0] += diff
        if slot[0] == 0:
            del bucket[slot_key]
            if not bucket:
                del state[jk]

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        affected: set = set()
        # incremental bilinear form: each entry is applied to state right
        # after emitting products against the *current* other side, so the
        # result is order-independent; port 0 (updates) still drains first to
        # honor updates-before-queries for as-of-now serving.
        for port in (0, 1):
            entries = self.take(port)
            out.extend(self._process(entries, left_side=(port == 0), affected=affected))
        # reconcile outer padding once both ports have settled for this time
        if self.left_outer:
            self._reconcile_padding(affected, left_side=True, out=out)
        if self.right_outer:
            self._reconcile_padding(affected, left_side=False, out=out)
        # raw diffs out: stateful consumers absorb add/retract pairs and
        # OutputNode/DeduplicateNode consolidate their own input — same
        # reasoning as row-wise maps (join emit is the next-hottest path)
        return out

    def _emit(self, lkey, lrow, rkey, rrow, diff, out: list[Entry]) -> None:
        values = self.out_fn(lkey, lrow, rkey, rrow)
        key = self.out_key_fn(lkey, lrow, rkey, rrow)
        out.append((key, values, diff))

    def _process(self, entries: list[Entry], left_side: bool, affected: set) -> list[Entry]:
        out: list[Entry] = []
        my_state = self.left_state if left_side else self.right_state
        other_state = self.right_state if left_side else self.left_state
        my_count = self.left_count if left_side else self.right_count
        slot = self.left_key_slot if left_side else self.right_key_slot
        out_fn = self.out_fn
        key_fn = self.out_key_fn
        append = out.append
        my_key_fn = None
        if slot is None:
            my_key_fn = self.left_key_fn if left_side else self.right_key_fn
        for key, row, diff in entries:
            if my_key_fn is None:
                jk = row[slot]
                if jk is None:
                    # a None CELL is an ordinary join key (the tuple path
                    # matches (None,) with (None,)); only a None result of
                    # a key FUNCTION (ix optional pointer) means no-match.
                    # _NULL_CELL is a process-unique hashable stand-in.
                    jk = _NULL_CELL
                else:
                    try:
                        hash(jk)
                    except TypeError:  # ndarray/Json cell — freeze it
                        jk = freeze_value(jk)
            else:
                jk = freeze_value(my_key_fn(key, row))
            if jk is ERROR or (
                type(jk) is tuple and any(v is ERROR for v in jk)
            ):
                # ERROR join keys never match and never enter join state
                # (reference error.rs semantics): log on addition, skip the
                # matching retraction symmetrically
                if diff > 0:
                    from .errors import register_error

                    register_error(
                        "row with ERROR join key excluded from join",
                        kind="join",
                        operator=self.name,
                    )
                continue
            if jk is None:
                # null join keys never match (SQL semantics); a null-key row
                # still participates in outer padding via a private bucket
                jk = ("__null__", key, left_side)
                affected.add(jk)
                self._apply(my_state, jk, key, row, diff)
                my_count[jk] += diff
                continue
            affected.add(jk)
            # inner products against the current other side; other_state
            # is a different dict from my_state and is only mutated by the
            # other port's drain, so iterating its live bucket is safe.
            # _emit is inlined with hoisted locals: this append is the
            # hottest line of the join (one per output row)
            bucket = other_state.get(jk)
            if bucket:
                if left_side:
                    for cnt, okey, orow in bucket.values():
                        append(
                            (
                                key_fn(key, row, okey, orow),
                                out_fn(key, row, okey, orow),
                                diff * cnt,
                            )
                        )
                else:
                    for cnt, okey, orow in bucket.values():
                        append(
                            (
                                key_fn(okey, orow, key, row),
                                out_fn(okey, orow, key, row),
                                diff * cnt,
                            )
                        )
            self._apply(my_state, jk, key, row, diff)
            my_count[jk] += diff
        return out

    def on_end(self) -> list[Entry]:
        if self.exact_match:
            # reference: joins.py exact-match validation — every row on each
            # side must have found a partner by stream close
            for jk, cnt in self.left_count.items():
                if cnt > 0 and self.right_count.get(jk, 0) <= 0:
                    raise ValueError(
                        "exact_match join: unmatched rows on the left side"
                    )
            for jk, cnt in self.right_count.items():
                if cnt > 0 and self.left_count.get(jk, 0) <= 0:
                    raise ValueError(
                        "exact_match join: unmatched rows on the right side"
                    )
        return []

    def _reconcile_padding(self, affected: set, left_side: bool, out: list[Entry]) -> None:
        my_state = self.left_state if left_side else self.right_state
        other_count = self.right_count if left_side else self.left_count
        padded = self.left_padded if left_side else self.right_padded
        for jk in affected:
            unmatched = (
                isinstance(jk, tuple) and len(jk) == 3 and jk[0] == "__null__"
            ) or other_count[jk] <= 0
            desired = my_state.get(jk, {}) if unmatched else {}
            current = padded.get(jk, {})
            if not desired and not current:
                continue
            for slot, (cnt, key, row) in list(current.items()):
                want = desired.get(slot, [0])[0]
                if want != cnt:
                    d = want - cnt
                    if left_side:
                        self._emit(key, row, None, None, d, out)
                    else:
                        self._emit(None, None, key, row, d, out)
            for slot, (cnt, key, row) in desired.items():
                if slot not in current:
                    if left_side:
                        self._emit(key, row, None, None, cnt, out)
                    else:
                        self._emit(None, None, key, row, cnt, out)
            if desired:
                padded[jk] = {s: [v[0], v[1], v[2]] for s, v in desired.items()}
            else:
                padded.pop(jk, None)


class ConcatNode(Node):
    """Union of inputs (reference: Graph::concat / concat_reindex).
    ``reindex=True`` derives fresh keys derive_subkey(key, port) to keep universes
    disjoint."""

    def __init__(self, n_inputs: int, reindex: bool = False, name: str = "concat"):
        super().__init__(n_inputs=n_inputs, name=name)
        self.reindex = reindex
        # key -> (owner_port, count): detects universe-disjointness violations
        self._owner: dict[Pointer, list] = {}

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        for port in range(self.n_inputs):
            for key, row, diff in self.take(port):
                if self.reindex:
                    out.append((derive_subkey(key, port), row, diff))
                    continue
                slot = self._owner.get(key)
                if slot is None:
                    slot = self._owner[key] = [port, 0]
                elif slot[0] != port:
                    raise ValueError(
                        "concat: tables have overlapping keys (universes are "
                        "not disjoint); use concat_reindex instead"
                    )
                slot[1] += diff
                if slot[1] == 0:
                    del self._owner[key]
                out.append((key, row, diff))
        return consolidate(out)


class UpdateRowsNode(Node):
    """``t.update_rows(other)`` — other's rows win on key collision
    (reference: graph.rs update_rows / table.py:1164)."""

    def __init__(self, name: str = "update_rows"):
        super().__init__(n_inputs=2, name=name)
        self.state: dict[Pointer, list] = {}  # key -> [self_row|None, other_row|None]

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        touched: dict[Pointer, tuple | None] = {}
        for port in (0, 1):
            # order-independent fold (see net_row_changes)
            for key, new_row in net_row_changes(self.take(port)).items():
                slot = self.state.setdefault(key, [None, None])
                if key not in touched:
                    touched[key] = self._current(slot)
                slot[port] = new_row
        for key, before in touched.items():
            slot = self.state.get(key, [None, None])
            after = self._current(slot)
            if before == after:
                continue
            if before is not None:
                out.append((key, before, -1))
            if after is not None:
                out.append((key, after, 1))
            if slot[0] is None and slot[1] is None:
                self.state.pop(key, None)
        return consolidate(out)

    @staticmethod
    def _current(slot) -> tuple | None:
        return slot[1] if slot[1] is not None else slot[0]


class UpdateCellsNode(Node):
    """``t.update_cells(other)`` — override listed columns where other has
    the key (reference: table.py:1064)."""

    def __init__(self, positions: list[int | None], name: str = "update_cells"):
        # positions[i] = index into other's row for output column i, or None
        super().__init__(n_inputs=2, name=name)
        self.positions = positions
        self.state: dict[Pointer, list] = {}

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        touched: dict[Pointer, tuple | None] = {}
        for port in (0, 1):
            # order-independent fold (see net_row_changes)
            for key, new_row in net_row_changes(self.take(port)).items():
                slot = self.state.setdefault(key, [None, None])
                if key not in touched:
                    touched[key] = self._current(slot)
                slot[port] = new_row
        for key, before in touched.items():
            slot = self.state.get(key, [None, None])
            after = self._current(slot)
            if before == after:
                continue
            if before is not None:
                out.append((key, before, -1))
            if after is not None:
                out.append((key, after, 1))
            if slot[0] is None and slot[1] is None:
                self.state.pop(key, None)
        return consolidate(out)

    def _current(self, slot) -> tuple | None:
        base, other = slot
        if base is None:
            return None
        if other is None:
            return base
        return tuple(
            other[p] if p is not None else v
            for v, p in zip(base, self.positions)
        )


class SemiJoinNode(Node):
    """Restrict port-0 rows by presence of their mask-key on port 1
    (intersect / difference / restrict / having).
    reference: graph.rs intersect/restrict/difference."""

    def __init__(
        self,
        mask_key_fn: Callable[[Pointer, tuple], Any],
        right_key_fn: Callable[[Pointer, tuple], Any] | None = None,
        mode: str = "intersect",
        name: str = "semijoin",
    ):
        super().__init__(n_inputs=2, name=name)
        self.mask_key_fn = mask_key_fn
        self.right_key_fn = right_key_fn or (lambda k, r: k)
        self.mode = mode
        self.left_state: dict[Any, dict] = defaultdict(dict)
        self.right_count: Counter = Counter()

    def _passes(self, count: int) -> bool:
        return count > 0 if self.mode == "intersect" else count == 0

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        for key, row, diff in self.take(0):
            mk = freeze_value(self.mask_key_fn(key, row))
            JoinNode._apply(self.left_state, mk, key, row, diff)
            if self._passes(self.right_count[mk]):
                out.append((key, row, diff))
        for key, row, diff in self.take(1):
            mk = freeze_value(self.right_key_fn(key, row))
            c0 = self.right_count[mk]
            self.right_count[mk] = c1 = c0 + diff
            flipped = self._passes(c1) != self._passes(c0)
            if flipped:
                sign = 1 if self._passes(c1) else -1
                for cnt, lkey, lrow in list(self.left_state.get(mk, {}).values()):
                    out.append((lkey, lrow, sign * cnt))
        return consolidate(out)


class DeduplicateNode(Node):
    """``t.deduplicate(value=..., acceptor=...)`` — keep one accepted row per
    instance, consulting ``acceptor(new, current)``
    (reference: stdlib/stateful/deduplicate.py + operators/stateful_reduce.rs).
    State survives via operator snapshots when persistence is on."""

    def __init__(
        self,
        instance_fn: Callable[[Pointer, tuple], Any],
        value_fn: Callable[[Pointer, tuple], Any],
        acceptor: Callable[[Any, Any], bool],
        name: str = "deduplicate",
        persistent_id: str | None = None,
    ):
        super().__init__(n_inputs=1, name=name)
        self.instance_fn = instance_fn
        self.value_fn = value_fn
        self.acceptor = acceptor
        self.persistent_id = persistent_id
        self.state: dict[Any, tuple[Pointer, tuple]] = {}
        # chunked operator-snapshot plane attached by the streaming driver
        # when full persistence is on (reference: operator_snapshot.rs);
        # _snap_dirty holds the instance keys touched since the last
        # finalized time, so a commit writes O(delta), not O(state)
        self._op_snapshot = None
        self._snap_dirty: set = set()

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        # consolidate here: a transient add+retract pair within one
        # timestamp (possible now that row-wise maps emit raw diffs) must
        # not reach the acceptor
        for key, row, diff in consolidate(self.take(0)):
            if diff <= 0:
                continue  # dedup consumes an append-only stream
            inst = freeze_value(self.instance_fn(key, row))
            new_val = self.value_fn(key, row)
            current = self.state.get(inst)
            if current is None:
                accept = True
            else:
                cur_val = self.value_fn(*current)
                accept = bool(self.acceptor(new_val, cur_val))
            if accept:
                out_key = ref_scalar(*(inst if isinstance(inst, tuple) else (inst,)))
                if current is not None:
                    out.append((out_key, current[1], -1))
                self.state[inst] = (key, row)
                self._snap_dirty.add(inst)
                out.append((out_key, row, 1))
        return consolidate(out)

    def end_of_step(self, time: int) -> None:
        if self._snap_dirty and self._op_snapshot is not None and self.persistent_id:
            upserts = {
                inst: self.state[inst]
                for inst in self._snap_dirty
                if inst in self.state
            }
            deletes = [i for i in self._snap_dirty if i not in self.state]
            self._op_snapshot.save_delta(
                self.persistent_id,
                time,
                upserts,
                deletes,
                live_entries=len(self.state),
            )
        self._snap_dirty.clear()

    def restore_snapshot(self, state: dict) -> None:
        """Adopt a restored base+delta state (streaming driver startup)."""
        self.state = dict(state)


class BufferNode(Node):
    """Delay/cutoff buffer for temporal behaviors
    (reference: src/engine/dataflow/operators/time_column.rs forget/buffer).

    Holds entries until ``threshold_fn(row) <= watermark``; with
    ``forget=True`` also retracts rows older than the cutoff."""

    def __init__(
        self,
        threshold_fn: Callable[[tuple], Any],
        name: str = "buffer",
    ):
        super().__init__(n_inputs=1, name=name)
        self.threshold_fn = threshold_fn
        self.held: list[Entry] = []

    def flush(self, time: int) -> list[Entry]:
        self.held.extend(self.take(0))
        ready: list[Entry] = []
        still: list[Entry] = []
        for key, row, diff in self.held:
            if self.threshold_fn(row) <= time:
                ready.append((key, row, diff))
            else:
                still.append((key, row, diff))
        self.held = still
        return consolidate(ready)

    def on_end(self) -> list[Entry]:
        ready, self.held = self.held, []
        return consolidate(ready)


class AsyncMapNode(Node):
    """Async row-wise apply with bounded fan-out
    (reference: graph.rs:723 ``async_apply_table`` +
    internals/udfs/executors.py AsyncExecutor: capacity/timeout/retries).

    Results are memoized by frozen input so retractions replay identically —
    the same contract the reference enforces for non-deterministic UDFs.

    Batches run on the process's persistent event loop (internals/aio.py).
    With ``pipelined=True`` (the ``fully_async`` executor contract:
    reference python/pathway/internals/udfs/executors.py
    ``FullyAsyncExecutor`` — results land at a *later* engine time) the
    node is double-buffered: flush(t) dispatches batch t to the loop and
    emits the now-resolved batch t-1, so device work for one micro-batch
    overlaps host ingest/parse of the next — the host/device overlap a
    TPU framework needs."""

    def __init__(
        self,
        async_fn: Callable,  # async (row) -> out_row
        capacity: int | None = None,
        pipelined: bool = False,
        name: str = "async_map",
    ):
        super().__init__(n_inputs=1, name=name)
        self.async_fn = async_fn
        self.capacity = capacity
        self.pipelined = pipelined
        self._memo: dict[tuple, tuple] = {}
        # pipelined mode: (dispatch_time, future, frozen_keys, entries)
        self._in_flight: list[tuple] = []
        #: inputs dispatched but possibly unresolved — a retraction whose
        #: addition is still in flight must NOT recompute (it could differ
        #: for a non-deterministic fn and unpair the add/retract)
        self._scheduled: set[tuple] = set()

    def _dispatch(self, rows: list):
        from .aio import submit

        async def runner():
            sem = asyncio.Semaphore(self.capacity) if self.capacity else None

            async def one(row):
                if sem is None:
                    return await self.async_fn(row)
                async with sem:
                    return await self.async_fn(row)

            return await asyncio.gather(*[one(r) for r in rows])

        return submit(runner())

    def flush(self, time: int) -> list[Entry]:
        entries = self.take(0)
        to_compute: dict[tuple, tuple] = {}
        for key, row, diff in entries:
            fk = freeze_row(row)
            if (
                fk not in self._memo
                and fk not in to_compute
                and fk not in self._scheduled
            ):
                to_compute[fk] = row
        if not self.pipelined:
            if to_compute:
                results = self._dispatch(list(to_compute.values())).result()
                for fk, res in zip(to_compute.keys(), results):
                    self._memo[fk] = res
            out: list[Entry] = []
            for key, row, diff in entries:
                out.append((key, self._memo[freeze_row(row)], diff))
            return consolidate(out)
        # pipelined: dispatch this batch, emit batches dispatched at
        # earlier timestamps (their device work ran while the host was
        # parsing/ingesting this one)
        if entries:
            fut = (
                self._dispatch(list(to_compute.values())) if to_compute else None
            )
            self._scheduled.update(to_compute.keys())
            self._in_flight.append((time, fut, list(to_compute.keys()), entries))
        return self._drain(lambda t: t < time)

    def _drain(self, ready) -> list[Entry]:
        out: list[Entry] = []
        rest: list[tuple] = []
        for t, fut, fks, batch in self._in_flight:
            if not ready(t):
                rest.append((t, fut, fks, batch))
                continue
            if fut is not None:
                for fk, res in zip(fks, fut.result()):
                    self._memo[fk] = res
            for key, row, diff in batch:
                out.append((key, self._memo[freeze_row(row)], diff))
        self._in_flight = rest
        return consolidate(out)

    def has_pending(self, time: int) -> bool:
        if super().has_pending(time):
            return True
        return self.pipelined and any(t < time for t, *_ in self._in_flight)

    def async_ready(self) -> bool:
        """True when a dispatched batch has resolved and only needs an
        engine step to emit — lets an idle streaming driver drain results
        promptly instead of waiting for the next input."""
        return self.pipelined and any(
            fut is None or fut.done() for _, fut, *_ in self._in_flight
        )

    def on_end(self) -> list[Entry]:
        return self._drain(lambda t: True) if self.pipelined else []


class OutputNode(Node):
    """Terminal node: materializes the table and fires subscribe callbacks
    (reference: graph.rs:733 ``subscribe_table`` / SubscribeCallbacks:548)."""

    def __init__(
        self,
        on_change: Callable | None = None,
        on_time_end: Callable | None = None,
        on_end: Callable | None = None,
        keep_history: bool = True,
        name: str = "output",
    ):
        super().__init__(n_inputs=1, name=name)
        self.on_change = on_change
        self.on_time_end_cb = on_time_end
        self.on_end_cb = on_end
        # debug/materialize needs the full update stream; long-running
        # subscribe sinks must not accumulate it (unbounded growth)
        self.keep_history = keep_history
        self.current: dict[Pointer, tuple] = {}
        self.history: list[tuple[Pointer, tuple, int, int]] = []  # key,row,time,diff

    def flush(self, time: int) -> list[Entry]:
        entries = consolidate(self.take(0))
        self._step_touched = self._step_touched or bool(entries)
        # retractions before additions (an upsert's delete must precede
        # its insert in callbacks); diffs are ±k so a stable partition
        # equals the old sorted(key=diff) at a fraction of the cost, and
        # the common all-additions batch skips the pass entirely
        if any(e[2] < 0 for e in entries):
            entries = [e for e in entries if e[2] < 0] + [
                e for e in entries if e[2] >= 0
            ]
        for key, row, diff in entries:
            if self.keep_history:
                self.history.append((key, row, time, diff))
            if diff > 0:
                self.current[key] = row
            else:
                self.current.pop(key, None)
            if self.on_change is not None:
                for _ in range(abs(diff)):
                    self.on_change(key, row, time, diff > 0)
        return []

    _step_touched = False

    def end_of_step(self, time: int) -> None:
        if self._step_touched and self.on_time_end_cb is not None:
            self.on_time_end_cb(time)
        self._step_touched = False

    def on_stream_close(self) -> None:
        if self.on_end_cb is not None:
            self.on_end_cb()


class Engine:
    """Micro-batch scheduler (replaces the reference's
    ``worker.step_or_park`` event loop, dataflow.rs:5680 area).

    Within one timestamp, nodes are flushed in passes until the whole graph
    is quiescent, so correctness does not depend on node insertion order
    (timely gets the same property from its scheduler)."""

    def __init__(self):
        self.nodes: list[Node] = []
        self.sources: list[SourceNode] = []
        self.frontier: int = -1
        # attached by pw.run when monitoring is on (internals/monitoring.py)
        self.monitor = None
        #: host worker pool (PATHWAY_THREADS, reference timely
        #: Config::process(threads), dataflow/config.rs:63-70): row-wise
        #: operator batches split across threads.  Pure Python mappers are
        #: GIL-bound, but UDFs doing IO or native work (numpy, JAX
        #: dispatch, tokenizers, zlib) release the GIL and scale.
        self.threads: int = 1
        self.host_pool = None
        self.shard_stateful = False

    def set_threads(self, threads: int) -> None:
        if threads > 1 and self.host_pool is None:
            import os as _os
            from concurrent.futures import ThreadPoolExecutor

            self.threads = threads
            self.host_pool = ThreadPoolExecutor(
                max_workers=threads, thread_name_prefix="pw-worker"
            )
            #: shard stateful columnar ingest across the pool only where
            #: threads can actually overlap (numpy releases the GIL, but
            #: a single core just pays the partitioning tax)
            self.shard_stateful = (
                (_os.cpu_count() or 1) > 1
                or _os.environ.get("PATHWAY_FORCE_THREAD_SHARDS") == "1"
            )

    def add(self, node: Node) -> Node:
        node.id = len(self.nodes)
        node.engine = self
        self.nodes.append(node)
        if isinstance(node, SourceNode):
            self.sources.append(node)
        return node

    def connect(self, src: Node, dst: Node, port: int = 0) -> None:
        src.downstream.append((dst, port))

    def step(self, time: int) -> None:
        """Process one timestamp to quiescence (drives :meth:`step_iter`
        straight through — the yields only matter to the distributed
        wavefront scheduler)."""
        for _node in self.step_iter(time):
            pass

    def step_iter(self, time: int, skip_ids: frozenset = frozenset()):
        """Resumable :meth:`step`: processes one timestamp to quiescence,
        yielding each exchange node just before flushing it.

        Two phases per pass: regular nodes run until quiet, then ``late``
        nodes (exchanges, as-of-now index serving) get one pass —
        guaranteeing every index update for this timestamp lands before
        any query is answered.

        The yield protocol is the poor-man's timely frontier (reference:
        src/engine/dataflow.rs:5689-5731 ``step_or_park``): between two
        yields a round's work runs atomically, so a scheduler that
        resumes round ``t+1`` past an exchange only after round ``t``
        passed it preserves per-node timestamp order while rounds overlap
        — a downstream exchange can send round ``t+1`` while an upstream
        straggler still completes ``t`` (io/streaming.py wavefront loop).
        """
        for _pass in range(100_000):
            progressed = False
            for node in self.nodes:
                if (
                    node.late
                    or node.id in skip_ids
                    or not node.has_pending(time)
                ):
                    # skip_ids: the ingest-safe subgraph belongs to the
                    # stage-1 ingest thread in distributed runs — touching
                    # it here would race half-delivered later rounds
                    continue
                progressed = True
                out = self._flush_node(node, time)
                if out:
                    for consumer, port in node.downstream:
                        consumer.receive(port, out)
            if progressed:
                continue
            # one late node per pass: its output must fully propagate (and any
            # downstream late node's inputs settle) before the next late node
            # answers — keeps the barrier correct for chained late nodes
            for node in self.nodes:
                if node.late and node.has_pending(time):
                    progressed = True
                    if getattr(node, "is_exchange", False):
                        # suspension point: local input is settled (all
                        # earlier nodes quiesced) — the scheduler may
                        # prepare()/send now and resume when peers' data
                        # arrived and the wavefront guard clears
                        yield node
                    out = self._flush_node(node, time)
                    if out:
                        for consumer, port in node.downstream:
                            consumer.receive(port, out)
                    break
            if not progressed:
                break
        else:  # pragma: no cover
            raise RuntimeError("engine did not quiesce (cycle without progress?)")
        for node in self.nodes:
            node.end_of_step(time)
        self.frontier = time
        if self.monitor is not None:
            self.monitor.record_step(time)

    def step_ingest(self, time: int, safe_ids: set, first_hop) -> None:
        """Stage 1 of a distributed round, runnable AHEAD of older
        unfinished rounds: flush the ingest-safe subgraph (nodes whose
        outputs flow only into exchange inputs — internals/exchange.py
        ``ingest_safe_nodes``) to quiescence for ``time``, then partition
        and SEND the first-hop exchanges' batches without waiting for
        peers.  Everything else stays queued until ``step`` finishes the
        round in order."""
        for _pass in range(100_000):
            progressed = False
            for node in self.nodes:
                if node.id not in safe_ids or not node.has_pending(time):
                    continue
                progressed = True
                out = self._flush_node(node, time)
                if out:
                    for consumer, port in node.downstream:
                        consumer.receive(port, out)
            if not progressed:
                break
        else:  # pragma: no cover
            raise RuntimeError("step_ingest did not quiesce")
        for node in first_hop:
            node.prepare(time)

    def _flush_node(self, node: Node, time: int) -> list[Entry]:
        logs = node.error_logs
        if logs:
            from .errors import set_current_local

            set_current_local(logs)
        try:
            import time as _time_mod

            from .flight_recorder import get_recorder

            recorder = get_recorder()
            if self.monitor is None and not recorder.enabled:
                return node.flush(time)
            wall0 = _time_mod.time()
            t0 = _time_mod.perf_counter()
            out = node.flush(time)
            elapsed = _time_mod.perf_counter() - t0
            if self.monitor is not None:
                self.monitor.record_flush(node.name, len(out), elapsed)
            # the flight recorder sees every flush even when the stats
            # monitor is off (the default server path): a slow operator
            # window is dumpable from /v1/debug/traces with zero setup
            recorder.record(
                f"flush:{node.name}",
                "engine",
                wall0,
                elapsed * 1000.0,
                attrs={"rows": len(out), "t": time},
            )
            return out
        finally:
            if logs:
                from .errors import set_current_local

                set_current_local(())

    def has_async_ready(self) -> bool:
        """Any pipelined async node holding resolved, unemitted results."""
        return any(
            isinstance(n, AsyncMapNode) and n.async_ready() for n in self.nodes
        )

    def has_placement_flush_pending(self) -> bool:
        """Any index node with an unstaged tier-placement change (duck-
        typed — ExternalIndexNode lives a layer above this module).  The
        streaming driver steps once while idle so end_of_step persists
        it; see lowering.ExternalIndexNode.placement_flush_pending."""
        for n in self.nodes:
            fn = getattr(n, "placement_flush_pending", None)
            if fn is not None and fn():
                return True
        return False

    def run_all(self) -> None:
        """Batch mode: drain all queued source times, then close."""
        with gc_batch_mode():
            while True:
                times = sorted(
                    {t for s in self.sources for t in s.pending_times()}
                )
                if not times:
                    break
                for t in times:
                    self.step(t)
        self.finish()

    def finish(self) -> None:
        for node in self.nodes:
            out = node.on_end()
            if out:
                for consumer, port in node.downstream:
                    consumer.receive(port, out)
        # propagate final emissions, then fire close callbacks
        self.step(self.frontier + 1)
        for node in self.nodes:
            node.on_stream_close()
        if self.host_pool is not None:
            # each run builds its own engine — don't leak worker threads
            self.host_pool.shutdown(wait=False)
            self.host_pool = None
