"""Dynamic value model for pathway_tpu.

TPU-native rebuild of the reference engine's value layer
(reference: src/engine/value.rs:207 ``Value`` enum, src/engine/time.rs).

Values flowing through the dataflow are plain Python objects drawn from a
closed set: ``None``, ``bool``, ``int``, ``float``, ``str``, ``bytes``,
:class:`Pointer` (128-bit keys, value.rs:41), ``numpy.ndarray`` (the
reference's IntArray/FloatArray), ``tuple``, :class:`Json`,
:class:`DateTimeNaive`, :class:`DateTimeUtc`, :class:`Duration`, and the
:data:`ERROR` sentinel (src/engine/error.rs ``Value::Error``).

Unlike the reference there is no boxed enum — the host runtime is Python and
numeric batches are handed to JAX as arrays, so boxing would only add cost.
"""

from __future__ import annotations

import datetime
import json as _json
from typing import Any, Iterator

import numpy as np

__all__ = [
    "Json",
    "Pointer",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "Error",
    "ERROR",
    "Pending",
    "PENDING",
    "NONE_SENTINEL",
]


class Error:
    """Singleton error marker (reference: src/engine/error.rs ``Value::Error``).

    Stored in cells when ``terminate_on_error=False`` routes row-level
    failures into the data plane instead of aborting the run.
    """

    _instance: "Error | None" = None

    def __new__(cls) -> "Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __reduce__(self):
        return (Error, ())


ERROR = Error()


class Pending:
    """Singleton marker for values of ``Future`` dtype that have not resolved
    yet (reference: python/pathway/internals/dtype.py ``Future``)."""

    _instance: "Pending | None" = None

    def __new__(cls) -> "Pending":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Pending"

    def __reduce__(self):
        return (Pending, ())


PENDING = Pending()

# Marker used internally where ``None`` is a valid payload.
NONE_SENTINEL = object()


class Json:
    """Thin immutable wrapper marking a value as JSON-typed
    (reference: src/engine/value.rs ``Value::Json``;
    python/pathway/internals/json.py).

    Supports ``[]`` access returning nested ``Json`` wrappers and ``.as_*``
    coercions mirroring the reference's ``pw.Json`` API.
    """

    __slots__ = ("_value",)

    NULL: "Json"

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    @classmethod
    def parse(cls, s: str | bytes) -> "Json":
        return cls(_json.loads(s))

    @classmethod
    def dumps(cls, obj: Any) -> str:
        return _json.dumps(obj, default=_json_default)

    def to_string(self) -> str:
        return _json.dumps(self._value, default=_json_default)

    def __getitem__(self, item: str | int) -> "Json":
        return Json(self._value[item])

    def get(self, key: str | int, default: Any = None) -> "Json | Any":
        try:
            return Json(self._value[key])
        except (KeyError, IndexError, TypeError):
            return default

    def __iter__(self) -> Iterator["Json"]:
        return (Json(v) for v in self._value)

    def __len__(self) -> int:
        return len(self._value)

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return NotImplemented

    def __hash__(self) -> int:
        return hash(_freeze(self._value))

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return self.to_string()

    # -- coercions (reference python/pathway/internals/json.py) --
    def as_int(self) -> int | None:
        v = self._value
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if isinstance(v, float):
            return int(v) if v.is_integer() else None
        return v

    def as_float(self) -> float | None:
        v = self._value
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)

    def as_str(self) -> str | None:
        return self._value if isinstance(self._value, str) else None

    def as_bool(self) -> bool | None:
        return self._value if isinstance(self._value, bool) else None

    def as_list(self) -> list | None:
        return self._value if isinstance(self._value, list) else None

    def as_dict(self) -> dict | None:
        return self._value if isinstance(self._value, dict) else None


Json.NULL = Json(None)


def _json_default(obj: Any):
    if isinstance(obj, Json):
        return obj.value
    if isinstance(obj, Pointer):
        return str(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (DateTimeNaive, DateTimeUtc, Duration)):
        return str(obj)
    if isinstance(obj, bytes):
        return obj.decode("utf-8", errors="replace")
    raise TypeError(f"not JSON serializable: {type(obj)}")


def _freeze(v: Any):
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    return v


class Pointer(int):
    """128-bit row key (reference: src/engine/value.rs:41 ``Key``).

    The low 16 bits form the shard field (value.rs:38 ``SHARD_MASK``) used by
    the ``ShardPolicy::LastKeyColumn`` instance-based co-partitioning — the
    same field decides which host/device shard owns the row in the TPU build.

    Subclasses ``int`` so hashing/equality on every consolidate, groupby
    and join probe run at C level with no Python frame — keys are the
    hottest dict keys in the engine.  Type-dispatch sites that must
    distinguish keys from plain ints (wire format, key serialization,
    const dtype inference) check Pointer before int.  Accepted tradeoff:
    ``Pointer(n) == n`` — a Pointer and a numerically equal plain int
    merge when used as dict keys in the same mapping.  Columns are
    statically typed (POINTER vs INT), so mixed mappings only arise for
    ANY-typed columns, mirroring the kind of cross-type equality the row
    path already had for int/float/bool.
    """

    __slots__ = ()

    SHARD_BITS = 16
    SHARD_MASK = (1 << SHARD_BITS) - 1
    _MOD = 1 << 128

    def __new__(cls, value: int):
        if 0 <= value < cls._MOD:
            # already in range (every derived key is): skip the 128-bit
            # mask, which would allocate a fresh bigint per construction
            return int.__new__(cls, value)
        return int.__new__(cls, value & (cls._MOD - 1))

    @property
    def value(self) -> int:
        return int(self)

    @property
    def shard(self) -> int:
        return int(self) & self.SHARD_MASK

    def with_shard(self, shard: int) -> "Pointer":
        """reference: value.rs:76 ``with_shard_of``"""
        return Pointer((int(self) & ~self.SHARD_MASK) | (shard & self.SHARD_MASK))

    def with_shard_of(self, other: "Pointer") -> "Pointer":
        return self.with_shard(other.shard)

    def __repr__(self) -> str:
        return f"^{self.value:032X}"

    def __str__(self) -> str:
        return f"^{self.value:032X}"


class Duration:
    """Signed time delta with nanosecond resolution
    (reference: src/engine/time.rs ``Duration``)."""

    __slots__ = ("_ns",)

    def __init__(self, ns: int = 0, **kwargs):
        if kwargs:
            td = datetime.timedelta(**kwargs)
            ns += (td.days * 86400 + td.seconds) * 1_000_000_000 + td.microseconds * 1000
        self._ns = int(ns)

    # constructors
    @classmethod
    def from_timedelta(cls, td: datetime.timedelta) -> "Duration":
        return cls(
            (td.days * 86400 + td.seconds) * 1_000_000_000 + td.microseconds * 1000
        )

    def to_timedelta(self) -> datetime.timedelta:
        return datetime.timedelta(microseconds=self._ns / 1000)

    # accessors (mirror pw .dt namespace needs)
    @property
    def ns(self) -> int:
        return self._ns

    def nanoseconds(self) -> int:
        return self._ns

    def microseconds(self) -> int:
        return self._ns // 1_000

    def milliseconds(self) -> int:
        return self._ns // 1_000_000

    def seconds(self) -> int:
        return self._ns // 1_000_000_000

    def minutes(self) -> int:
        return self._ns // 60_000_000_000

    def hours(self) -> int:
        return self._ns // 3_600_000_000_000

    def days(self) -> int:
        return self._ns // 86_400_000_000_000

    def weeks(self) -> int:
        return self._ns // 604_800_000_000_000

    def __add__(self, other):
        if isinstance(other, Duration):
            return Duration(self._ns + other._ns)
        if isinstance(other, (DateTimeNaive, DateTimeUtc)):
            return other + self
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, Duration):
            return Duration(self._ns - other._ns)
        return NotImplemented

    def __neg__(self):
        return Duration(-self._ns)

    def __mul__(self, other):
        if isinstance(other, bool):
            return NotImplemented
        if isinstance(other, int):
            return Duration(self._ns * other)
        return NotImplemented

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, Duration):
            return self._ns / other._ns
        return NotImplemented

    def __floordiv__(self, other):
        if isinstance(other, Duration):
            return self._ns // other._ns
        if isinstance(other, int) and not isinstance(other, bool):
            return Duration(self._ns // other)
        return NotImplemented

    def __mod__(self, other):
        if isinstance(other, Duration):
            return Duration(self._ns % other._ns)
        return NotImplemented

    def __eq__(self, other):
        return isinstance(other, Duration) and self._ns == other._ns

    def __lt__(self, other):
        return self._ns < other._ns

    def __le__(self, other):
        return self._ns <= other._ns

    def __gt__(self, other):
        return self._ns > other._ns

    def __ge__(self, other):
        return self._ns >= other._ns

    def __hash__(self):
        return hash(("Duration", self._ns))

    def __repr__(self):
        return f"Duration({self.to_timedelta()!r})"

    def __str__(self):
        return str(self.to_timedelta())


class _DateTimeBase:
    __slots__ = ("_ns",)
    _utc: bool = False

    def __init__(self, value: "str | int | datetime.datetime | None" = None, fmt: str | None = None, ns: int | None = None):
        if ns is not None:
            self._ns = int(ns)
            return
        if isinstance(value, int):
            self._ns = value
            return
        if isinstance(value, datetime.datetime):
            self._ns = _dt_to_ns(value, self._utc)
            return
        if isinstance(value, str):
            if fmt is not None:
                dt = datetime.datetime.strptime(value, _convert_format(fmt))
            else:
                dt = datetime.datetime.fromisoformat(value)
            self._ns = _dt_to_ns(dt, self._utc)
            return
        raise TypeError(f"cannot construct datetime from {value!r}")

    @property
    def ns(self) -> int:
        return self._ns

    def timestamp_ns(self) -> int:
        return self._ns

    def timestamp(self, unit: str = "ns") -> float:
        div = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}[unit]
        return self._ns / div

    def to_datetime(self) -> datetime.datetime:
        tz = datetime.timezone.utc if self._utc else None
        return datetime.datetime.fromtimestamp(self._ns / 1_000_000_000, tz=tz)

    # components
    def _dt(self) -> datetime.datetime:
        return self.to_datetime()

    def year(self) -> int:
        return self._dt().year

    def month(self) -> int:
        return self._dt().month

    def day(self) -> int:
        return self._dt().day

    def hour(self) -> int:
        return self._dt().hour

    def minute(self) -> int:
        return self._dt().minute

    def second(self) -> int:
        return self._dt().second

    def millisecond(self) -> int:
        return self._dt().microsecond // 1000

    def microsecond(self) -> int:
        return self._dt().microsecond

    def nanosecond(self) -> int:
        return self._ns % 1_000_000_000

    def weekday(self) -> int:
        """0 = Monday … 6 = Sunday (reference: date_time.py:1567 — naive
        uses the wall-clock day, UTC the UTC day; both are this ns' day).
        1970-01-01 was a Thursday (= 3)."""
        return int(((self._ns // 86_400_000_000_000) + 3) % 7)

    def strftime(self, fmt: str) -> str:
        return self._dt().strftime(_convert_format(fmt))

    def __add__(self, other):
        if isinstance(other, Duration):
            return type(self)(ns=self._ns + other.ns)
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, type(self)):
            return Duration(self._ns - other._ns)
        if isinstance(other, Duration):
            return type(self)(ns=self._ns - other.ns)
        return NotImplemented

    def __eq__(self, other):
        return type(other) is type(self) and self._ns == other._ns

    def __lt__(self, other):
        self._check(other)
        return self._ns < other._ns

    def __le__(self, other):
        self._check(other)
        return self._ns <= other._ns

    def __gt__(self, other):
        self._check(other)
        return self._ns > other._ns

    def __ge__(self, other):
        self._check(other)
        return self._ns >= other._ns

    def _check(self, other):
        if type(other) is not type(self):
            raise TypeError(f"cannot compare {type(self).__name__} with {type(other).__name__}")

    def __hash__(self):
        return hash((type(self).__name__, self._ns))

    def __str__(self):
        return self._dt().isoformat(sep=" ")

    def __repr__(self):
        return f"{type(self).__name__}({self})"


class DateTimeNaive(_DateTimeBase):
    """Timezone-naive datetime, ns resolution (reference: src/engine/time.rs
    ``DateTimeNaive``)."""

    _utc = False


class DateTimeUtc(_DateTimeBase):
    """UTC datetime, ns resolution (reference: src/engine/time.rs
    ``DateTimeUtc``)."""

    _utc = True


def _dt_to_ns(dt: datetime.datetime, utc: bool) -> int:
    # exact integer arithmetic — float paths lose sub-microsecond precision
    if utc:
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    else:
        if dt.tzinfo is not None:
            dt = dt.replace(tzinfo=None)
        epoch = datetime.datetime(1970, 1, 1)
    td = dt - epoch
    return (td.days * 86400 + td.seconds) * 1_000_000_000 + td.microseconds * 1000


_FORMAT_MAP = {
    # chrono-style codes used by the reference docs that strptime lacks
    "%T": "%H:%M:%S",
    "%F": "%Y-%m-%d",
}


def _convert_format(fmt: str) -> str:
    for k, v in _FORMAT_MAP.items():
        fmt = fmt.replace(k, v)
    return fmt
