"""Central registry of every ``pathway_*`` metric family this process emits.

Observability drifts silently: a renamed series breaks dashboards without
breaking a single test.  Every emitter (operator stats, connectors, the
serving scheduler, breakers, the error log, tracing stage histograms,
freshness watermarks, XLA compile counters) declares its families here and
``tests/test_observability.py`` greps the tree for emitted ``pathway_*``
literals and fails on any series not declared — the lint that keeps the
README metric table honest across PRs.

This module is a dependency LEAF (stdlib only): ``flight_recorder.py``,
``monitoring.py`` and the xpack emitters all import it, so it must never
import back into the package.  The shared OpenMetrics helpers
(:func:`escape_label_value`, :class:`Histogram`) live here for the same
reason — one escaping implementation for every emitter instead of five
ad-hoc ``.replace()`` calls.
"""

from __future__ import annotations

__all__ = ["METRICS", "declared_metric_names", "escape_label_value", "Histogram"]


#: family name -> (type, help).  ``histogram`` families emit
#: ``_bucket``/``_sum``/``_count`` samples; everything else emits samples
#: under the family name itself.
METRICS: dict[str, tuple[str, str]] = {
    # engine / operator plane (internals/monitoring.py)
    "pathway_uptime_seconds": ("gauge", "seconds since the monitor started"),
    "pathway_current_timestamp": ("gauge", "engine frontier timestamp"),
    "pathway_operator_rows_total": ("counter", "rows emitted per operator"),
    "pathway_operator_busy_seconds": ("counter", "cumulative flush time per operator"),
    "pathway_operator_flush_ms": ("histogram", "per-operator flush latency"),
    # connector plane (internals/monitoring.py)
    "pathway_connector_messages_total": ("counter", "messages committed per connector"),
    "pathway_connector_finished": ("gauge", "1 once a finite connector closed"),
    # serving scheduler (xpacks/llm/_scheduler.py)
    "pathway_scheduler_submitted_total": ("counter", "work items admitted"),
    "pathway_scheduler_completed_total": ("counter", "work items completed"),
    "pathway_scheduler_failed_total": ("counter", "work items failed"),
    "pathway_scheduler_shed_deadline_total": ("counter", "items shed past deadline"),
    "pathway_scheduler_shed_queue_total": ("counter", "admissions refused at max_queue"),
    "pathway_scheduler_batches_total": ("counter", "device-step batches executed"),
    "pathway_scheduler_multi_item_batches_total": ("counter", "batches with >1 item"),
    "pathway_scheduler_queue_depth": ("gauge", "current admission-queue depth"),
    "pathway_scheduler_queue_depth_max": ("gauge", "high-watermark queue depth"),
    "pathway_scheduler_batch_occupancy_max": ("gauge", "largest batch executed"),
    "pathway_scheduler_batch_occupancy_mean": ("gauge", "mean batch occupancy"),
    "pathway_scheduler_wait_ms": ("histogram", "queue wait before dispatch"),
    # unified device-tick runtime (pathway_tpu/runtime/executor.py) —
    # every series carries a qos label (interactive/llm_rerank/bulk_ingest)
    # except the tick-level families
    "pathway_runtime_submitted_total": ("counter", "work items admitted per QoS class"),
    "pathway_runtime_completed_total": ("counter", "work items completed per QoS class"),
    "pathway_runtime_failed_total": ("counter", "work items failed per QoS class"),
    "pathway_runtime_shed_deadline_total": (
        "counter",
        "items shed past deadline per QoS class",
    ),
    "pathway_runtime_admission_rejected_total": (
        "counter",
        "sheddable admissions refused at the class queue-depth target",
    ),
    "pathway_runtime_inline_total": (
        "counter",
        "re-entrant submits executed inline inside the running tick",
    ),
    "pathway_runtime_queue_depth": ("gauge", "current per-class queue depth"),
    "pathway_runtime_queue_depth_max": ("gauge", "high-watermark per-class queue depth"),
    "pathway_runtime_ticks_total": ("counter", "device ticks composed and executed"),
    "pathway_runtime_preemptions_total": (
        "counter",
        "ticks where interactive work displaced queued lower-class work",
    ),
    "pathway_runtime_wait_ms": ("histogram", "per-class queue wait before dispatch"),
    "pathway_runtime_tick_occupancy": ("histogram", "work items per device tick"),
    "pathway_runtime_tick_tokens": ("histogram", "estimated token mass per device tick"),
    "pathway_runtime_starvation_share": (
        "histogram",
        "bulk-ingest share of contended ticks (the starvation bound, observed)",
    ),
    # multi-chip serving mesh (pathway_tpu/parallel/index.py) — every
    # series carries an index label; shard_rows adds a shard label
    "pathway_mesh_devices": (
        "gauge",
        "devices the sharded KNN index's data axis spans",
    ),
    "pathway_mesh_shard_rows": (
        "gauge",
        "live rows per shard of a mesh-sharded index (row-balance observable)",
    ),
    "pathway_mesh_sharded_ticks_total": (
        "counter",
        "fused embed→search ticks answered by a mesh-sharded index",
    ),
    # circuit breakers (xpacks/llm/_breaker.py)
    "pathway_breaker_state": ("gauge", "0=closed 1=half_open 2=open"),
    "pathway_breaker_trips_total": ("counter", "closed/half_open -> open transitions"),
    "pathway_breaker_refused_total": ("counter", "calls refused while open"),
    "pathway_breaker_failures_total": ("counter", "failures recorded"),
    "pathway_breaker_successes_total": ("counter", "successes recorded"),
    # error log (internals/errors.py)
    "pathway_errors_total": ("counter", "failure-domain events per kind"),
    "pathway_errors_last_minute": ("gauge", "errors in the trailing 60 s"),
    # request tracing (internals/flight_recorder.py)
    "pathway_request_stage_ms": (
        "histogram",
        "per-request stage latency (queue_wait / embed / search / serialize / total)",
    ),
    "pathway_flight_recorder_spans_total": (
        "counter",
        "spans recorded into the in-process ring buffer",
    ),
    "pathway_trace_dropped_total": (
        "counter",
        "spans evicted from the flight-recorder ring before any read — "
        'nonzero means a "no slow spans found" answer may be a lie',
    ),
    # observability plane (pathway_tpu/observability/) — the unified HBM
    # ledger; every series carries a component label, shard optional
    "pathway_hbm_bytes": (
        "gauge",
        "device-resident bytes per registered allocation (component=, shard=)",
    ),
    "pathway_hbm_total_bytes": (
        "gauge",
        "sum of every ledger-attributed device allocation in this process",
    ),
    "pathway_hbm_unattributed_bytes": (
        "gauge",
        "device bytes_in_use minus the attributed total, emitted only while "
        "drift exceeds PATHWAY_HBM_DRIFT_FRAC (TPU reconcile)",
    ),
    # SLO engine (pathway_tpu/observability/slo.py) — endpoint label on
    # the histogram; burn gauges carry slo/objective/window labels
    "pathway_endpoint_latency_ms": (
        "histogram",
        "per-endpoint request latency with trace-id exemplars on buckets",
    ),
    "pathway_slo_burn_rate": (
        "gauge",
        "error-budget burn rate per SLO/objective/window (SRE workbook: "
        "both windows >= 14.4 means the budget is burning)",
    ),
    # end-to-end freshness (io/streaming.py read-time stamps through
    # internals/monitoring.py) — connector label
    "pathway_freshness_seconds": (
        "gauge",
        "connector read-time -> queryable lag, end to end per connector "
        "(the index-level freshness gauge is one stage of this)",
    ),
    # data freshness (internals/monitoring.py + stdlib/indexing/lowering.py)
    "pathway_index_freshness_seconds": (
        "gauge",
        "ingest -> queryable lag of the last index update, per index",
    ),
    # index quantization (pathway_tpu/ops/knn.py) — every series carries
    # an index label; dtype adds a dtype label
    "pathway_index_dtype": (
        "gauge",
        "resident storage dtype of each live KNN index (f32/bf16/int8)",
    ),
    "pathway_index_hbm_bytes": (
        "gauge",
        "resident device bytes per index (codes+scales+rescore ring when int8)",
    ),
    "pathway_index_rescore_depth": (
        "gauge",
        "stage-1 candidate funnel depth of the quantized rescore (0 = unquantized)",
    ),
    # tiered index (pathway_tpu/tiering/index.py) — every series carries
    # an index label; rows adds a tier label, migrations a direction label
    "pathway_tier_rows": (
        "gauge",
        "live rows per tier (hot = HBM-resident, cold = host-RAM) of each tiered index",
    ),
    "pathway_tier_migrations_total": (
        "counter",
        "online tier reassignments per direction (promote = cold→HBM, demote = HBM→cold)",
    ),
    "pathway_tier_probe_partitions": (
        "gauge",
        "cold partitions probed per query (the routing fan-out knob, observed config)",
    ),
    # XLA compilation (internals/flight_recorder.py, wrapped jit entry points)
    "pathway_xla_compile_total": (
        "counter",
        "XLA compilations per jit entry point (bucket_q/bucket_k pin: flat under serving)",
    ),
    # fused serving tick (ops/fused_serving.py) — per-stage device
    # dispatch counts on the serving search path; the fused megakernel's
    # ≤2-launches-per-tick pin is readable straight off the stage= split
    "pathway_serving_launches_total": (
        "counter",
        "serving-path device dispatches by stage (fused/prep/score/topk/rescore/wire)",
    ),
    # ingest plane (internals/flight_recorder.py accumulators fed by
    # models/encoder.py packed dispatch, xpacks/llm/_ingest.py pipeline,
    # stdlib/indexing/lowering.py index adds, models/tokenizer.py cache)
    "pathway_ingest_docs_total": (
        "counter",
        "documents embedded and applied to a live index",
    ),
    "pathway_embed_padding_efficiency": (
        "gauge",
        "real tokens / padded tokens across embed dispatches (1.0 = no padding waste)",
    ),
    "pathway_embed_intra_bucket_efficiency": (
        "gauge",
        "real tokens / row-layout tokens: token padding INSIDE buckets only "
        "(~0.906 packed-bucket, ~1.0 ragged)",
    ),
    "pathway_attention_impl": (
        "gauge",
        "encoders built per attention implementation (flax/fused/pallas/ragged)",
    ),
    "pathway_tokenizer_cache_hits_total": (
        "counter",
        "tokenizer LRU memoization hits per encoder (dedup-heavy live streams)",
    ),
    "pathway_tokenizer_cache_misses_total": (
        "counter",
        "tokenizer LRU memoization misses per encoder",
    ),
    # serving query cache stack (xpacks/llm/_query_cache.py) — every
    # series carries a layer label (embed / result)
    "pathway_query_cache_hits_total": (
        "counter",
        "serving-cache hits per layer (embed = encoder skipped, result = whole query skipped)",
    ),
    "pathway_query_cache_misses_total": (
        "counter",
        "serving-cache misses per layer (includes watermark-invalidated entries)",
    ),
    "pathway_query_cache_stale_served_total": (
        "counter",
        "result-cache entries served inside the stale-while-revalidate window",
    ),
    "pathway_query_cache_evictions_total": (
        "counter",
        "LRU evictions per cache layer",
    ),
    "pathway_collab_embeds_total": (
        "counter",
        "queries embedded on host CPU by the WindVE collaborative path under queue pressure",
    ),
    # paged-KV continuous-batching decode (pathway_tpu/generation/)
    "pathway_decode_live_sequences": (
        "gauge",
        "sequences currently advancing per decode tick across live DecodeSessions",
    ),
    "pathway_decode_kv_blocks": (
        "gauge",
        "paged KV pool blocks per state (used / free) — the token-budget admission signal",
    ),
    "pathway_decode_tokens_total": (
        "counter",
        "tokens generated by the paged continuous-batching decode path",
    ),
    "pathway_decode_prefill_tokens_total": (
        "counter",
        "prompt tokens prefilled into paged KV blocks (ragged packed launches)",
    ),
    "pathway_decode_shed_total": (
        "counter",
        "decode requests shed (queue-depth backpressure or deadline passed while queued)",
    ),
    "pathway_decode_retired_total": (
        "counter",
        "sequences retired (EOS or max_new_tokens reached; blocks freed unless retained)",
    ),
    "pathway_decode_prefix_hit_blocks_total": (
        "counter",
        "KV blocks adopted from the content-addressed prefix index instead of prefilled",
    ),
    "pathway_decode_shared_blocks": (
        "gauge",
        "KV blocks currently referenced by two or more sequences (refcount >= 2)",
    ),
    "pathway_decode_cow_copies_total": (
        "counter",
        "copy-on-write block duplications before a write into a shared KV block",
    ),
    "pathway_decode_draft_proposed_total": (
        "counter",
        "speculative draft tokens proposed by host-side prompt-lookup drafting",
    ),
    "pathway_decode_draft_accepted_total": (
        "counter",
        "speculative draft tokens accepted by the multi-position verify launch",
    ),
    # -- generation-plane fault containment (ISSUE 18) --
    "pathway_decode_fault_retries_total": (
        "counter",
        "transient device-launch failures retried in place (PATHWAY_DECODE_FAULT_RETRIES)",
    ),
    "pathway_decode_fault_contained_total": (
        "counter",
        "launch failures contained to their own sequences (blast-radius isolation)",
    ),
    "pathway_decode_fault_replays_total": (
        "counter",
        "sequences resurrected by replay re-prefill after a fatal pool quarantine",
    ),
    "pathway_kv_pool_rebuilds_total": (
        "counter",
        "paged-KV pools quarantined and reallocated fresh after a FATAL device error",
    ),
    # -- replicated serving fleet (fleet/router.py /status) --
    "pathway_fleet_replicas": (
        "gauge",
        "replicas known to the fleet router by state (ready/draining/detached)",
    ),
    "pathway_fleet_requests_total": (
        "counter",
        "proxied serving requests by outcome (ok = some replica answered)",
    ),
    "pathway_fleet_failovers_total": (
        "counter",
        "dispatch attempts that moved to the next replica (503 or transport error)",
    ),
    "pathway_fleet_affinity_spills_total": (
        "counter",
        "queries routed off their consistent-hash owner because it was hot",
    ),
    "pathway_fleet_epoch_restarts_total": (
        "counter",
        "replica process-epoch changes observed (restart detected; history re-verified)",
    ),
    "pathway_fleet_ingest_batches_total": (
        "counter",
        "ingest batches fanned out to the fleet under a fresh watermark",
    ),
    "pathway_fleet_ingest_watermark": (
        "gauge",
        "per-replica ingest/queryable freshness watermark (convergence probe input)",
    ),
    "pathway_fleet_autoscale_total": (
        "counter",
        "autoscale actions taken by the burn-verdict controller (spawn/drain)",
    ),
    # -- per-launch decode telemetry (generation/engine.py launch guards) —
    # every series carries a kind label (prefill / decode_step / verify)
    "pathway_decode_launch_ms": (
        "histogram",
        "device-launch wall time per guarded generation launch (kind=)",
    ),
    "pathway_decode_batch_rows": (
        "histogram",
        "sequences riding each guarded generation launch (kind=)",
    ),
    # -- telemetry federation (observability/federation.py via the fleet
    # router's /status) — replica-labeled re-exposition plus aggregates
    "pathway_fleet_aggregate_total": (
        "counter",
        "fleet-wide sum of a counter family across live replicas "
        "(family= names the source family; restart-safe, never decreases)",
    ),
    "pathway_fleet_scrapes_total": (
        "counter",
        "replica /status scrapes completed by the federation plane",
    ),
    "pathway_fleet_scrape_errors_total": (
        "counter",
        "replica /status scrapes that failed (replica unreachable or "
        "exposition unparsable)",
    ),
    "pathway_fleet_slo_burn_rate": (
        "gauge",
        "fleet-level error-budget burn rate per endpoint/window, computed "
        "from the federated per-endpoint latency histograms",
    ),
    "pathway_fleet_slo_verdict": (
        "gauge",
        "fleet-level burn verdict per endpoint (0=ok 1=warn 2=burning)",
    ),
}


def declared_metric_names() -> set[str]:
    """All sample names the registry allows: family names plus the
    histogram suffixes."""
    names: set[str] = set()
    for family, (kind, _help) in METRICS.items():
        names.add(family)
        if kind == "histogram":
            names.update(
                {f"{family}_bucket", f"{family}_sum", f"{family}_count"}
            )
    return names


def escape_label_value(value: object) -> str:
    """Escape a label value per the OpenMetrics exposition format:
    backslash, double-quote and line feed must be escaped (in that order —
    escaping ``\\`` last would corrupt the other two)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Histogram:
    """Fixed-bucket histogram with OpenMetrics rendering.

    NOT internally locked — every holder (StatsMonitor, the stage-metrics
    table in flight_recorder) already serializes observes under its own
    lock, and double-locking the hot path buys nothing.
    """

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def openmetrics_lines(self, family: str, labels: str = "") -> list[str]:
        """``_bucket``/``_sum``/``_count`` samples (no ``# TYPE`` line —
        the caller declares the family once for all label sets)."""
        sep = "," if labels else ""
        lines = []
        cum = 0
        for le, n in zip((*self.buckets, float("inf")), self.counts):
            cum += n
            le_s = "+Inf" if le == float("inf") else f"{le:g}"
            lines.append(
                f'{family}_bucket{{{labels}{sep}le="{le_s}"}} {cum}'
            )
        brace = f"{{{labels}}}" if labels else ""
        lines.append(f"{family}_sum{brace} {self.sum:.3f}")
        lines.append(f"{family}_count{brace} {self.count}")
        return lines
