"""Length-prefixed binary wire format for the exchange plane.

reference: timely's serialized channel allocators
(external/timely-dataflow/communication/src/allocator/zero_copy/) move
``Message<T: Serialize>`` frames over TCP with explicit length headers —
never Python pickle.  This module is the equivalent contract for the
host exchange plane: a self-describing, versioned binary encoding of the
engine value model (src/engine/value.rs:207 ``Value`` enum parity —
see :mod:`pathway_tpu.internals.value`), with a tagged pickle escape
hatch only for exotic UDF-produced objects.

Layout of one frame body (the transport adds a ``<Q`` total-length
prefix):

    u8   version
    u16  channel-name length | channel utf-8 bytes
    i64  timestamp
    u16  sender process id
    u32  entry count
    entries: key(u128 little) | diff(i32) | row  (row = value encoding)

Value encoding is one tag byte then a tag-specific payload; containers
nest.  Integers outside i64 use a length-prefixed big-int payload, so
arbitrary-precision Python ints survive the trip bit-exactly.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any

import numpy as np

from .value import (
    ERROR,
    PENDING,
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    Json,
    Pointer,
)

__all__ = ["encode_frame", "decode_frame", "encode_value", "decode_value"]

WIRE_VERSION = 1

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

# value tags
_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_I64 = 0x03
_T_BIGINT = 0x04
_T_F64 = 0x05
_T_STR = 0x06
_T_BYTES = 0x07
_T_POINTER = 0x08
_T_TUPLE = 0x09
_T_LIST = 0x0A
_T_DICT = 0x0B
_T_NDARRAY = 0x0C
_T_JSON = 0x0D
_T_DT_NAIVE = 0x0E
_T_DT_UTC = 0x0F
_T_DURATION = 0x10
_T_ERROR = 0x11
_T_PENDING = 0x12
_T_SET = 0x13
_T_PICKLE = 0xFF

#: the pickle escape hatch can execute code at decode time.  It is OFF by
#: default: an authenticated-but-hostile (or replayed) frame must not be
#: able to run arbitrary code.  Clusters that exchange exotic UDF values
#: opt in explicitly on every process.  Programmatic override for embed-
#: ders/tests; the env var is consulted at call time so setting it after
#: import works as the error message instructs.
_ALLOW_PICKLE = False


def _pickle_allowed() -> bool:
    return (
        _ALLOW_PICKLE
        or os.environ.get("PATHWAY_WIRE_ALLOW_PICKLE", "") == "1"
    )


_PICKLE_OFF_MSG = (
    "the wire-format pickle escape hatch is disabled (it can execute "
    "code on the receiving process); set PATHWAY_WIRE_ALLOW_PICKLE=1 on "
    "every process to exchange values outside the engine value model"
)

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1

#: per-value payload limit (u32 length fields); the frame header is u64 so
#: a batch may exceed this, but one value may not
_MAX_VALUE_BYTES = (1 << 32) - 1


def _check_len(n: int, what: str) -> int:
    if n > _MAX_VALUE_BYTES:
        raise ValueError(
            f"wire format: a single {what} of {n} bytes exceeds the 4 GiB "
            "per-value limit; split the payload across rows"
        )
    return n


def encode_value(v: Any, out: bytearray) -> None:
    """Append the tagged encoding of one value to ``out``."""
    if v is None:
        out.append(_T_NONE)
    elif v is ERROR:
        out.append(_T_ERROR)
    elif v is PENDING:
        out.append(_T_PENDING)
    elif isinstance(v, bool):
        out.append(_T_TRUE if v else _T_FALSE)
    elif isinstance(v, Pointer):  # before int: Pointer subclasses it
        out.append(_T_POINTER)
        out += v.value.to_bytes(16, "little")
    elif isinstance(v, int):
        if _I64_MIN <= v <= _I64_MAX:
            out.append(_T_I64)
            out += _I64.pack(v)
        else:
            raw = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
            out.append(_T_BIGINT)
            out += _U32.pack(len(raw))
            out += raw
    elif isinstance(v, float):
        out.append(_T_F64)
        out += _F64.pack(v)
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(_T_STR)
        out += _U32.pack(_check_len(len(raw), "string"))
        out += raw
    elif isinstance(v, bytes):
        out.append(_T_BYTES)
        out += _U32.pack(_check_len(len(v), "bytes value"))
        out += v
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        out += _U32.pack(len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, list):
        out.append(_T_LIST)
        out += _U32.pack(len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        out += _U32.pack(len(v))
        for k, item in v.items():
            encode_value(k, out)
            encode_value(item, out)
    elif isinstance(v, frozenset):
        out.append(_T_SET)
        out += _U32.pack(len(v))
        # deterministic order so identical sets encode identically
        for item in sorted(v, key=repr):
            encode_value(item, out)
    elif isinstance(v, np.ndarray):
        if v.dtype.hasobject:
            # object arrays hold pointers — tobytes() would serialize raw
            # addresses; route through the tagged pickle escape hatch
            if not _pickle_allowed():
                raise TypeError(_PICKLE_OFF_MSG)
            raw = pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
            out.append(_T_PICKLE)
            out += _U32.pack(_check_len(len(raw), "object array"))
            out += raw
            return
        data = np.ascontiguousarray(v)
        dt = str(data.dtype).encode()
        out.append(_T_NDARRAY)
        out += _U16.pack(len(dt))
        out += dt
        out.append(data.ndim)
        for d in data.shape:
            out += _U32.pack(d)
        raw = data.tobytes()
        out += _U32.pack(_check_len(len(raw), "ndarray"))
        out += raw
    elif isinstance(v, Json):
        raw = v.to_string().encode("utf-8")
        out.append(_T_JSON)
        out += _U32.pack(_check_len(len(raw), "json value"))
        out += raw
    elif isinstance(v, DateTimeNaive):
        out.append(_T_DT_NAIVE)
        out += v.ns.to_bytes(16, "little", signed=True)
    elif isinstance(v, DateTimeUtc):
        out.append(_T_DT_UTC)
        out += v.ns.to_bytes(16, "little", signed=True)
    elif isinstance(v, Duration):
        out.append(_T_DURATION)
        out += v.ns.to_bytes(16, "little", signed=True)
    elif isinstance(v, np.integer):
        encode_value(int(v), out)
    elif isinstance(v, np.floating):
        encode_value(float(v), out)
    elif isinstance(v, np.bool_):
        encode_value(bool(v), out)
    else:
        # exotic UDF output — tagged escape hatch, still length-prefixed
        if not _pickle_allowed():
            raise TypeError(_PICKLE_OFF_MSG)
        raw = pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
        out.append(_T_PICKLE)
        out += _U32.pack(_check_len(len(raw), "pickled value"))
        out += raw


def decode_value(buf: memoryview, pos: int) -> tuple[Any, int]:
    """Decode one value at ``pos``; returns (value, next_pos)."""
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_I64:
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_BIGINT:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return int.from_bytes(buf[pos : pos + n], "little", signed=True), pos + n
    if tag == _T_F64:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_STR:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return str(buf[pos : pos + n], "utf-8"), pos + n
    if tag == _T_BYTES:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return bytes(buf[pos : pos + n]), pos + n
    if tag == _T_POINTER:
        return Pointer(int.from_bytes(buf[pos : pos + 16], "little")), pos + 16
    if tag in (_T_TUPLE, _T_LIST, _T_SET):
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos)
            items.append(item)
        if tag == _T_TUPLE:
            return tuple(items), pos
        if tag == _T_SET:
            return frozenset(items), pos
        return items, pos
    if tag == _T_DICT:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = decode_value(buf, pos)
            v, pos = decode_value(buf, pos)
            d[k] = v
        return d, pos
    if tag == _T_NDARRAY:
        (dt_len,) = _U16.unpack_from(buf, pos)
        pos += 2
        dtype = np.dtype(str(buf[pos : pos + dt_len], "ascii"))
        pos += dt_len
        ndim = buf[pos]
        pos += 1
        shape = []
        for _ in range(ndim):
            (d,) = _U32.unpack_from(buf, pos)
            shape.append(d)
            pos += 4
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        arr = np.frombuffer(buf[pos : pos + n], dtype=dtype).reshape(shape).copy()
        return arr, pos + n
    if tag == _T_JSON:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return Json.parse(str(buf[pos : pos + n], "utf-8")), pos + n
    if tag == _T_DT_NAIVE:
        return (
            DateTimeNaive(
                ns=int.from_bytes(buf[pos : pos + 16], "little", signed=True)
            ),
            pos + 16,
        )
    if tag == _T_DT_UTC:
        return (
            DateTimeUtc(
                ns=int.from_bytes(buf[pos : pos + 16], "little", signed=True)
            ),
            pos + 16,
        )
    if tag == _T_DURATION:
        return (
            Duration(int.from_bytes(buf[pos : pos + 16], "little", signed=True)),
            pos + 16,
        )
    if tag == _T_ERROR:
        return ERROR, pos
    if tag == _T_PENDING:
        return PENDING, pos
    if tag == _T_PICKLE:
        if not _pickle_allowed():
            raise ValueError(_PICKLE_OFF_MSG)
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        return pickle.loads(buf[pos : pos + n]), pos + n
    raise ValueError(f"unknown wire tag 0x{tag:02x} at offset {pos - 1}")


def encode_frame(
    channel: str, time: int, sender: int, entries: list,
    is_entries: bool = True,
) -> bytes:
    """Encode one exchange batch (without the transport length prefix).

    The caller states what the items are: ``is_entries=True`` for engine
    entries ``(Pointer, row, diff)`` — the data plane — or
    ``is_entries=False`` for arbitrary control values (the driver's
    barriers exchange bare flags on ``__ctl__`` channels).  The explicit
    flag (rather than per-item shape sniffing) guarantees a control value
    that happens to look like an entry keeps its shape on the far side.
    """
    out = bytearray()
    out.append(WIRE_VERSION)
    ch = channel.encode("utf-8")
    out += _U16.pack(len(ch))
    out += ch
    out += _I64.pack(time)
    out += _U16.pack(sender)
    out += _U32.pack(len(entries))
    for item in entries:
        if is_entries:
            key, row, diff = item
            out.append(0x01)
            out += key.value.to_bytes(16, "little")
            out += _I32.pack(diff)
            encode_value(row, out)
        else:
            out.append(0x00)
            encode_value(item, out)
    return bytes(out)


def decode_frame(body: bytes | memoryview) -> tuple[str, int, int, list[tuple]]:
    """Decode a frame body into (channel, time, sender, entries)."""
    buf = memoryview(body)
    version = buf[0]
    if version != WIRE_VERSION:
        raise ValueError(f"wire version mismatch: got {version}, expect {WIRE_VERSION}")
    pos = 1
    (ch_len,) = _U16.unpack_from(buf, pos)
    pos += 2
    channel = str(buf[pos : pos + ch_len], "utf-8")
    pos += ch_len
    (time,) = _I64.unpack_from(buf, pos)
    pos += 8
    (sender,) = _U16.unpack_from(buf, pos)
    pos += 2
    (count,) = _U32.unpack_from(buf, pos)
    pos += 4
    entries: list = []
    for _ in range(count):
        marker = buf[pos]
        pos += 1
        if marker == 0x01:
            key = Pointer(int.from_bytes(buf[pos : pos + 16], "little"))
            pos += 16
            (diff,) = _I32.unpack_from(buf, pos)
            pos += 4
            row, pos = decode_value(buf, pos)
            entries.append((key, row, diff))
        else:
            item, pos = decode_value(buf, pos)
            entries.append(item)
    return channel, time, sender, entries
