from .date_time import DateTimeNamespace
from .string import StringNamespace
from .numerical import NumericalNamespace

__all__ = ["DateTimeNamespace", "StringNamespace", "NumericalNamespace"]
