"""``col.num.*`` namespace (reference: python/pathway/internals/expressions/numerical.py)."""

from __future__ import annotations

import math

from .. import dtype as dt
from ..expression import ColumnExpression, MethodCallExpression, smart_wrap
from ..value import ERROR


def _m(name, fun, result, *args, propagate_none=True):
    return MethodCallExpression(f"num.{name}", fun, result, *args, propagate_none=propagate_none)


class NumericalNamespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def abs(self):
        def res(arg_dtypes):
            inner = dt.unoptionalize(arg_dtypes[0])
            return inner if inner in (dt.INT, dt.FLOAT) else dt.FLOAT

        return _m("abs", abs, res, self._expr)

    def round(self, decimals=0):
        def res(arg_dtypes):
            return dt.unoptionalize(arg_dtypes[0])

        return _m("round", lambda v, d: round(v, d), res, self._expr, smart_wrap(decimals))

    def fill_na(self, default_value):
        def impl(v, d):
            if v is None or v is ERROR:
                return d
            if isinstance(v, float) and math.isnan(v):
                return d
            return v

        def res(arg_dtypes):
            return dt.types_lcm(dt.unoptionalize(arg_dtypes[0]), arg_dtypes[1])

        return MethodCallExpression(
            "num.fill_na", impl, res, self._expr, smart_wrap(default_value), propagate_none=False
        )
