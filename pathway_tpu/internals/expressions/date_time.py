"""``col.dt.*`` namespace (reference: python/pathway/internals/expressions/date_time.py, 1613 LoC)."""

from __future__ import annotations

from .. import dtype as dt
from ..expression import ColumnExpression, MethodCallExpression, smart_wrap
from ..value import DateTimeNaive, DateTimeUtc, Duration


def _m(name, fun, result, *args, propagate_none=True):
    return MethodCallExpression(f"dt.{name}", fun, result, *args, propagate_none=propagate_none)


def _dt_or_dur_same(arg_dtypes):
    return dt.unoptionalize(arg_dtypes[0])


class DateTimeNamespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    # component accessors
    def year(self):
        return _m("year", lambda v: v.year(), dt.INT, self._expr)

    def month(self):
        return _m("month", lambda v: v.month(), dt.INT, self._expr)

    def day(self):
        return _m("day", lambda v: v.day(), dt.INT, self._expr)

    def hour(self):
        return _m("hour", lambda v: v.hour(), dt.INT, self._expr)

    def minute(self):
        return _m("minute", lambda v: v.minute(), dt.INT, self._expr)

    def second(self):
        return _m("second", lambda v: v.second(), dt.INT, self._expr)

    def millisecond(self):
        return _m("millisecond", lambda v: v.millisecond(), dt.INT, self._expr)

    def microsecond(self):
        return _m("microsecond", lambda v: v.microsecond(), dt.INT, self._expr)

    def nanosecond(self):
        return _m("nanosecond", lambda v: v.nanosecond(), dt.INT, self._expr)

    def timestamp(self, unit: str = "ns"):
        return _m(
            "timestamp", lambda v, u: v.timestamp(u), dt.FLOAT, self._expr, smart_wrap(unit)
        )

    def strftime(self, fmt: str):
        return _m("strftime", lambda v, f: v.strftime(f), dt.STR, self._expr, smart_wrap(fmt))

    def strptime(self, fmt: str | None = None, contains_timezone: bool | None = None):
        tz = contains_timezone
        if tz is None:
            tz = fmt is not None and ("%z" in fmt or "%Z" in fmt)

        def impl(v, f):
            cls = DateTimeUtc if tz else DateTimeNaive
            return cls(v, fmt=f)

        return _m(
            "strptime",
            impl,
            dt.DATE_TIME_UTC if tz else dt.DATE_TIME_NAIVE,
            self._expr,
            smart_wrap(fmt),
        )

    def to_naive(self, timezone: str = "UTC"):
        def impl(v):
            return DateTimeNaive(ns=v.ns)

        return _m("to_naive", impl, dt.DATE_TIME_NAIVE, self._expr)

    def to_utc(self, from_timezone: str = "UTC"):
        def impl(v):
            return DateTimeUtc(ns=v.ns)

        return _m("to_utc", impl, dt.DATE_TIME_UTC, self._expr)

    def from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]

        def impl(v):
            return DateTimeNaive(ns=int(v * mult))

        return _m("from_timestamp", impl, dt.DATE_TIME_NAIVE, self._expr)

    def utc_from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]

        def impl(v):
            return DateTimeUtc(ns=int(v * mult))

        return _m("utc_from_timestamp", impl, dt.DATE_TIME_UTC, self._expr)

    def round(self, duration):
        def impl(v, d):
            d_ns = d.ns if isinstance(d, Duration) else int(d)
            half = d_ns // 2
            rounded = ((v.ns + half) // d_ns) * d_ns
            return type(v)(ns=rounded)

        return _m("round", impl, _dt_or_dur_same, self._expr, smart_wrap(duration))

    def floor(self, duration):
        def impl(v, d):
            d_ns = d.ns if isinstance(d, Duration) else int(d)
            return type(v)(ns=(v.ns // d_ns) * d_ns)

        return _m("floor", impl, _dt_or_dur_same, self._expr, smart_wrap(duration))

    # duration accessors
    def nanoseconds(self):
        return _m("nanoseconds", lambda v: v.nanoseconds(), dt.INT, self._expr)

    def microseconds(self):
        return _m("microseconds", lambda v: v.microseconds(), dt.INT, self._expr)

    def milliseconds(self):
        return _m("milliseconds", lambda v: v.milliseconds(), dt.INT, self._expr)

    def seconds(self):
        return _m("seconds", lambda v: v.seconds(), dt.INT, self._expr)

    def minutes(self):
        return _m("minutes", lambda v: v.minutes(), dt.INT, self._expr)

    def hours(self):
        return _m("hours", lambda v: v.hours(), dt.INT, self._expr)

    def days(self):
        return _m("days", lambda v: v.days(), dt.INT, self._expr)

    def weeks(self):
        return _m("weeks", lambda v: v.weeks(), dt.INT, self._expr)
