"""``col.dt.*`` namespace (reference: python/pathway/internals/expressions/date_time.py, 1613 LoC)."""

from __future__ import annotations

import datetime as _datetime
import functools

from .. import dtype as dt
from ..expression import ColumnExpression, MethodCallExpression, smart_wrap
from ..value import DateTimeNaive, DateTimeUtc, Duration

_EPOCH = _datetime.datetime(1970, 1, 1)
_UTC = _datetime.timezone.utc


def _m(name, fun, result, *args, propagate_none=True):
    return MethodCallExpression(f"dt.{name}", fun, result, *args, propagate_none=propagate_none)


def _dt_or_dur_same(arg_dtypes):
    return dt.unoptionalize(arg_dtypes[0])


@functools.lru_cache(maxsize=None)
def _zone(name: str):
    from zoneinfo import ZoneInfo

    return ZoneInfo(name)


def _utc_ns_from_wall(ns: int, tz_name: str) -> int:
    """Wall-clock ns in ``tz_name`` → UTC ns, with the reference's DST
    semantics (date_time.py:660): a nonexistent wall time maps to the
    first existing instant after it (the transition), an ambiguous one to
    the LATER moment (fold=1)."""
    if tz_name == "UTC":
        return ns
    zone = _zone(tz_name)
    sec, rem = divmod(ns, 1_000_000_000)
    wall = _EPOCH + _datetime.timedelta(seconds=sec)
    d1 = wall.replace(tzinfo=zone, fold=1)
    utc1 = d1.astimezone(_UTC)
    if utc1.astimezone(zone).replace(tzinfo=None) == wall:
        utc = utc1
    else:
        # nonexistent (spring-forward gap): the transition instant lies
        # between the two fold candidates — binary search for the first
        # UTC second whose zone offset equals the post-transition offset.
        # Rare path (one hour per year per zone), so per-value search is
        # fine; offsets are whole seconds.
        utc0 = wall.replace(tzinfo=zone, fold=0).astimezone(_UTC)
        lo, hi = sorted((utc0, utc1))
        target_off = hi.astimezone(zone).utcoffset()
        lo_s = int((lo - _EPOCH.replace(tzinfo=_UTC)).total_seconds())
        hi_s = int((hi - _EPOCH.replace(tzinfo=_UTC)).total_seconds())
        while lo_s < hi_s:
            mid = (lo_s + hi_s) // 2
            t = _EPOCH.replace(tzinfo=_UTC) + _datetime.timedelta(seconds=mid)
            if t.astimezone(zone).utcoffset() == target_off:
                hi_s = mid
            else:
                lo_s = mid + 1
        utc = _EPOCH.replace(tzinfo=_UTC) + _datetime.timedelta(seconds=lo_s)
        rem = 0  # clamped to the transition: sub-second remainder is gone
    delta = utc.replace(tzinfo=None) - _EPOCH
    return (delta.days * 86_400 + delta.seconds) * 1_000_000_000 + delta.microseconds * 1_000 + rem


def _wrap_duration(d):
    """Accept the reference's duration spellings (Duration, pd.Timedelta,
    datetime.timedelta, or a column expression) as an expression."""
    if isinstance(d, ColumnExpression):
        return d
    if isinstance(d, Duration):
        return smart_wrap(d)
    if hasattr(d, "value") and hasattr(d, "total_seconds"):  # pd.Timedelta
        return smart_wrap(Duration(int(d.value)))
    if isinstance(d, _datetime.timedelta):
        return smart_wrap(
            Duration(
                (d.days * 86_400 + d.seconds) * 1_000_000_000
                + d.microseconds * 1_000
            )
        )
    return smart_wrap(d)


def _wall_ns_from_utc(ns: int, tz_name: str) -> int:
    """UTC ns → wall-clock ns in ``tz_name`` (reference: date_time.py:750
    ``to_naive_in_timezone``).  Always well-defined."""
    if tz_name == "UTC":
        return ns
    zone = _zone(tz_name)
    sec, rem = divmod(ns, 1_000_000_000)
    utc = _EPOCH.replace(tzinfo=_UTC) + _datetime.timedelta(seconds=sec)
    wall = utc.astimezone(zone).replace(tzinfo=None)
    delta = wall - _EPOCH
    return (delta.days * 86_400 + delta.seconds) * 1_000_000_000 + delta.microseconds * 1_000 + rem


class DateTimeNamespace:
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    # component accessors
    def year(self):
        return _m("year", lambda v: v.year(), dt.INT, self._expr)

    def month(self):
        return _m("month", lambda v: v.month(), dt.INT, self._expr)

    def day(self):
        return _m("day", lambda v: v.day(), dt.INT, self._expr)

    def hour(self):
        return _m("hour", lambda v: v.hour(), dt.INT, self._expr)

    def minute(self):
        return _m("minute", lambda v: v.minute(), dt.INT, self._expr)

    def second(self):
        return _m("second", lambda v: v.second(), dt.INT, self._expr)

    def millisecond(self):
        return _m("millisecond", lambda v: v.millisecond(), dt.INT, self._expr)

    def microsecond(self):
        return _m("microsecond", lambda v: v.microsecond(), dt.INT, self._expr)

    def nanosecond(self):
        return _m("nanosecond", lambda v: v.nanosecond(), dt.INT, self._expr)

    def timestamp(self, unit: str = "ns"):
        return _m(
            "timestamp", lambda v, u: v.timestamp(u), dt.FLOAT, self._expr, smart_wrap(unit)
        )

    def strftime(self, fmt: str):
        return _m("strftime", lambda v, f: v.strftime(f), dt.STR, self._expr, smart_wrap(fmt))

    def strptime(self, fmt: str | None = None, contains_timezone: bool | None = None):
        tz = contains_timezone
        if tz is None:
            tz = fmt is not None and ("%z" in fmt or "%Z" in fmt)

        def impl(v, f):
            cls = DateTimeUtc if tz else DateTimeNaive
            return cls(v, fmt=f)

        return _m(
            "strptime",
            impl,
            dt.DATE_TIME_UTC if tz else dt.DATE_TIME_NAIVE,
            self._expr,
            smart_wrap(fmt),
        )

    def to_naive(self, timezone: str = "UTC"):
        def impl(v, tz):
            return DateTimeNaive(ns=_wall_ns_from_utc(v.ns, tz))

        return _m(
            "to_naive", impl, dt.DATE_TIME_NAIVE, self._expr, smart_wrap(timezone)
        )

    def to_naive_in_timezone(self, timezone):
        """DateTimeUtc → wall clock in ``timezone``
        (reference: date_time.py:750)."""
        return self.to_naive(timezone)

    def to_utc(self, from_timezone: str = "UTC"):
        def impl(v, tz):
            return DateTimeUtc(ns=_utc_ns_from_wall(v.ns, tz))

        return _m(
            "to_utc", impl, dt.DATE_TIME_UTC, self._expr, smart_wrap(from_timezone)
        )

    def weekday(self):
        """0 = Monday … 6 = Sunday (reference: date_time.py:1567)."""
        return _m("weekday", lambda v: v.weekday(), dt.INT, self._expr)

    def add_duration_in_timezone(self, duration, timezone):
        """DST-aware wall-clock addition (reference: date_time.py:840 —
        composed exactly the same way: via UTC and back)."""
        return (
            self.to_utc(timezone) + _wrap_duration(duration)
        ).dt.to_naive_in_timezone(timezone)

    def subtract_duration_in_timezone(self, duration, timezone):
        """DST-aware wall-clock subtraction (reference: date_time.py:895)."""
        return (
            self.to_utc(timezone) - _wrap_duration(duration)
        ).dt.to_naive_in_timezone(timezone)

    def subtract_date_time_in_timezone(self, date_time, timezone):
        """Difference of two wall-clock DateTimeNaives measured in real
        elapsed time (reference: date_time.py:928)."""
        other = (
            date_time
            if isinstance(date_time, ColumnExpression)
            else smart_wrap(date_time)
        )
        return self.to_utc(timezone) - other.dt.to_utc(timezone)

    def from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]

        def impl(v):
            return DateTimeNaive(ns=int(v * mult))

        return _m("from_timestamp", impl, dt.DATE_TIME_NAIVE, self._expr)

    def utc_from_timestamp(self, unit: str = "s"):
        mult = {"s": 1_000_000_000, "ms": 1_000_000, "us": 1_000, "ns": 1}[unit]

        def impl(v):
            return DateTimeUtc(ns=int(v * mult))

        return _m("utc_from_timestamp", impl, dt.DATE_TIME_UTC, self._expr)

    def round(self, duration):
        def impl(v, d):
            d_ns = d.ns if isinstance(d, Duration) else int(d)
            half = d_ns // 2
            rounded = ((v.ns + half) // d_ns) * d_ns
            return type(v)(ns=rounded)

        return _m("round", impl, _dt_or_dur_same, self._expr, smart_wrap(duration))

    def floor(self, duration):
        def impl(v, d):
            d_ns = d.ns if isinstance(d, Duration) else int(d)
            return type(v)(ns=(v.ns // d_ns) * d_ns)

        return _m("floor", impl, _dt_or_dur_same, self._expr, smart_wrap(duration))

    # duration accessors
    def nanoseconds(self):
        return _m("nanoseconds", lambda v: v.nanoseconds(), dt.INT, self._expr)

    def microseconds(self):
        return _m("microseconds", lambda v: v.microseconds(), dt.INT, self._expr)

    def milliseconds(self):
        return _m("milliseconds", lambda v: v.milliseconds(), dt.INT, self._expr)

    def seconds(self):
        return _m("seconds", lambda v: v.seconds(), dt.INT, self._expr)

    def minutes(self):
        return _m("minutes", lambda v: v.minutes(), dt.INT, self._expr)

    def hours(self):
        return _m("hours", lambda v: v.hours(), dt.INT, self._expr)

    def days(self):
        return _m("days", lambda v: v.days(), dt.INT, self._expr)

    def weeks(self):
        return _m("weeks", lambda v: v.weeks(), dt.INT, self._expr)
