"""``col.str.*`` namespace (reference: python/pathway/internals/expressions/string.py, 931 LoC)."""

from __future__ import annotations

from typing import Any

from .. import dtype as dt
from ..expression import ColumnExpression, MethodCallExpression, smart_wrap


def _m(name, fun, result, *args, propagate_none=True):
    return MethodCallExpression(f"str.{name}", fun, result, *args, propagate_none=propagate_none)


def to_string_expr(expr: ColumnExpression) -> ColumnExpression:
    def impl(v):
        if isinstance(v, bool):
            return "True" if v else "False"
        if isinstance(v, float) and v.is_integer():
            return str(v)
        return str(v)

    return _m("to_string", impl, dt.STR, expr)


class StringNamespace:
    """String methods over STR columns."""

    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def lower(self):
        return _m("lower", lambda s: s.lower(), dt.STR, self._expr)

    def upper(self):
        return _m("upper", lambda s: s.upper(), dt.STR, self._expr)

    def reversed(self):
        return _m("reversed", lambda s: s[::-1], dt.STR, self._expr)

    def strip(self, chars: str | None = None):
        return _m("strip", lambda s, c: s.strip(c), dt.STR, self._expr, smart_wrap(chars))

    def rstrip(self, chars: str | None = None):
        return _m("rstrip", lambda s, c: s.rstrip(c), dt.STR, self._expr, smart_wrap(chars))

    def lstrip(self, chars: str | None = None):
        return _m("lstrip", lambda s, c: s.lstrip(c), dt.STR, self._expr, smart_wrap(chars))

    def len(self):
        return _m("len", lambda s: len(s), dt.INT, self._expr)

    def count(self, sub, start=None, end=None):
        return _m(
            "count",
            lambda s, su, st, e: s.count(su, st, e),
            dt.INT,
            self._expr,
            smart_wrap(sub),
            smart_wrap(start),
            smart_wrap(end),
        )

    def find(self, sub, start=None, end=None):
        return _m(
            "find",
            lambda s, su, st, e: s.find(su, st, e),
            dt.INT,
            self._expr,
            smart_wrap(sub),
            smart_wrap(start),
            smart_wrap(end),
        )

    def rfind(self, sub, start=None, end=None):
        return _m(
            "rfind",
            lambda s, su, st, e: s.rfind(su, st, e),
            dt.INT,
            self._expr,
            smart_wrap(sub),
            smart_wrap(start),
            smart_wrap(end),
        )

    def startswith(self, prefix):
        return _m("startswith", lambda s, p: s.startswith(p), dt.BOOL, self._expr, smart_wrap(prefix))

    def endswith(self, suffix):
        return _m("endswith", lambda s, p: s.endswith(p), dt.BOOL, self._expr, smart_wrap(suffix))

    def removeprefix(self, prefix):
        """reference: string.py:634 — drop ``prefix`` if present, else
        return the string unchanged (Python ``str.removeprefix``)."""
        return _m(
            "removeprefix",
            lambda s, p: s.removeprefix(p),
            dt.STR,
            self._expr,
            smart_wrap(prefix),
        )

    def removesuffix(self, suffix):
        """reference: string.py:693 (Python ``str.removesuffix``)."""
        return _m(
            "removesuffix",
            lambda s, p: s.removesuffix(p),
            dt.STR,
            self._expr,
            smart_wrap(suffix),
        )

    def swapcase(self):
        return _m("swapcase", lambda s: s.swapcase(), dt.STR, self._expr)

    def title(self):
        return _m("title", lambda s: s.title(), dt.STR, self._expr)

    def replace(self, old, new, count: int = -1):
        return _m(
            "replace",
            lambda s, o, n, c: s.replace(o, n, c),
            dt.STR,
            self._expr,
            smart_wrap(old),
            smart_wrap(new),
            smart_wrap(count),
        )

    def split(self, sep=None, maxsplit: int = -1):
        return _m(
            "split",
            lambda s, se, m: tuple(s.split(se, m)),
            dt.List(dt.STR),
            self._expr,
            smart_wrap(sep),
            smart_wrap(maxsplit),
        )

    def slice(self, start: int, end: int):
        return _m(
            "slice",
            lambda s, a, b: s[a:b],
            dt.STR,
            self._expr,
            smart_wrap(start),
            smart_wrap(end),
        )

    def parse_int(self, optional: bool = False):
        def impl(s):
            try:
                return int(s)
            except (TypeError, ValueError):
                if optional:
                    return None
                raise

        res = dt.Optional(dt.INT) if optional else dt.INT
        return _m("parse_int", impl, res, self._expr)

    def parse_float(self, optional: bool = False):
        def impl(s):
            try:
                return float(s)
            except (TypeError, ValueError):
                if optional:
                    return None
                raise

        res = dt.Optional(dt.FLOAT) if optional else dt.FLOAT
        return _m("parse_float", impl, res, self._expr)

    def parse_bool(
        self,
        true_values=("on", "true", "yes", "1"),
        false_values=("off", "false", "no", "0"),
        optional: bool = False,
    ):
        def impl(s):
            low = s.lower()
            if low in true_values:
                return True
            if low in false_values:
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        res = dt.Optional(dt.BOOL) if optional else dt.BOOL
        return _m("parse_bool", impl, res, self._expr)
