"""Pallas flash attention over a PACKED RAGGED batch (one launch per tick).

The bucketed dispatch (models/encoder.py) pads every row to a
(batch_bucket, seq_bucket) shape and pays one kernel launch per bucket —
0.906 padding efficiency on the mixed ingest corpus, and a launch count
that grows with length heterogeneity.  This module is the TPU-native fix
from "Ragged Paged Attention" (PAPERS.md): rows are CONCATENATED along
one token axis (``cu_seqlens``/segment ids mark the boundaries), the
whole tick is ONE kernel launch, and only the tail block's alignment is
padding (~1.0 efficiency).

Kernel design (see /opt/skills/guides/pallas_guide.md):

* grid = (heads, q_blocks) — the ragged layout has no batch axis left to
  tile, so programs flatten over head x token-block; each program owns a
  ``[block_q, head_dim]`` query tile and streams kv blocks through the
  MXU with an f32 online softmax (bf16 in / f32 accumulate).
* **block-aligned ragged masks**: rows never attend across segment
  boundaries (``seg_q == seg_k`` elementwise inside a block), and blocks
  wholly outside the q tile's row span are SKIPPED, not masked — the per
  q-block kv range rides in as a scalar-prefetch ``[q_blocks, 2]`` array
  (``ragged_bounds``, host-computed from cu_seqlens) so the fori_loop
  trip count is data-dependent.  Cross-row attention is structurally
  impossible; the wasted compute is only the partial blocks at segment
  boundaries.
* K/V live whole in VMEM per head (encoder geometry: T<=8192, head_dim
  <=128 -> <=4 MB), so no manual DMA pipeline is needed; the MXU sees
  back-to-back [block_q, dh] x [dh, block_k] and [block_q, block_k] x
  [block_k, dh] matmuls.

Off-TPU the DEFAULT is an XLA reference (``mode="reference"``): scatter
the packed tokens to a dense ``[rows, seq_bucket]`` layout, run the
exact masked softmax there, gather back — same numerics as the flax
golden path, and the per-token 96% of the network still runs unpadded on
the ragged axis.  ``PATHWAY_RAGGED_KERNEL=pallas`` forces the Pallas
kernel (interpret mode off-TPU) so tier-1 tests exercise the real kernel
on the CPU mesh.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = [
    "ragged_attention",
    "ragged_block",
    "ragged_bounds",
    "validate_attention_geometry",
]

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)

#: kernel tile along the packed token axis (q and kv); token buckets are
#: multiples of this (or one of the small sub-block buckets below it)
TOKEN_BLOCK = 128

#: VMEM guard: whole-K/V-per-head residency is the kernel's design point
#: (encoder sequences are short); past this the kernel would need an HBM
#: streaming loop it does not have
MAX_PACKED_TOKENS = 8192


def ragged_block(total_tokens: int) -> int:
    """Kernel block size for a packed launch: TOKEN_BLOCK, except a
    launch smaller than one block runs at its own (bucketed) size — a
    1-row tick of 5 tokens must not pad to a 128-token block."""
    return TOKEN_BLOCK if total_tokens >= TOKEN_BLOCK else total_tokens


def validate_attention_geometry(head_dim: int, sm_scale, *, knob: str) -> None:
    """Up-front geometry check shared by the dense and ragged Pallas
    kernels.  Mosaic tiles the minor dimension in 128-wide lanes; a
    head_dim that neither divides nor is a multiple of the lane tile
    fails deep inside lowering with an opaque error — refuse here and
    name the knob that selects a working implementation instead."""
    if head_dim <= 0 or (128 % head_dim != 0 and head_dim % 128 != 0):
        raise ValueError(
            f"{knob} requires head_dim to divide (or be a multiple of) the "
            f"128-lane MXU tile; got head_dim={head_dim}.  Use "
            "attention_impl='fused' (PATHWAY_ATTENTION_IMPL=fused) for "
            "this geometry."
        )
    if sm_scale is not None and (
        not math.isfinite(sm_scale) or sm_scale <= 0.0
    ):
        raise ValueError(
            f"{knob}: sm_scale must be a positive finite float, got "
            f"{sm_scale!r}.  Callers that already applied the softmax "
            "scale to the query must pass pre_scaled=True instead of a "
            "second scale."
        )


def kernel_mode() -> str:
    """``PATHWAY_RAGGED_KERNEL``: ``auto`` (Pallas compiled on TPU, XLA
    reference elsewhere), ``pallas`` (force the kernel; interpret mode
    off-TPU — slow but exact, how tier-1 exercises it on CPU), or
    ``reference`` (force the XLA path everywhere)."""
    raw = os.environ.get("PATHWAY_RAGGED_KERNEL", "auto").strip().lower()
    if raw in ("auto", "pallas", "reference"):
        return raw
    import warnings

    warnings.warn(
        f"PATHWAY_RAGGED_KERNEL={raw!r} is not one of auto/pallas/reference"
        " — using auto",
        stacklevel=2,
    )
    return "auto"


def ragged_bounds(cu_seqlens, total_tokens: int, block: int) -> np.ndarray:
    """Per-q-block kv BLOCK range ``[lo, hi)`` for the packed layout —
    the host half of the block-aligned ragged mask.

    ``cu_seqlens``: int array ``[rows+1]`` of cumulative row lengths
    (``cu[0] == 0``, ``cu[-1] == real tokens``).  ``total_tokens`` is the
    bucket-padded launch length (a multiple of ``block``).  Blocks whose
    q tokens are all padding get ``lo == hi == 0`` (the kernel skips them
    entirely)."""
    cu = np.asarray(cu_seqlens, dtype=np.int64)
    if total_tokens % block:
        raise ValueError(
            f"total_tokens={total_tokens} is not a multiple of block={block}"
        )
    n_blocks = total_tokens // block
    t_real = int(cu[-1])
    bounds = np.zeros((n_blocks, 2), np.int32)
    for i in range(n_blocks):
        q0 = i * block
        if q0 >= t_real:
            continue  # pure pad tail: zero-trip loop
        q1 = min((i + 1) * block, t_real)
        first = int(np.searchsorted(cu, q0, side="right")) - 1
        last = int(np.searchsorted(cu, q1 - 1, side="right")) - 1
        bounds[i, 0] = cu[first] // block
        bounds[i, 1] = -(-int(cu[last + 1]) // block)
    return bounds


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _ragged_kernel(
    bounds_ref,  # scalar-prefetch [q_blocks, 2] (SMEM)
    q_ref,  # [1, block_q, dh]
    k_ref,  # [1, T, dh] (whole kv for this head)
    v_ref,  # [1, T, dh]
    seg_ref,  # [1, T] int32 segment ids (pads = num_rows)
    pos_ref,  # [1, T] int32 position-within-row (causal masking)
    o_ref,  # [1, block_q, dh]
    *,
    block_q: int,
    block_k: int,
    sm_scale: float,
    causal: bool,
):
    i = pl.program_id(1)
    lo = bounds_ref[i, 0]
    hi = bounds_ref[i, 1]
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, dh]
    seg_q = seg_ref[0, pl.ds(i * block_q, block_q)]  # [bq]
    pos_q = pos_ref[0, pl.ds(i * block_q, block_q)]  # [bq]

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        seg_k = seg_ref[0, pl.ds(j * block_k, block_k)]
        valid = seg_q[:, None] == seg_k[None, :]
        if causal:
            # decoder prefill: a token attends only to its own row's
            # PREFIX (pos_q >= pos_k); the block-skip bounds stay the
            # bidirectional row bounds — future blocks mask, not skip
            pos_k = pos_ref[0, pl.ds(j * block_k, block_k)]
            valid &= pos_q[:, None] >= pos_k[None, :]
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # masked entries must contribute 0 even when a row has seen no
        # valid key yet (m_new still _NEG_INF -> exp(s - m_new) == 1)
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot(
            p, vb, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, a0))
    # pad-tail blocks (zero-trip) and all-pad rows divide 0/eps -> 0
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block", "sm_scale", "interpret", "causal")
)
def _ragged_pallas(q, k, v, seg, pos, bounds, block, sm_scale, interpret,
                   causal=False):
    # layout: [T, h, dh] -> [h, T, dh]; one program per (head, q block)
    total, heads, dh = q.shape
    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    seg2 = seg.astype(jnp.int32)[None, :]  # [1, T]
    if pos is None:
        pos = jnp.zeros((total,), jnp.int32)
    pos2 = pos.astype(jnp.int32)[None, :]  # [1, T]
    n_blocks = total // block
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(heads, n_blocks),
        in_specs=[
            pl.BlockSpec((1, block, dh), lambda h, i, b: (h, i, 0)),
            pl.BlockSpec((1, total, dh), lambda h, i, b: (h, 0, 0)),
            pl.BlockSpec((1, total, dh), lambda h, i, b: (h, 0, 0)),
            pl.BlockSpec((1, total), lambda h, i, b: (0, 0)),
            pl.BlockSpec((1, total), lambda h, i, b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, dh), lambda h, i, b: (h, i, 0)),
    )
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel,
            block_q=block,
            block_k=block,
            sm_scale=sm_scale,
            causal=causal,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((heads, total, dh), q.dtype),
        cost_estimate=pl.CostEstimate(
            # upper bound: a fully dense launch; the ragged bounds make
            # the realized cost ~(mean row len / T) of this
            flops=4 * heads * total * total * dh,
            bytes_accessed=3 * heads * total * dh * q.dtype.itemsize
            + heads * total * dh * q.dtype.itemsize,
            transcendentals=heads * total * total,
        ),
        interpret=interpret,
    )(bounds, qh, kh, vh, seg2, pos2)
    return jnp.transpose(out, (1, 0, 2))


# ---------------------------------------------------------------------------
# XLA reference (off-TPU default): dense-unpack -> exact softmax -> repack
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("num_rows", "dense_s", "sm_scale", "causal")
)
def _ragged_reference(q, k, v, seg, pos, starts, num_rows, dense_s, sm_scale,
                      causal=False):
    """Gather the packed tokens into the bucketed dense layout
    ``[rows, seq_bucket]`` the legacy dispatch uses, run the flax-exact
    masked softmax there, gather back to the packed axis.  GATHERS, not
    scatters: XLA-CPU lowers scatter row-serially, which erased the
    ragged path's win in the first cut; the dense view is
    ``packed[starts[r] + s]`` with junk lanes (positions past a row's
    end alias the next row) masked out of the SCORES instead of zeroed
    in the operands.  Attention is the only stage that pays the dense
    shape; every other FLOP in the encoder runs on the unpadded token
    axis — the ragged path's whole win off-TPU, where Mosaic is
    unavailable."""
    total, heads, dh = q.shape
    seg = seg.astype(jnp.int32)
    pos = pos.astype(jnp.int32)
    # [R, S] token index of each dense lane into the packed axis
    idx = jnp.clip(
        starts.astype(jnp.int32)[:, None]
        + jax.lax.broadcasted_iota(jnp.int32, (num_rows, dense_s), 1),
        0,
        total - 1,
    )
    # a lane is real iff the token it aliases belongs to row r AND sits
    # at that lane's position — the position check catches the clipped
    # tail of the LAST row, whose out-of-range lanes alias back into the
    # row itself when the launch has no pad tail (seg alone would call
    # them valid and double-count the final token).  Layer-invariant, so
    # XLA CSE shares it across the 6 layers' attention calls.
    valid = (
        seg[idx]
        == jax.lax.broadcasted_iota(jnp.int32, (num_rows, dense_s), 0)
    ) & (
        pos[idx]
        == jax.lax.broadcasted_iota(jnp.int32, (num_rows, dense_s), 1)
    )
    qd = q[idx]  # [R, S, h, d] — junk lanes ride along, masked below
    kd = k[idx]
    vd = v[idx]
    s = jnp.einsum(
        "rqhd,rkhd->rhqk", qd, kd, preferred_element_type=jnp.float32
    ) * sm_scale
    s = jnp.where(valid[:, None, None, :], s, _NEG_INF)
    if causal:
        # in the dense unpack the lane index IS the within-row position,
        # so causal masking is a plain lower-triangular mask
        tri = jnp.tril(jnp.ones((dense_s, dense_s), bool))
        s = jnp.where(tri[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    od = jnp.einsum("rhqk,rkhd->rqhd", p, vd.astype(p.dtype))
    # gather back (pads clamp to the last row — their output is
    # unspecified by contract and dropped at pooling)
    gather_seg = jnp.minimum(seg, num_rows - 1)
    return od[gather_seg, pos].astype(q.dtype)


def ragged_attention(
    q,
    k,
    v,
    seg,
    *,
    pos=None,
    starts=None,
    bounds=None,
    num_rows: int | None = None,
    dense_s: int | None = None,
    sm_scale: float | None = None,
    pre_scaled: bool = False,
    causal: bool = False,
    mode: str | None = None,
):
    """Attention over a packed ragged batch.

    ``causal=True`` additionally masks each token to its own row's
    prefix (``pos_q >= pos_k``) — the decoder-prefill contract (the
    paged-KV generation subsystem rides this for its one-launch
    mixed-length prefill).  Requires ``pos`` in BOTH modes.

    ``q``/``k``/``v``: ``[T, heads, head_dim]`` — rows concatenated along
    the token axis, ``T`` padded to a token bucket.  ``seg``: ``[T]``
    int segment ids (row index per token; pad-tail tokens carry
    ``num_rows``).  Tokens attend only within their own segment; pad
    tokens' outputs are unspecified (callers drop them at pooling).

    ``bounds``: ``[T // block, 2]`` kv block ranges from
    :func:`ragged_bounds` (required for the Pallas kernel).  ``pos`` +
    ``num_rows`` + ``dense_s`` parameterize the XLA reference's dense
    unpack (position-within-row, row bucket, seq bucket).

    ``pre_scaled=True`` means the caller already multiplied the softmax
    scale into ``q`` — passing a second ``sm_scale`` alongside it raises
    instead of silently double-scaling.
    """
    if pre_scaled:
        if sm_scale is not None:
            raise ValueError(
                "ragged_attention: pre_scaled=True with an explicit "
                "sm_scale would double-scale the logits — pass one or "
                "the other"
            )
        scale = 1.0
    else:
        scale = (
            1.0 / math.sqrt(q.shape[-1]) if sm_scale is None else float(sm_scale)
        )
    validate_attention_geometry(
        int(q.shape[-1]), scale, knob="attention_impl='ragged'"
    )
    total = int(q.shape[0])
    if total > MAX_PACKED_TOKENS:
        raise ValueError(
            f"packed launch of {total} tokens exceeds MAX_PACKED_TOKENS="
            f"{MAX_PACKED_TOKENS} (whole-K/V VMEM residency); split the "
            "batch (PATHWAY_EMBED_MAX_TOKENS) or use attention_impl='fused'"
        )
    if mode is None:
        mode = kernel_mode()
    if mode == "auto":
        mode = "pallas" if jax.default_backend() == "tpu" else "reference"
    if mode == "reference":
        if pos is None or starts is None or num_rows is None or dense_s is None:
            raise ValueError(
                "ragged_attention reference mode needs pos, starts, "
                "num_rows and dense_s for the dense unpack"
            )
        return _ragged_reference(
            q, k, v, seg, pos, starts, int(num_rows), int(dense_s),
            float(scale), causal=causal,
        )
    block = ragged_block(total)
    if total % block:
        raise ValueError(
            f"packed length {total} is not a multiple of the {block}-token "
            "block — pad to a token bucket (models/encoder.ragged_prepare)"
        )
    if bounds is None:
        raise ValueError(
            "ragged_attention pallas mode needs the per-q-block kv bounds "
            "(ragged_bounds)"
        )
    if causal and pos is None:
        raise ValueError(
            "ragged_attention causal=True needs pos (position within row) "
            "for the prefix mask"
        )
    interpret = jax.default_backend() != "tpu"
    return _ragged_pallas(
        q, k, v, seg, pos, bounds, block, float(scale), interpret,
        causal=causal,
    )
