"""HBM-resident brute-force KNN index with incremental upsert/delete.

reference semantics: src/external_integration/brute_force_knn_integration.rs
(dense matrix, grow-by-doubling at :113-120, cos + l2sq, top-k) — redesigned
for TPU:

* the vector matrix lives on device (HBM) as a padded ``[capacity, dim]``
  array; rows are recycled through a tombstone ``valid`` mask instead of
  compaction, so deletes are O(1) mask flips and search stays one fused
  matmul+top-k on the MXU (``ops/topk.py``);
* upserts/deletes arriving from the dataflow are staged host-side and
  applied in one scatter per micro-batch (donated buffers — no reallocation
  until the capacity doubles);
* cosine vectors are L2-normalized once at insert, making query scoring a
  plain dot product.

The multi-device sharded variant lives in ``pathway_tpu/parallel/index.py``.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading
import weakref
from typing import Any, Hashable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .topk import topk_search
from .quantized_scoring import (
    dequantize_record,
    is_quant_record,
    quantize_jnp,
    rescore_cache_rows_default,
    rescore_depth_default,
    resolve_index_dtype,
)

__all__ = [
    "DeviceKnnIndex",
    "upsert_slice_rows",
    "upsert_coalesce_rows",
    "quantization_status",
]


def upsert_slice_rows() -> int:
    """Row cap per staged device scatter (``PATHWAY_UPSERT_SLICE_ROWS``,
    default 1024 — the largest dispatch batch bucket).  Device batches
    bigger than this are staged as multiple bounded slices, so (a) the
    scatter compile set stays on the bounded grid a jumbo bulk load
    would otherwise blow past, and (b) every individual scatter dispatch
    is tick-sized: under the unified runtime a bulk backfill becomes a
    sequence of bounded device steps instead of one monopolizing launch."""
    try:
        n = int(os.environ.get("PATHWAY_UPSERT_SLICE_ROWS", "1024"))
    except ValueError:
        n = 1024
    return max(n, 1)


def upsert_coalesce_rows() -> int:
    """Row cap per COALESCED apply-time scatter
    (``PATHWAY_UPSERT_COALESCE_ROWS``, default 8192; 0 disables).

    Staging slices batches to tick-sized chunks (``upsert_slice_rows``)
    so the runtime can preempt between them — but once a search (or a
    budget drain) decides to APPLY, issuing one scatter per chunk just
    multiplies dispatch latency: a 100-chunk bulk backlog pays 100
    launches where ~12 suffice.  The apply path therefore re-coalesces
    consecutive staged chunks up to this many rows per scatter (padded
    to a power of two so the compiled scatter shapes stay bounded)."""
    try:
        n = int(os.environ.get("PATHWAY_UPSERT_COALESCE_ROWS", "8192"))
    except ValueError:
        n = 8192
    return max(n, 0)


class DeviceKnnIndex:
    """Single-device incremental KNN index."""

    #: dead-slot fraction beyond which the matrix is rebuilt smaller —
    #: a churny corpus (steady upsert+delete) keeps matmul cost bounded at
    #: O(live) instead of paying for every slot it ever touched (the
    #: reference's HNSW actually removes points, usearch_integration.rs:60-90;
    #: brute-force here compacts instead)
    COMPACT_DEAD_FRACTION = 0.75
    MIN_CAPACITY = 8

    def __init__(
        self,
        dim: int,
        metric: str = "cos",
        capacity: int = 1024,
        dtype=None,
        index_dtype: str | None = None,
        rescore_depth: int | None = None,
        rescore_cache_rows: int | None = None,
    ):
        if metric not in ("cos", "l2sq", "dot"):
            raise ValueError(f"unknown metric {metric!r}")
        self.dim = dim
        self.metric = metric
        #: storage-dtype knob value ("f32" / "bf16" / "int8"); explicit
        #: arg > explicit jnp dtype > PATHWAY_INDEX_DTYPE process default
        self.index_dtype = resolve_index_dtype(index_dtype, dtype)
        self.quantized = self.index_dtype == "int8"
        if self.quantized:
            # compute dtype for queries/rescoring; codes live in int8
            self.dtype = jnp.float32
        else:
            self.dtype = jnp.bfloat16 if self.index_dtype == "bf16" else jnp.float32
        self.capacity = self._round_capacity(int(capacity))
        if self.quantized:
            self.vectors = None  # stale f32 paths must fail loudly
            self.codes = jnp.zeros((self.capacity, dim), dtype=jnp.int8)
            self.scales = jnp.zeros((self.capacity,), dtype=jnp.float32)
            #: stage-1 candidate funnel depth (effective per-search depth
            #: is bucket_k(max(k, rescore_depth)))
            self.rescore_depth = (
                int(rescore_depth)
                if rescore_depth is not None
                else rescore_depth_default()
            )
            #: f32 rescore ring: recently written rows keep an exact
            #: full-precision copy (the latency-critical tier)
            self.rescore_cache_rows = (
                int(rescore_cache_rows)
                if rescore_cache_rows is not None
                else rescore_cache_rows_default()
            )
            r = self.rescore_cache_rows
            self.rescore_vecs = jnp.zeros((r, dim), dtype=jnp.float32)
            self.cache_map = jnp.full((self.capacity,), -1, dtype=jnp.int32)
            # host mirrors of the ring (truth for rebuilds/compaction):
            # slot -> ring row, ring row -> slot (-1 empty), next ring pos
            self._cache_row_of_slot: dict[int, int] = {}
            self._cache_slot_of_row = np.full((r,), -1, dtype=np.int64)
            self._cache_next = 0
            # snapshot-restored rows staged as ready-made codes (zero
            # re-quantization): slot -> (codes int8 [dim], scale f32)
            self._staged_coded: dict[int, tuple[np.ndarray, np.float32]] = {}
        else:
            self.vectors = jnp.zeros((self.capacity, dim), dtype=self.dtype)
            self.rescore_depth = 0
            self.rescore_cache_rows = 0
            self._staged_coded = {}
        self.valid = jnp.zeros((self.capacity,), dtype=bool)
        self.key_of_slot: list[Hashable | None] = [None] * self.capacity
        self.slot_of_key: dict[Hashable, int] = {}
        self.free: list[int] = list(range(self.capacity - 1, -1, -1))
        # staged updates applied lazily before the next search
        self._staged_set: dict[int, np.ndarray] = {}
        self._staged_valid: dict[int, bool] = {}
        # device-resident staged batches: (slots[-1 = pad row], device
        # array [bb, dim]) applied FIFO before the host dict — keeps
        # last-write-wins semantics when the same slot is touched by both
        self._staged_device: list[tuple[np.ndarray, Any]] = []
        # the engine serializes index ops, but REST/serving threads may
        # query while another thread ingests — a coarse reentrant lock
        # keeps every public op a coherent snapshot (cost is ~100ns,
        # noise next to a device dispatch)
        self._lock = threading.RLock()
        # scatter fns — subclasses swap in sharding-preserving variants
        self._scatter_rows_fn = _scatter_rows
        self._scatter_mask_fn = _scatter_mask
        self._scatter_dropping_fn = _scatter_rows_dropping
        self._quant_scatter_fn = _quant_scatter
        self._coded_scatter_fn = _coded_scatter
        #: fatal-device-fault recoveries performed (rebuild_device_arrays)
        self.rebuilds = 0
        #: staged-device scatters actually dispatched (after coalescing) —
        #: the observable the coalescing satellite pins by test
        self.scatter_dispatches = 0
        #: quantized searches answered (quantization-block observable)
        self.quant_searches = 0
        self.quant_label = f"knn{next(_quant_label_seq)}"
        _LIVE_INDEXES.add(self)
        _ensure_index_provider()
        _register_hbm_ledger(self)

    def _round_capacity(self, capacity: int) -> int:
        """Capacities at/above the Pallas threshold are kept at multiples
        of its 1024-row tile so every large index takes the tiled path
        (doubling preserves the invariant)."""
        from .topk import PALLAS_MIN_ROWS

        capacity = max(capacity, self.MIN_CAPACITY)
        if capacity >= PALLAS_MIN_ROWS and capacity % 1024:
            capacity += 1024 - capacity % 1024
        return capacity

    def _place(self) -> None:
        """Re-establish array placement after a rebuild (sharded subclasses
        re-pin to the mesh)."""

    def __len__(self) -> int:
        return len(self.slot_of_key)

    def hbm_bytes(self) -> int:
        """Resident device bytes of this index (matrix + tombstones +,
        when quantized, scales, rescore ring and slot→ring table) — the
        ``pathway_index_hbm_bytes`` observable."""
        cap = self.capacity
        if self.quantized:
            # the ring and the slot→ring table REPLICATE on a mesh (see
            # ShardedKnnIndex) — count every copy, or an operator sizing
            # corpus-per-chip from this gauge overcommits HBM
            repl = getattr(self, "n_shards", 1)
            return (
                cap * self.dim  # int8 codes
                + cap * 4  # f32 scales
                + repl * cap * 4  # int32 cache map (replicated)
                + repl * self.rescore_cache_rows * self.dim * 4  # f32 ring
                + cap  # bool tombstones
            )
        itemsize = jnp.dtype(self.dtype).itemsize
        return cap * self.dim * itemsize + cap

    def hbm_ledger_entries(self):
        """This index's entry in the unified HBM ledger
        (``pathway_hbm_bytes{component="knn:<label>"}``) — an ``int``
        here; :class:`~pathway_tpu.parallel.index.ShardedKnnIndex`
        overrides with a per-shard dict that sums to EXACTLY the same
        total, so the ledger and the legacy ``pathway_index_hbm_bytes``
        gauge can never disagree (one source of truth: this method
        family)."""
        return self.hbm_bytes()

    def staged_hbm_bytes(self) -> int:
        """Device-staged scatter debt: embed→upsert batches that landed
        on device but have not been applied into the matrix yet hold
        their OWN device arrays until the next search drains them —
        invisible to :meth:`hbm_bytes`, real to the allocator."""
        return int(
            sum(
                int(getattr(arr, "nbytes", 0))
                for _slots, arr in list(self._staged_device)
            )
        )

    # -- mutation --
    def upsert(self, key: Hashable, vector: Any) -> None:
        with self._lock:
            self._upsert_locked(key, vector)

    def _upsert_locked(self, key: Hashable, vector: Any) -> None:
        vec = np.asarray(vector, dtype=np.float32).reshape(-1)
        if vec.shape[0] != self.dim:
            raise ValueError(
                f"vector dim {vec.shape[0]} != index dim {self.dim}"
            )
        if self.metric == "cos" and not self.quantized:
            # quantized rows stage RAW and normalize inside the fused
            # device quantize scatter instead — host- and device-staged
            # rows then share ONE normalization arithmetic, so their
            # codes and scales are bit-identical (the invariant the
            # snapshot plane's verbatim code export rests on)
            norm = float(np.linalg.norm(vec))
            if norm > 0:
                vec = vec / norm
        slot = self.slot_of_key.get(key)
        if slot is None:
            if not self.free:
                self._grow()
            slot = self.free.pop()
            self.slot_of_key[key] = slot
            self.key_of_slot[slot] = key
        self._staged_set[slot] = vec
        self._staged_coded.pop(slot, None)
        self._staged_valid[slot] = True

    def upsert_coded(self, key: Hashable, record: dict) -> None:
        """Stage one snapshot record (``quantize_record_np`` output) —
        the zero-re-quantization restore path: a quantized index scatters
        the codes straight back into HBM; any other dtype dequantizes
        once and takes the normal upsert path."""
        with self._lock:
            if not self.quantized:
                self._upsert_locked(key, dequantize_record(record))
                return
            codes = np.asarray(record["codes"], dtype=np.int8).reshape(-1)
            if codes.shape[0] != self.dim:
                raise ValueError(
                    f"record dim {codes.shape[0]} != index dim {self.dim}"
                )
            slot = self.slot_of_key.get(key)
            if slot is None:
                if not self.free:
                    self._grow()
                slot = self.free.pop()
                self.slot_of_key[key] = slot
                self.key_of_slot[slot] = key
            self._staged_coded[slot] = (codes, np.float32(record["scale"]))
            self._staged_set.pop(slot, None)
            self._staged_valid[slot] = True
            # a coded write supersedes any cached f32 copy of the slot's
            # previous value — drop the host mapping and force a device
            # cache_map rebuild at apply time.  The rebuild is marked
            # UNCONDITIONALLY: a slot recycled from the free list may
            # still carry a stale DEVICE mapping from a deleted key
            # (harmless while tombstoned, but a coded revive would score
            # the new key against the old key's ring vector), and the
            # host mirror cannot see that entry.
            pos = self._cache_row_of_slot.pop(slot, None)
            if pos is not None and self._cache_slot_of_row[pos] == slot:
                self._cache_slot_of_row[pos] = -1
            self._cache_map_dirty = True

    #: opt-out hook for subclasses that cannot take device-array staging;
    #: the mesh-sharded index (parallel/index.py) used to set this False —
    #: since PR 8 its dropping scatter pins ``out_shardings`` to the mesh,
    #: so device batches stage everywhere
    _device_stage_ok = True

    def upsert_batch(self, keys: Sequence[Hashable], vectors) -> None:
        """Stage a whole batch of vectors under one lock acquisition.

        ``vectors`` is ``[n, dim]`` — a host array (staged row-by-row like
        :meth:`upsert`), or a DEVICE array straight off the encoder
        (``n >= len(keys)``; rows past ``len(keys)`` are dispatch pad rows).
        Device batches never round-trip to host: they are kept as-is and
        scattered into the HBM matrix by ``_apply_staged`` in one fused
        normalize+scatter, with pad rows dropped via an out-of-bounds
        index (XLA scatter ``mode="drop"``).  This is the ingest-plane
        embed→upsert fast path — the D2H copy of the embedding and the
        H2D re-stage of the same bytes both disappear."""
        with self._lock:
            if isinstance(vectors, np.ndarray) or not self._device_stage_ok:
                vecs = np.asarray(vectors, dtype=np.float32)
                for j, key in enumerate(keys):
                    self._upsert_locked(key, vecs[j])
                return
            if vectors.ndim != 2 or vectors.shape[1] != self.dim:
                raise ValueError(
                    f"vector batch shape {vectors.shape} != [n, {self.dim}]"
                )
            if vectors.shape[0] < len(keys):
                raise ValueError(
                    f"{len(keys)} keys for {vectors.shape[0]} vector rows"
                )
            slots = np.full((vectors.shape[0],), -1, dtype=np.int64)
            row_of_slot: dict[int, int] = {}
            for j, key in enumerate(keys):
                slot = self.slot_of_key.get(key)
                if slot is None:
                    if not self.free:
                        self._grow()
                    slot = self.free.pop()
                    self.slot_of_key[key] = slot
                    self.key_of_slot[slot] = key
                # this device value supersedes any host value staged
                # earlier for the slot (FIFO batches apply before the dict)
                self._staged_set.pop(slot, None)
                self._staged_coded.pop(slot, None)
                self._staged_valid[slot] = True
                # a repeated key within ONE batch would put the same index
                # into the scatter twice — XLA applies duplicate updates in
                # undefined order, so drop the earlier row (last wins, like
                # the host path)
                prev = row_of_slot.get(slot)
                if prev is not None:
                    slots[prev] = -1
                row_of_slot[slot] = j
                slots[j] = slot
            # tick-granularity staging: bound each staged scatter at
            # upsert_slice_rows() rows (slicing a device array is lazy —
            # no host round trip); FIFO order within the batch preserves
            # last-write-wins exactly
            step = upsert_slice_rows()
            n = vectors.shape[0]
            if n <= step:
                self._staged_device.append((slots, vectors))
            else:
                for s in range(0, n, step):
                    self._staged_device.append(
                        (slots[s : s + step], vectors[s : s + step])
                    )

    def remove(self, key: Hashable) -> None:
        with self._lock:
            self._remove_locked(key)

    def _remove_locked(self, key: Hashable) -> None:
        slot = self.slot_of_key.pop(key, None)
        if slot is None:
            return
        self.key_of_slot[slot] = None
        self.free.append(slot)
        self._staged_valid[slot] = False
        self._staged_set.pop(slot, None)
        self._staged_coded.pop(slot, None)
        if self.quantized:
            # ring hygiene only: the device cache_map entry may stay —
            # a tombstoned slot scores -inf in stage 1 and the rescore
            # keeps -inf for invalid candidates, so a stale mapping can
            # never resurrect the row
            pos = self._cache_row_of_slot.pop(slot, None)
            if pos is not None and self._cache_slot_of_row[pos] == slot:
                self._cache_slot_of_row[pos] = -1

    def _grow(self) -> None:
        """Double capacity (reference: brute_force add :113-120)."""
        old = self.capacity
        self.capacity = self._round_capacity(old * 2)
        extra = self.capacity - old
        if self.quantized:
            self.codes = jnp.concatenate(
                [self.codes, jnp.zeros((extra, self.dim), dtype=jnp.int8)]
            )
            self.scales = jnp.concatenate(
                [self.scales, jnp.zeros((extra,), dtype=jnp.float32)]
            )
            self.cache_map = jnp.concatenate(
                [self.cache_map, jnp.full((extra,), -1, dtype=jnp.int32)]
            )
        else:
            self.vectors = jnp.concatenate(
                [self.vectors, jnp.zeros((extra, self.dim), dtype=self.dtype)]
            )
        self.valid = jnp.concatenate([self.valid, jnp.zeros((extra,), dtype=bool)])
        self.key_of_slot.extend([None] * extra)
        self.free.extend(range(self.capacity - 1, old - 1, -1))
        self._place()

    def _maybe_compact(self) -> None:
        """Shrink the matrix once dead slots dominate (amortized: a rebuild
        moves O(live) rows and at least halves capacity, so its cost is
        charged to the deletes that created the slack)."""
        live = len(self.slot_of_key)
        if self.capacity <= self.MIN_CAPACITY:
            return
        if live > self.capacity * (1.0 - self.COMPACT_DEAD_FRACTION):
            return
        new_capacity = self._round_capacity(max(2 * live, self.MIN_CAPACITY))
        if new_capacity >= self.capacity:
            return
        live_slots = sorted(self.slot_of_key.values())
        idx = jnp.asarray(np.asarray(live_slots, dtype=np.int32))
        pad = new_capacity - len(live_slots)
        if self.quantized:
            gathered_c = self.codes[idx] if live_slots else jnp.zeros(
                (0, self.dim), dtype=jnp.int8
            )
            gathered_s = self.scales[idx] if live_slots else jnp.zeros(
                (0,), dtype=jnp.float32
            )
            self.codes = jnp.concatenate(
                [gathered_c, jnp.zeros((pad, self.dim), dtype=jnp.int8)]
            )
            self.scales = jnp.concatenate(
                [gathered_s, jnp.zeros((pad,), dtype=jnp.float32)]
            )
        else:
            gathered = self.vectors[idx] if live_slots else jnp.zeros(
                (0, self.dim), dtype=self.dtype
            )
            self.vectors = jnp.concatenate(
                [gathered, jnp.zeros((pad, self.dim), dtype=self.dtype)]
            )
        self.valid = jnp.concatenate(
            [
                jnp.ones((len(live_slots),), dtype=bool),
                jnp.zeros((pad,), dtype=bool),
            ]
        )
        remap = {old: new for new, old in enumerate(live_slots)}
        if self.quantized:
            # remap the rescore ring's slot side; the ring rows (and the
            # f32 vectors they hold) are untouched — only slot indices
            # moved
            new_row_of_slot: dict[int, int] = {}
            slot_of_row = np.full_like(self._cache_slot_of_row, -1)
            for slot, row in self._cache_row_of_slot.items():
                ns = remap.get(slot)
                if ns is not None:
                    new_row_of_slot[ns] = row
                    slot_of_row[row] = ns
            self._cache_row_of_slot = new_row_of_slot
            self._cache_slot_of_row = slot_of_row
            self._staged_coded = {
                remap[s]: v
                for s, v in self._staged_coded.items()
                if s in remap
            }
            self._rebuild_cache_map(new_capacity)
        self.slot_of_key = {k: remap[s] for k, s in self.slot_of_key.items()}
        self.key_of_slot = [None] * new_capacity
        for key, slot in self.slot_of_key.items():
            self.key_of_slot[slot] = key
        self.capacity = new_capacity
        self.free = list(range(new_capacity - 1, len(live_slots) - 1, -1))
        self._place()

    def apply_staged_budget(self, max_entries: int = 8) -> int:
        """Apply up to ``max_entries`` staged device batches NOW (oldest
        first) and return how many were applied.

        Incremental, tick-sized flushing for bulk backfills: a search
        still applies everything pending (as-of-now semantics are
        untouched — staged rows stay invisible either way until the
        valid-mask scatter in :meth:`_apply_staged` runs), but a bulk
        ingest driver can drain its scatter debt in bounded doses
        between searches instead of handing the next query one
        100-dispatch apply burst.  FIFO order is preserved, so
        last-write-wins semantics against later host writes hold."""
        with self._lock:
            from ..testing import faults

            if faults.enabled and self._staged_device:
                faults.perturb("device.upsert")
            n = 0
            while self._staged_device and n < max_entries:
                self._apply_device_entry(*self._staged_device.pop(0))
                n += 1
            return n

    def _rebuild_cache_map(self, capacity: int) -> None:
        """Re-materialize the device slot→ring-row table from the host
        mirror (capacity changes and rebuilds rewrite slot indices
        wholesale — one H2D of ``[capacity]`` int32 beats scatter
        surgery)."""
        m = np.full((capacity,), -1, dtype=np.int32)
        for slot, row in self._cache_row_of_slot.items():
            if 0 <= slot < capacity:
                m[slot] = row
        self.cache_map = jnp.asarray(m)

    def _assign_cache_rows(
        self, slots: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Ring-assign rescore-cache rows for one apply batch (host
        bookkeeping under the index lock).  Returns ``(rows, map_idx,
        evict_idx)`` aligned with ``slots``: ``rows[j]`` is the cache row
        receiving row j's f32 vector (``R`` = none, dropped by the OOB
        scatter), ``map_idx[j]`` the slot whose mapping is set (capacity
        = none), ``evict_idx[j]`` a slot whose mapping must clear first
        (capacity = none).  A slot already resident reuses its row; a
        batch larger than the ring keeps only its newest R rows."""
        r = self.rescore_cache_rows
        n = int(slots.shape[0])
        rows = np.full((n,), r, dtype=np.int32)
        map_idx = np.full((n,), self.capacity, dtype=np.int32)
        evict_idx = np.full((n,), self.capacity, dtype=np.int32)
        if r <= 0:
            return rows, map_idx, evict_idx
        last_j_of_row: dict[int, int] = {}
        for j in range(n):
            slot = int(slots[j])
            if slot < 0:
                continue
            pos = self._cache_row_of_slot.get(slot)
            if pos is None:
                pos = self._cache_next
                self._cache_next = (pos + 1) % r
                old = int(self._cache_slot_of_row[pos])
                if old >= 0 and self._cache_row_of_slot.get(old) == pos:
                    del self._cache_row_of_slot[old]
                    evict_idx[j] = old
            prev_j = last_j_of_row.get(pos)
            if prev_j is not None:
                # the ring wrapped within this one batch: the earlier
                # row's write must be blanked — duplicate scatter rows
                # apply in undefined order, and its mapping would
                # otherwise resurrect after the evict pass
                rows[prev_j] = r
                map_idx[prev_j] = self.capacity
            last_j_of_row[pos] = j
            self._cache_slot_of_row[pos] = slot
            self._cache_row_of_slot[slot] = pos
            rows[j] = pos
            map_idx[j] = slot
        return rows, map_idx, evict_idx

    def _apply_device_entry(self, slots: np.ndarray, vals: Any) -> None:
        """Scatter ONE staged device batch into the matrix.  Pad rows
        (slot -1) scatter out of bounds and are dropped on device; the
        OOB index is resolved at apply time — capacity may have grown
        since staging.  Shared by the search-time full apply and the
        incremental budget apply so their numerics can never diverge.
        Subclasses with sharded matrices point ``_scatter_dropping_fn``
        at a mesh-pinning variant (``out_shardings``), so device-staged
        rows land in their owning shard instead of collapsing the
        placement onto one device.

        A quantized index routes through the fused quantize+scatter
        instead: rows normalize (cos) and quantize ON DEVICE, codes and
        scales scatter into their matrices, and the f32 rows land in the
        rescore ring — still one launch, no host round trip."""
        idx = np.where(slots >= 0, slots, self.capacity).astype(np.int32)
        self.scatter_dispatches += 1
        if self.quantized:
            self._apply_quantized_rows(
                idx, slots, vals, normalize=(self.metric == "cos")
            )
            return
        self.vectors = self._scatter_dropping_fn(
            self.vectors,
            jnp.asarray(idx),
            vals,
            normalize=(self.metric == "cos"),
        )

    def _apply_quantized_rows(
        self, idx: np.ndarray, slots: np.ndarray, vals: Any, normalize: bool
    ) -> None:
        """One fused quantize+scatter of ``vals`` rows into (codes,
        scales, rescore ring, cache map).  ``idx`` is the drop-resolved
        scatter index (pad rows already at capacity)."""
        rows, map_idx, evict_idx = self._assign_cache_rows(slots)
        (
            self.codes,
            self.scales,
            self.rescore_vecs,
            self.cache_map,
        ) = self._quant_scatter_fn(
            self.codes,
            self.scales,
            self.rescore_vecs,
            self.cache_map,
            jnp.asarray(idx),
            jnp.asarray(rows),
            jnp.asarray(map_idx),
            jnp.asarray(evict_idx),
            vals,
            normalize=normalize,
        )

    def _coalesce_staged_device(
        self,
    ) -> list[tuple[np.ndarray, Any]]:
        """Re-group the staged device chunks into few large scatters
        (≤ :func:`upsert_coalesce_rows` rows each, padded to a power of
        two so compiled scatter shapes stay bounded).

        Only CONSECUTIVE chunks merge, so FIFO order is preserved; a slot
        written by two coalesced chunks keeps only its LAST row (XLA
        applies duplicate scatter indices in undefined order), which is
        exactly the last-write-wins outcome the sequential applies had."""
        entries = self._staged_device
        cap = upsert_coalesce_rows()
        if cap <= 0 or len(entries) <= 1:
            return list(entries)
        groups: list[list[tuple[np.ndarray, Any]]] = []
        cur: list[tuple[np.ndarray, Any]] = []
        rows = 0
        for slots, vals in entries:
            n = int(slots.shape[0])
            if cur and rows + n > cap:
                groups.append(cur)
                cur, rows = [], 0
            cur.append((slots, vals))
            rows += n
        if cur:
            groups.append(cur)
        out: list[tuple[np.ndarray, Any]] = []
        for group in groups:
            if len(group) == 1:
                out.append(group[0])
                continue
            slots = np.concatenate([s for s, _ in group])
            # later occurrences win: blank earlier duplicates (walk from
            # the end; np.concatenate copied, so staged arrays are safe)
            seen: set[int] = set()
            for i in range(len(slots) - 1, -1, -1):
                s = int(slots[i])
                if s < 0:
                    continue
                if s in seen:
                    slots[i] = -1
                else:
                    seen.add(s)
            total = int(slots.shape[0])
            padded = 1 << (total - 1).bit_length()
            parts = [v for _, v in group]
            if padded > total:
                slots = np.concatenate(
                    [slots, np.full((padded - total,), -1, dtype=slots.dtype)]
                )
                parts.append(
                    jnp.zeros(
                        (padded - total, parts[0].shape[1]),
                        dtype=parts[0].dtype,
                    )
                )
            out.append((slots, jnp.concatenate(parts)))
        return out

    def _apply_staged(self) -> None:
        if (
            not self._staged_set
            and not self._staged_valid
            and not self._staged_device
            and not self._staged_coded
        ):
            self._maybe_compact()
            return
        from ..testing import faults

        if faults.enabled:
            # chaos site "device.upsert": the staged scatter is where a
            # flaky dispatch / HBM allocator failure lands in production —
            # a "fail" here surfaces through whichever caller (search or
            # ingest flush) triggered the apply, exercising both
            # containment paths
            faults.perturb("device.upsert")
        # device batches FIRST (FIFO), host dict after: a host upsert that
        # landed later than a device batch for the same slot wins, and
        # upsert_batch already evicts older host entries for its slots.
        # A long backlog coalesces into few large scatters here — the
        # tick-sized chunks existed for preemptibility while QUEUED, not
        # to be paid one launch each once the apply is committed.
        for slots, vals in self._coalesce_staged_device():
            self._apply_device_entry(slots, vals)
        self._staged_device.clear()
        if self._staged_set:
            idx = np.fromiter(self._staged_set.keys(), dtype=np.int32)
            if self.quantized:
                # host rows staged RAW: the fused scatter normalizes
                # (cos) and quantizes on device — the same arithmetic
                # the device-batch path runs, so host- and device-staged
                # rows can never diverge in their codes or scales
                vals = np.stack(list(self._staged_set.values())).astype(
                    np.float32
                )
                self._apply_quantized_rows(
                    idx, idx.astype(np.int64), jnp.asarray(vals),
                    normalize=(self.metric == "cos"),
                )
            else:
                vals = np.stack(list(self._staged_set.values())).astype(self.dtype)
                self.vectors = self._scatter_rows_fn(
                    self.vectors, jnp.asarray(idx), jnp.asarray(vals)
                )
        if self._staged_coded:
            cidx = np.fromiter(self._staged_coded.keys(), dtype=np.int32)
            ccodes = np.stack([c for c, _ in self._staged_coded.values()])
            cscales = np.asarray(
                [s for _, s in self._staged_coded.values()], dtype=np.float32
            )
            self.codes, self.scales = self._coded_scatter_fn(
                self.codes,
                self.scales,
                jnp.asarray(cidx),
                jnp.asarray(ccodes),
                jnp.asarray(cscales),
            )
            self._staged_coded.clear()
            if getattr(self, "_cache_map_dirty", False):
                self._rebuild_cache_map(self.capacity)
                self._cache_map_dirty = False
                self._place()
        if self._staged_valid:
            vidx = np.fromiter(self._staged_valid.keys(), dtype=np.int32)
            vvals = np.fromiter(self._staged_valid.values(), dtype=bool)
            self.valid = self._scatter_mask_fn(
                self.valid, jnp.asarray(vidx), jnp.asarray(vvals)
            )
        self._staged_set.clear()
        self._staged_valid.clear()
        self._maybe_compact()

    def export_records(self, keys: Sequence[Hashable]) -> dict:
        """Snapshot records for ``keys`` holding the EXACT resident
        bytes (codes + scale) the index serves — one batched gather +
        D2H for the whole delta.  Applying staged first is deliberate:
        a snapshot must describe committed rows, and the apply was due
        at the next search anyway.  Restore scatters these bytes back
        verbatim (``upsert_coded``): bit-identical, zero re-embeds,
        zero re-quantization.  Empty for unquantized indexes."""
        with self._lock:
            if not self.quantized:
                return {}
            self._apply_staged()
            present = [
                (k, self.slot_of_key[k]) for k in keys if k in self.slot_of_key
            ]
            if not present:
                return {}
            slots = jnp.asarray(
                np.asarray([s for _, s in present], dtype=np.int32)
            )
            codes = np.asarray(self.codes[slots])
            scales = np.asarray(self.scales[slots])
            from .quantized_scoring import QUANT_RECORD_KEY

            return {
                k: {
                    QUANT_RECORD_KEY: 1,
                    "codes": codes[i],
                    "scale": np.float32(scales[i]),
                }
                for i, (k, _slot) in enumerate(present)
            }

    # -- fatal-device-fault recovery ------------------------------------
    def rebuild_device_arrays(self, vectors_by_key=None) -> bool:
        """Recreate the device-resident arrays after a fatal device fault
        (HBM OOM, XLA runtime error, failed transfer) without losing the
        host-side bookkeeping.

        Two recovery sources, tried in order:

        1. **host mirror** — pull the (possibly still readable) matrix
           back to host and re-place fresh arrays from the copy; the
           usual path when the fault hit a scatter/launch but the
           resident buffers survived;
        2. **snapshot provider** — ``vectors_by_key`` (key → raw vector,
           e.g. replayed from the operator-snapshot plane by
           ``ExternalIndexNode``): slots are reassigned and every vector
           re-staged, the path when the arrays themselves are gone.

        Staged device batches are salvaged to host where their buffers
        still read; rows that cannot be copied are dropped loudly (the
        error log) rather than poisoning the rebuild.  ``_place()`` runs
        at the end so sharded subclasses re-pin to the mesh instead of
        landing on the default device.  Returns True on success.
        """
        with self._lock:
            return self._rebuild_locked(vectors_by_key)

    def _rebuild_locked(self, vectors_by_key) -> bool:
        from ..internals.errors import register_error

        salvaged: list[tuple[np.ndarray, np.ndarray]] = []
        dropped_slots: list[int] = []
        for slots, vals in self._staged_device:
            try:
                salvaged.append((slots, np.asarray(vals, dtype=np.float32)))
            except Exception:  # noqa: BLE001 — buffer on the dead device
                dropped_slots.extend(int(s) for s in slots if s >= 0)
        self._staged_device.clear()
        if dropped_slots:
            register_error(
                f"index rebuild dropped {len(dropped_slots)} staged device "
                "rows (buffers unreadable after device fault)",
                kind="index",
                operator="knn.rebuild",
            )
        host = valid = None
        try:
            if self.quantized:
                # the quantized resident state is codes+scales (+ the f32
                # rescore ring): pull ALL of it back — a rebuild that
                # resurrected only an f32 matrix would silently lose the
                # codes the searches actually scan (the PR 6 device-fault
                # path predating quantization did exactly that)
                host_codes = np.asarray(self.codes, dtype=np.int8)
                host_scales = np.asarray(self.scales, dtype=np.float32)
                host_cache = np.asarray(self.rescore_vecs, dtype=np.float32)
                host = True
            else:
                host = np.asarray(self.vectors, dtype=np.float32)
            valid = np.asarray(self.valid, dtype=bool)
        except Exception:  # noqa: BLE001 — resident arrays are gone too
            host = None
        slots_reassigned = False
        if host is not None:
            if self.quantized:
                self.codes = jnp.asarray(host_codes)
                self.scales = jnp.asarray(host_scales)
                self.rescore_vecs = jnp.asarray(host_cache)
                self._rebuild_cache_map(self.capacity)
            else:
                self.vectors = jnp.asarray(host.astype(np.float32), dtype=self.dtype)
            self.valid = jnp.asarray(valid)
        elif vectors_by_key is not None:
            # arrays unreadable: rebuild bookkeeping + staging from the
            # snapshot.  Keys absent from the provider (an uncommitted
            # tail) are lost here and re-enter via replay/re-ingest.
            lost = len(self.slot_of_key) - sum(
                1 for k in self.slot_of_key if k in vectors_by_key
            )
            if lost:
                register_error(
                    f"index rebuild from snapshot lost {lost} uncommitted "
                    "rows (will re-enter via replay/re-ingest)",
                    kind="index",
                    operator="knn.rebuild",
                )
            self.slot_of_key = {}
            self.key_of_slot = [None] * self.capacity
            self.free = list(range(self.capacity - 1, -1, -1))
            self._staged_set.clear()
            self._staged_valid.clear()
            self._staged_coded.clear()
            if self.quantized:
                self.codes = jnp.zeros((self.capacity, self.dim), dtype=jnp.int8)
                self.scales = jnp.zeros((self.capacity,), dtype=jnp.float32)
                self.rescore_vecs = jnp.zeros(
                    (self.rescore_cache_rows, self.dim), dtype=jnp.float32
                )
                self._cache_row_of_slot = {}
                self._cache_slot_of_row = np.full(
                    (self.rescore_cache_rows,), -1, dtype=np.int64
                )
                self._cache_next = 0
                self._rebuild_cache_map(self.capacity)
            else:
                self.vectors = jnp.zeros((self.capacity, self.dim), dtype=self.dtype)
            self.valid = jnp.zeros((self.capacity,), dtype=bool)
            for key, vec in vectors_by_key.items():
                # snapshot records restore their codes verbatim (zero
                # re-quantization); raw f32 vectors re-code through the
                # normal staged path
                if is_quant_record(vec):
                    self.upsert_coded(key, vec)
                else:
                    self._upsert_locked(key, vec)
            slots_reassigned = True
        else:
            return False
        if slots_reassigned:
            # the snapshot path reassigned every slot: salvaged batches
            # carry only PRE-rebuild slot indices, so re-staging them
            # would write stale vectors into slots now owned by other
            # keys (or resurrect freed slots).  Drop them loudly — they
            # belong to an uncommitted tail that re-enters via replay.
            n = sum(int((slots >= 0).sum()) for slots, _ in salvaged)
            if n:
                register_error(
                    f"index rebuild from snapshot dropped {n} salvaged "
                    "staged rows (slot layout was reassigned; rows "
                    "re-enter via replay/re-ingest)",
                    kind="index",
                    operator="knn.rebuild",
                )
        else:
            # re-stage salvaged device rows host-side; pre-existing host
            # staging wins (it was staged AFTER the device batches)
            host_staged = set(self._staged_set) | set(self._staged_coded)
            for slots, vals in salvaged:
                for j, slot in enumerate(slots):
                    slot = int(slot)
                    if slot < 0 or slot in host_staged:
                        continue
                    vec = vals[j]
                    if self.metric == "cos" and not self.quantized:
                        # quantized rows stay RAW — the fused scatter
                        # normalizes on device (see _upsert_locked)
                        norm = float(np.linalg.norm(vec))
                        if norm > 0:
                            vec = vec / norm
                    self._staged_set[slot] = vec.astype(np.float32)
                    self._staged_valid[slot] = True
            # dropped rows whose slot holds NO materialized vector (a new
            # key whose only write was the unreadable batch) must not stay
            # pending-valid: the scatter would mark a never-written matrix
            # row live and searches would rank its zeros.  Keys with an
            # old materialized vector keep it.
            for slot in dropped_slots:
                if (
                    slot in self._staged_set
                    or slot in self._staged_coded
                    or bool(valid[slot])
                ):
                    continue
                self._staged_valid.pop(slot, None)
                key = self.key_of_slot[slot]
                if key is not None:
                    del self.slot_of_key[key]
                    self.key_of_slot[slot] = None
                    self.free.append(slot)
        self._place()
        self.rebuilds += 1
        return True

    # -- search --
    def search_among(
        self, query: Any, keys: list[Hashable], k: int
    ) -> list[tuple[Hashable, float]]:
        """Exact rescoring restricted to ``keys`` (LSH candidate sets).
        Gathers candidate rows on device and runs the same fused top-k."""
        with self._lock:
            return self._search_among_locked(query, keys, k)

    def _search_among_locked(self, query, keys, k):
        self._apply_staged()
        slots = [self.slot_of_key[key] for key in keys if key in self.slot_of_key]
        if not slots:
            return []
        q = np.asarray(query, dtype=np.float32).reshape(1, -1)
        if self.metric == "cos":
            norm = np.linalg.norm(q)
            if norm > 0:
                q = q / norm
        idx = jnp.asarray(np.asarray(slots, dtype=np.int32))
        if self.quantized:
            from .quantized_scoring import dequant_gather

            sub_vectors = dequant_gather(self.codes, self.scales, idx)
        else:
            sub_vectors = self.vectors[idx]
        sub_valid = self.valid[idx]
        k_eff = min(k, len(slots))
        scores, sub_idx = topk_search(
            jnp.asarray(q, dtype=self.dtype), sub_vectors, sub_valid, k_eff, self.metric
        )
        out: list[tuple[Hashable, float]] = []
        for s, i in zip(np.asarray(scores)[0], np.asarray(sub_idx)[0]):
            if not np.isfinite(s):
                continue
            key = self.key_of_slot[slots[int(i)]]
            if key is not None:
                out.append((key, float(s)))
        return out

    def search_among_batched(
        self,
        queries: Any,  # [Q, D]
        keys_lists: list[list[Hashable]],
        k: int,
    ) -> list[list[tuple[Hashable, float]]]:
        """Batched :meth:`search_among`: one device call rescoring every
        query against its own candidate set (padded to shared buckets so
        compiled shapes stay stable).  The per-query form costs one RPC
        round trip each over a remote chip; this is the LSH serving path."""
        with self._lock:
            return self._search_among_batched_locked(queries, keys_lists, k)

    #: elements budget for the [Q, C, D] candidate gather — bounds peak
    #: HBM next to the resident index (32M f32 elems ≈ 128 MB); larger
    #: batches process in query chunks
    _AMONG_GATHER_ELEMS = 32 * 1024 * 1024

    def _search_among_batched_locked(self, queries, keys_lists, k):
        from .topk import among_topk_search, bucket_k, bucket_q

        self._apply_staged()
        slot_lists = [
            [self.slot_of_key[key] for key in keys if key in self.slot_of_key]
            for keys in keys_lists
        ]
        cmax = max((len(s) for s in slot_lists), default=0)
        if cmax == 0:
            return [[] for _ in keys_lists]
        # bucket the candidate dim: stable compiled shapes
        c_b = max(16, 1 << (cmax - 1).bit_length())
        n_q = len(slot_lists)
        # chunk queries so the [Q, C, D] gather stays within budget (one
        # huge bucket union must not OOM HBM; a chunk of 1 degrades to the
        # per-query cost, never worse)
        max_chunk = max(1, self._AMONG_GATHER_ELEMS // (c_b * self.dim))
        q_all = np.asarray(queries, dtype=np.float32).reshape(n_q, -1)
        results: list[list[tuple[Hashable, float]]] = []
        for start in range(0, n_q, max_chunk):
            chunk = slot_lists[start : start + max_chunk]
            q_b = bucket_q(len(chunk))
            idx = np.zeros((q_b, c_b), np.int32)
            pad_valid = np.zeros((q_b, c_b), bool)
            for i, s in enumerate(chunk):
                idx[i, : len(s)] = s
                pad_valid[i, : len(s)] = True
            q = np.zeros((q_b, self.dim), np.float32)
            q[: len(chunk)] = q_all[start : start + len(chunk)]
            if self.metric == "cos":
                norms = np.linalg.norm(q, axis=1, keepdims=True)
                np.divide(q, norms, out=q, where=norms > 0)
            # bucket k like q/c: heterogeneous serving k values must not
            # each compile a fresh kernel — top_k rows come back sorted,
            # so slicing recovers the exact k-result (ADVICE #2)
            k_eff = min(k, c_b)
            if self.quantized:
                from .quantized_scoring import quant_among_topk_search

                scores, sub_idx = quant_among_topk_search(
                    jnp.asarray(q, dtype=jnp.float32),
                    self.codes,
                    self.scales,
                    self.valid,
                    jnp.asarray(idx),
                    jnp.asarray(pad_valid),
                    bucket_k(k_eff, c_b),
                    self.metric,
                )
            else:
                scores, sub_idx = among_topk_search(
                    jnp.asarray(q, dtype=self.dtype),
                    self.vectors,
                    self.valid,
                    jnp.asarray(idx),
                    jnp.asarray(pad_valid),
                    bucket_k(k_eff, c_b),
                    self.metric,
                )
            scores = np.asarray(scores)[:, :k_eff]
            sub_idx = np.asarray(sub_idx)[:, :k_eff]
            for i in range(len(chunk)):
                row: list[tuple[Hashable, float]] = []
                for s, j in zip(scores[i], sub_idx[i]):
                    if not np.isfinite(s):
                        continue
                    key = self.key_of_slot[int(idx[i, int(j)])]
                    if key is not None:
                        row.append((key, float(s)))
                results.append(row)
        return results

    def quant_depth(self, k: int) -> int:
        """Stage-1 candidate count for a quantized search: the rescore
        funnel never narrows below ``k`` and rides the same power-of-two
        bucket grid as ``k`` itself."""
        from .topk import bucket_k

        return bucket_k(max(k, self.rescore_depth), self.capacity)

    def _quant_device_search(self, q) -> Any:
        """Shared quantized stage-1 inputs: queries as a device f32
        array (kernel/reference cast per mode inside the jit)."""
        return jnp.asarray(q, dtype=jnp.float32)

    def _device_search(self, q: np.ndarray, k: int) -> tuple[jax.Array, jax.Array]:
        """(scores, slot indices) for PREPPED (normalized + padded)
        queries — the staged REFERENCE chain: scoring and top-k as
        separate dispatches with the full ``[Q, N]`` score intermediate
        materialized between them.  Serving reaches this only under
        ``PATHWAY_SERVING_KERNEL=reference`` (the A/B baseline the fused
        path is benched and parity-pinned against); subclasses override
        with the mesh-sharded formulation."""
        from .fused_serving import (
            dense_reference_search,
            quant_reference_search,
            record_launch,
        )
        from .topk import PALLAS_MIN_ROWS, pallas_topk_search

        if self.quantized:
            self.quant_searches += 1
            return quant_reference_search(
                self._quant_device_search(q),
                self.codes,
                self.scales,
                self.valid,
                self.rescore_vecs,
                self.cache_map,
                c=self.quant_depth(k),
                k=min(k, self.capacity),
                metric=self.metric,
                use_cache=self.rescore_cache_rows > 0,
            )
        if (
            self.metric in ("cos", "dot")
            and self.capacity >= PALLAS_MIN_ROWS
            and self.capacity % 1024 == 0
            # compiled Mosaic only: off-TPU the "kernel" would run in
            # interpret mode — a per-element Python-level evaluator meant
            # for test coverage, ~40x slower than the fused XLA path at
            # this size (it silently dominated the CPU exact-search
            # numbers in knn_crossover before the quantized A/B caught it)
            and jax.default_backend() == "tpu"
        ):
            record_launch("topk")
            return pallas_topk_search(
                jnp.asarray(q, dtype=self.dtype),
                self.vectors,
                self.valid,
                min(k, self.capacity),
                self.metric,
            )
        return dense_reference_search(
            q,
            self.vectors,
            self.valid,
            k=min(k, self.capacity),
            metric=self.metric,
            qdt="bf16" if self.dtype == jnp.bfloat16 else "f32",
        )

    def _fused_device_search(
        self, q, k: int, q_b: int, normalize: bool, mode: str
    ) -> tuple[jax.Array, jax.Array]:
        """(scores, slot indices) for RAW queries — the fused serving
        path (megakernel or single-jit XLA per
        ``fused_serving.pick_serving_impl``): widen/normalize/pad, score
        and top-k inside one dispatch, plus at most the rescore-ring
        pass.  Subclasses override with the mesh-sharded fused path."""
        from .fused_serving import dense_fused_search, quant_fused_search

        if self.quantized:
            self.quant_searches += 1
            # raw queries straight in — the fused jit widens/normalizes
            # in-register (no eager pre-cast dispatch like the staged
            # reference's `_quant_device_search`)
            return quant_fused_search(
                q if isinstance(q, jax.Array)
                else jnp.asarray(q, dtype=jnp.float32),
                self.codes,
                self.scales,
                self.valid,
                self.rescore_vecs,
                self.cache_map,
                c=self.quant_depth(k),
                k=min(k, self.capacity),
                q_b=q_b,
                metric=self.metric,
                normalize=normalize,
                use_cache=self.rescore_cache_rows > 0,
                mode=mode,
            )
        return dense_fused_search(
            q if isinstance(q, jax.Array) else jnp.asarray(q),
            self.vectors,
            self.valid,
            k=min(k, self.capacity),
            q_b=q_b,
            metric=self.metric,
            normalize=normalize,
            qdt="bf16" if self.dtype == jnp.bfloat16 else "f32",
            mode=mode,
        )

    def search(
        self,
        queries: Any,
        k: int,
        n_valid: int | None = None,
        *,
        pre_normalized: bool = False,
    ) -> list[list[tuple[Hashable, float]]]:
        """Top-k per query as (key, score) lists, higher scores better.

        ``queries`` may be a host ``[Q, D]`` array, or a DEVICE array
        straight off the encoder (the fused serving tick): device
        queries are normalized and bucket-padded on device — the
        embed→search handoff never round-trips through host memory.
        By default the whole chain runs as the fused serving path —
        normalize, scoring and top-k in ONE launch (megakernel on TPU,
        single-jit XLA elsewhere; ``PATHWAY_SERVING_KERNEL`` selects,
        ``reference`` restores the staged legacy chain).
        ``n_valid`` caps how many leading rows get host-side result
        assembly (the fused tick's trailing dispatch-pad rows searched
        on device anyway, but building and filtering (key, score) lists
        for them is pure waste).  ``pre_normalized`` tells a cos index
        the caller already L2-normalized the queries (the tiered hot
        tier does) so they are not normalized twice."""
        with self._lock:
            return self._search_locked(
                queries, k, n_valid, pre_normalized=pre_normalized
            )

    def _search_locked(self, queries, k, n_valid=None, *, pre_normalized=False):
        from .fused_serving import (
            record_launch,
            serving_kernel_mode,
            serving_tick,
        )
        from .topk import bucket_k, bucket_q

        self._apply_staged()
        on_device = isinstance(queries, jax.Array) and not isinstance(
            queries, np.ndarray
        )
        if on_device and queries.ndim == 1:
            queries = queries[None, :]  # lazy device reshape
        if len(self.slot_of_key) == 0 or k <= 0:
            n = (
                queries.shape[0]
                if on_device
                else np.atleast_2d(np.asarray(queries)).shape[0]
            )
            if n_valid is not None:
                n = min(n, n_valid)
            return [[] for _ in range(n)]
        # normalize cosine queries exactly ONCE: host queries normalize
        # on host (below), device queries inside the fused jit / the
        # reference `_prep_queries` dispatch — never both, and never
        # again when the caller (tiered hot tier) already did
        normalize = self.metric == "cos" and not pre_normalized
        mode = serving_kernel_mode()
        if on_device:
            n_q = queries.shape[0]
            q_b = bucket_q(n_q)
            q = queries
        else:
            q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
            if normalize:
                norms = np.linalg.norm(q, axis=1, keepdims=True)
                norms[norms == 0] = 1.0
                q = q / norms
            normalize = False  # already done, host-side
            n_q = q.shape[0]
            # bucket BOTH dims that vary under serving traffic: the ragged
            # scheduler-tick batch size (pad Q to a power of two, slice
            # back) and the heterogeneous per-request k (bucket_k; top_k
            # rows come back sorted so slicing recovers the exact result)
            # — without this every distinct (Q, k) pair compiles a fresh
            # XLA program
            q_b = bucket_q(n_q)
            if q_b != n_q:
                q = np.concatenate(
                    [q, np.zeros((q_b - n_q, q.shape[1]), dtype=q.dtype)]
                )
        k_req = min(k, self.capacity)
        k_b = bucket_k(k_req, self.capacity)
        with serving_tick():
            if mode == "reference":
                if on_device:
                    q = _prep_queries(q, q_b=q_b, normalize=normalize)
                    record_launch("prep")
                scores, idx = self._device_search(q, k_b)
            else:
                scores, idx = self._fused_device_search(
                    q, k_b, q_b=q_b, normalize=normalize, mode=mode
                )
        if n_valid is not None:
            n_q = min(n_q, n_valid)
        scores = np.asarray(scores)[:n_q]
        idx = np.asarray(idx)[:n_q]
        out: list[list[tuple[Hashable, float]]] = []
        for qi in range(n_q):
            row: list[tuple[Hashable, float]] = []
            for s, i in zip(scores[qi], idx[qi]):
                if not np.isfinite(s):
                    continue
                key = self.key_of_slot[int(i)]
                if key is None:
                    continue
                row.append((key, float(s)))
                if len(row) == k_req:
                    break
            out.append(row)
        return out


@jax.jit
def _scatter_rows(matrix: jax.Array, idx: jax.Array, vals: jax.Array) -> jax.Array:
    return matrix.at[idx].set(vals)


def _scatter_rows_dropping_body(
    matrix: jax.Array, idx: jax.Array, vals: jax.Array, normalize: bool
) -> jax.Array:
    """Device-resident embed→upsert scatter: rows whose index is out of
    bounds (dispatch pad rows) are dropped by XLA, cos rows are
    L2-normalized on device (f32 accumulation) — one fused kernel instead
    of a D2H copy, host normalize, and H2D re-stage.  The un-jitted body
    is shared with the sharded index's mesh-pinning jit
    (``out_shardings``) so the two paths can never numerically diverge."""
    v = vals.astype(jnp.float32)
    if normalize:
        norm = jnp.linalg.norm(v, axis=1, keepdims=True)
        v = v / jnp.maximum(norm, 1e-30)
    return matrix.at[idx].set(v.astype(matrix.dtype), mode="drop")


_scatter_rows_dropping = functools.partial(jax.jit, static_argnames=("normalize",))(
    _scatter_rows_dropping_body
)


def _quant_scatter_body(
    codes: jax.Array,  # [cap, D] int8
    scales: jax.Array,  # [cap] f32
    cache_vecs: jax.Array,  # [R, D] f32
    cache_map: jax.Array,  # [cap] int32
    idx: jax.Array,  # [n] scatter slots (cap = dropped pad row)
    rows: jax.Array,  # [n] ring rows (R = no cache row)
    map_idx: jax.Array,  # [n] slots whose mapping is set (cap = none)
    evict_idx: jax.Array,  # [n] slots whose mapping clears first (cap = none)
    vals: jax.Array,  # [n, D] raw rows (device or host-staged)
    normalize: bool,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Quantized twin of the dropping scatter: normalize (cos) and
    symmetric-scale quantize the rows ON DEVICE, scatter codes+scales
    into the resident matrices, and land the exact f32 rows in the
    rescore ring — one fused launch, the embed→upsert fast path never
    round-trips to host.  All out-of-bounds indices drop, so pad rows
    and no-cache rows cost nothing.  The un-jitted body is shared with
    the sharded index's mesh-pinning jit (``out_shardings``) so the two
    paths can never numerically diverge."""
    v = vals.astype(jnp.float32)
    if normalize:
        norm = jnp.linalg.norm(v, axis=1, keepdims=True)
        v = v / jnp.maximum(norm, 1e-30)
    c, s = quantize_jnp(v)
    codes = codes.at[idx].set(c, mode="drop")
    scales = scales.at[idx].set(s, mode="drop")
    cache_vecs = cache_vecs.at[rows].set(v, mode="drop")
    cache_map = cache_map.at[evict_idx].set(-1, mode="drop")
    cache_map = cache_map.at[map_idx].set(rows.astype(jnp.int32), mode="drop")
    return codes, scales, cache_vecs, cache_map


_quant_scatter = functools.partial(jax.jit, static_argnames=("normalize",))(
    _quant_scatter_body
)


def _coded_scatter_body(
    codes: jax.Array, scales: jax.Array, idx: jax.Array,
    new_codes: jax.Array, new_scales: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Snapshot-restore scatter: ready-made codes land verbatim (zero
    re-quantization — the bytes that were durable are the bytes that
    serve)."""
    return (
        codes.at[idx].set(new_codes, mode="drop"),
        scales.at[idx].set(new_scales, mode="drop"),
    )


_coded_scatter = jax.jit(_coded_scatter_body)


@functools.partial(jax.jit, static_argnames=("q_b", "normalize"))
def _prep_queries(q: jax.Array, q_b: int, normalize: bool) -> jax.Array:
    """Fused-serving query prep, on device: f32 widen, optional L2
    normalize, pad the ragged tick batch up to its Q bucket.  Shapes come
    from the same power-of-two grid as the host path, so the compile set
    stays bounded."""
    q = q.astype(jnp.float32)
    if normalize:
        norm = jnp.linalg.norm(q, axis=1, keepdims=True)
        q = q / jnp.maximum(norm, 1e-30)
    if q_b > q.shape[0]:
        q = jnp.pad(q, ((0, q_b - q.shape[0]), (0, 0)))
    return q


@jax.jit
def _scatter_mask(mask: jax.Array, idx: jax.Array, vals: jax.Array) -> jax.Array:
    return mask.at[idx].set(vals)


# ---------------------------------------------------------------------------
# quantization observability: pathway_index_* series on /status, the
# "quantization" block on /v1/health (internals/health.py reads
# quantization_status() only when this module is already imported — a
# health probe never pulls in jax state)
# ---------------------------------------------------------------------------

#: live device indexes, for /status + /v1/health quantization surfacing
#: (weak: a finished run's indexes drop out with it)
_LIVE_INDEXES: "weakref.WeakSet[DeviceKnnIndex]" = weakref.WeakSet()
_quant_label_seq = itertools.count()


def _live_indexes() -> list["DeviceKnnIndex"]:
    return sorted(_LIVE_INDEXES, key=lambda i: i.quant_label)


class _IndexMetricsProvider:
    """``pathway_index_dtype`` / ``pathway_index_hbm_bytes`` /
    ``pathway_index_rescore_depth`` OpenMetrics series over every live
    device index."""

    def stats(self) -> dict:
        return quantization_status() or {}

    def openmetrics_lines(self) -> list[str]:
        from ..internals.metrics_names import escape_label_value

        indexes = _live_indexes()
        if not indexes:
            return []
        lines = ["# TYPE pathway_index_dtype gauge"]
        for idx in indexes:
            lines.append(
                f'pathway_index_dtype{{index="'
                f'{escape_label_value(idx.quant_label)}",dtype="'
                f'{escape_label_value(idx.index_dtype)}"}} 1'
            )
        lines.append("# TYPE pathway_index_hbm_bytes gauge")
        for idx in indexes:
            lines.append(
                f'pathway_index_hbm_bytes{{index="'
                f'{escape_label_value(idx.quant_label)}"}} {idx.hbm_bytes()}'
            )
        lines.append("# TYPE pathway_index_rescore_depth gauge")
        for idx in indexes:
            lines.append(
                f'pathway_index_rescore_depth{{index="'
                f'{escape_label_value(idx.quant_label)}"}} '
                f"{idx.rescore_depth}"
            )
        return lines


def _ledger_index_bytes(idx: "DeviceKnnIndex"):
    return idx.hbm_ledger_entries()


def _ledger_staged_bytes(idx: "DeviceKnnIndex") -> int:
    return idx.staged_hbm_bytes()


def _register_hbm_ledger(idx: "DeviceKnnIndex") -> None:
    """Every device index is a unified-HBM-ledger client: the resident
    matrix/codes/ring under ``knn:<label>`` and the transient
    staged-scatter debt under ``knn_staged:<label>`` (module-level
    ``bytes_fn``s so the ledger's weak owner ref stays the only
    reference — a bound method would pin the index alive)."""
    from ..observability.hbm_ledger import get_ledger

    led = get_ledger()
    led.register(f"knn:{idx.quant_label}", idx, _ledger_index_bytes)
    led.register(f"knn_staged:{idx.quant_label}", idx, _ledger_staged_bytes)


def _ensure_index_provider() -> None:
    # once-registration with a strong ref held by monitoring (the
    # provider table itself is weak-valued)
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once("index_quant", _IndexMetricsProvider)


def quantization_status() -> dict | None:
    """Per-index storage dtype + byte footprint + rescore configuration
    for ``/v1/health`` (None when no device index is live)."""
    indexes = _live_indexes()
    if not indexes:
        return None
    out = {}
    for idx in indexes:
        cap = max(int(idx.capacity), 1)
        info = {
            "dtype": idx.index_dtype,
            # "hot" when the index serves as a tiered index's HBM tier
            # (pathway_tpu/tiering), "primary" when it IS the corpus
            "role": getattr(idx, "tier_role", "primary"),
            "metric": idx.metric,
            "dim": int(idx.dim),
            "capacity_rows": int(idx.capacity),
            "live_rows": len(idx),
            "hbm_bytes": int(idx.hbm_bytes()),
            "bytes_per_vector": round(idx.hbm_bytes() / cap, 2),
        }
        if idx.quantized:
            info["rescore_depth"] = int(idx.rescore_depth)
            info["rescore_cache_rows"] = int(idx.rescore_cache_rows)
            info["cache_rows_live"] = len(idx._cache_row_of_slot)
            info["quant_searches"] = int(idx.quant_searches)
        out[idx.quant_label] = info
    return out


# observable compile counts (pathway_xla_compile_total): upsert scatters
# recompile only on capacity growth/compaction — a climbing counter here
# under steady traffic means the doubling/rounding invariants broke
from ..internals.flight_recorder import instrument_jit as _instrument_jit

_scatter_rows = _instrument_jit(_scatter_rows, "knn.scatter_rows")
_scatter_mask = _instrument_jit(_scatter_mask, "knn.scatter_mask")
# device-batch shapes come from the dispatch bucket grid (plus the
# power-of-two coalesce pads), so this site is bounded by
# (#batch_buckets x capacity growths), like the others
_scatter_rows_dropping = _instrument_jit(
    _scatter_rows_dropping, "knn.scatter_rows_padded"
)
# quantized twins: same bounded shape grids as their f32 counterparts
_quant_scatter = _instrument_jit(_quant_scatter, "knn.quant_scatter")
_coded_scatter = _instrument_jit(_coded_scatter, "knn.coded_scatter")
# fused-serving query prep: shapes are (bucket_q, dim) — same grid the
# search itself compiles over
_prep_queries = _instrument_jit(_prep_queries, "knn.query_prep")
