"""Fused serving-tick megakernel: normalized query → top-k in ONE launch.

The serving hot path used to execute as a chain of separate device
dispatches — query L2-normalize (``_prep_queries``), masked scoring over
the resident corpus, ``lax.top_k``, and (int8) the rescore-ring pass —
each paying dispatch latency plus a round trip through HBM for the full
``[Q, N]`` score intermediate.  This module collapses the chain:

* **Pallas megakernel** — one ``pallas_call`` whose grid streams corpus
  blocks through VMEM while the query tile stays resident: the queries
  L2-normalize in VMEM at the first block, every block's scores are
  computed on the MXU (asymmetric int8 dequant-in-register on the
  quantized path, the ``ops/quantized_scoring.py`` math), and a running
  per-query top-k merges across the block grid (the online-accumulator
  idiom from ``ops/ragged_attention.py`` / ``decode_kernel.py``) — the
  full score matrix never exists in HBM;
* **fused XLA formulation** — the same normalize→score→top-k
  composition under ONE jit (one dispatch, XLA fuses the mask into the
  matmul epilogue).  Off-TPU this is the fused lowering (Pallas
  interpret mode is a per-element evaluator, ~40x slower) and
  everywhere it is the bit-compatibility oracle the megakernel is
  pinned against;
* **staged reference formulation** — the legacy separate-launch chain
  (normalize / score matrix / top-k / rescore as individual dispatches,
  the ``[Q, N]`` intermediate materialized) kept for A/B benches and
  parity tests.

Mode knob (``PATHWAY_QUANT_KERNEL`` idiom): ``PATHWAY_SERVING_KERNEL=``
``auto`` (megakernel on TPU when the geometry tiles, fused XLA
elsewhere), ``fused`` (same lowering, stated intent), ``reference``
(the staged legacy chain), ``pallas`` (force the megakernel body —
interpret mode off-TPU, how tier-1 exercises the real kernel on CPU).
``validate_serving_geometry`` names the knob when a forced kernel
cannot tile.

Bit-compatibility contract: every score element is the same length-D
dot in every formulation (per-element reductions are insensitive to the
output tiling — the property the sharded-parity tests already pin), the
megakernel's online merge breaks score ties toward the lower slot index
exactly like ``lax.top_k``'s stable order, and rows with fewer than k
valid slots surface the same ``-inf``/index tail.  Fused-vs-reference
top-k is therefore bit-exact at f32, pinned by ``tests/test_fused_serving.py``.

Launch accounting: every serving-path dispatch calls
:func:`record_launch`; :func:`serving_tick` aggregates per tick and
emits a ``pathway_serving_launches_total{stage=}`` counter family plus
a flight-recorder ``serving.tick`` span carrying per-stage launch
counts — the fused win is provable without a chip
(``PATHWAY_LAUNCH_ACCOUNTING=0`` disables, for overhead A/Bs).

Wire dtype: ``PATHWAY_SERVING_WIRE_DTYPE`` (default ``bf16``) is the
encoder→search handoff dtype — half the bytes on the device-resident
wire, widened back to f32 in-register before normalization (exact), so
query-cache hit/miss bit-exactness is preserved.  ``f32`` opts out
(see MIGRATION).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
import warnings
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from .quantized_scoring import (
    _reference_scores,
    compute_dtype,
    pick_block_n,
    rescore_topk,
)
from .topk import _scores as _dense_scores

__all__ = [
    "SERVING_KERNEL_MODES",
    "SERVING_WIRE_DTYPES",
    "serving_kernel_mode",
    "serving_wire_dtype",
    "launch_accounting_enabled",
    "validate_serving_geometry",
    "record_launch",
    "serving_tick",
    "launch_totals",
    "reset_launch_metrics",
    "dense_fused_search",
    "quant_fused_search",
    "dense_reference_search",
    "quant_reference_search",
    "pallas_fused_topk",
    "pallas_fused_quant_topk",
]

#: every literal the mode parser accepts — the kernel-registry lint pins
#: this tuple against the README knob table, both directions
SERVING_KERNEL_MODES = ("auto", "fused", "reference", "pallas")

SERVING_WIRE_DTYPES = ("bf16", "f32")

#: tombstoned-slot sentinel INSIDE the megakernel (the ragged_attention
#: idiom: finite, so the taken-entry marker below it still exists in
#: f32).  Converted back to -inf at the final grid step so the output is
#: bit-identical to the reference's ``where(valid, s, -inf)`` masking.
_MASKED = -0.7 * float(jnp.finfo(jnp.float32).max)
#: unfilled top-k lane sentinel: strictly below every maskable score so
#: real (even tombstoned) candidates always displace it — rows with
#: >= k corpus slots can never surface an unfilled lane
_UNFILLED = -0.8 * float(jnp.finfo(jnp.float32).max)

_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16}


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def serving_kernel_mode() -> str:
    """``PATHWAY_SERVING_KERNEL``: ``auto`` (megakernel on TPU when the
    geometry tiles, fused XLA elsewhere — the serving default),
    ``fused`` (explicit fused lowering, same dispatch), ``reference``
    (staged legacy chain: separate normalize/score/top-k/rescore
    launches), or ``pallas`` (force the megakernel; interpret mode
    off-TPU — slow but exact, tier-1's kernel coverage)."""
    raw = os.environ.get("PATHWAY_SERVING_KERNEL", "auto").strip().lower()
    if raw in SERVING_KERNEL_MODES:
        return raw
    warnings.warn(
        f"PATHWAY_SERVING_KERNEL={raw!r} is not one of "
        f"{'/'.join(SERVING_KERNEL_MODES)} — using auto",
        stacklevel=2,
    )
    return "auto"


def serving_wire_dtype() -> str:
    """``PATHWAY_SERVING_WIRE_DTYPE`` (default ``bf16``): dtype of the
    encoder→search device handoff.  bf16 halves the on-wire bytes (the
    banked ``wire_bf16`` A/B win) and widens back to f32 exactly before
    normalization, so scores and cache hit/miss bit-exactness are
    unchanged; ``f32`` opts out (MIGRATION documents the flip)."""
    raw = os.environ.get("PATHWAY_SERVING_WIRE_DTYPE", "bf16").strip().lower()
    if raw in SERVING_WIRE_DTYPES:
        return raw
    warnings.warn(
        f"PATHWAY_SERVING_WIRE_DTYPE={raw!r} is not one of "
        f"{'/'.join(SERVING_WIRE_DTYPES)} — using bf16",
        stacklevel=2,
    )
    return "bf16"


def launch_accounting_enabled() -> bool:
    """``PATHWAY_LAUNCH_ACCOUNTING`` (default on): per-dispatch launch
    counting + the per-tick ``serving.tick`` flight-recorder span.  The
    off switch exists for the ``obs_overhead.py --fused`` budget A/B."""
    return os.environ.get("PATHWAY_LAUNCH_ACCOUNTING", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def validate_serving_geometry(n_rows: int, metric: str) -> int:
    """Block size for the megakernel's corpus grid, or raise naming the
    knob when the forced kernel cannot tile this index.  ``auto``/
    ``fused`` callers never raise — they fall back to the fused XLA
    formulation instead (same launch count, no tiling constraint)."""
    problems = []
    if metric not in ("cos", "dot"):
        problems.append(
            f"metric {metric!r} has no megakernel body (cos/dot only)"
        )
    block_n = pick_block_n(n_rows)
    if block_n is None:
        problems.append(
            f"corpus capacity {n_rows} has no power-of-two block tile "
            "(needs a divisor >= 32, the int8 sublane tile)"
        )
    if problems:
        raise ValueError(
            "PATHWAY_SERVING_KERNEL=pallas forces the fused serving "
            "megakernel, but " + "; ".join(problems) + " — set "
            "PATHWAY_SERVING_KERNEL=auto (or fused) to use the fused "
            "XLA formulation on this geometry"
        )
    return int(block_n)


def pick_serving_impl(mode: str, n_rows: int, metric: str) -> str:
    """``"pallas"`` or ``"xla"`` for the fused lowering.  ``pallas``
    mode validates (and raises on) geometry; ``auto``/``fused`` take the
    megakernel only where it is compiled Mosaic on a real TPU and the
    corpus tiles — everywhere else the single-jit XLA formulation is
    the same launch count without interpret-mode cost."""
    if mode == "pallas":
        validate_serving_geometry(n_rows, metric)
        return "pallas"
    if (
        metric in ("cos", "dot")
        and pick_block_n(n_rows) is not None
        and jax.default_backend() == "tpu"
    ):
        return "pallas"
    return "xla"


# ---------------------------------------------------------------------------
# launch accounting
# ---------------------------------------------------------------------------

_tls = threading.local()
_totals_lock = threading.Lock()
_LAUNCH_TOTALS: dict[str, int] = {}
_provider_registered = False


class _Tick:
    """Per-serving-tick launch ledger (thread-local; nested ticks fold
    into the outermost one)."""

    __slots__ = ("counts", "_t0_wall", "_t0_mono")

    def __init__(self) -> None:
        self.counts: dict[str, int] = {}
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()

    @property
    def total(self) -> int:
        return sum(self.counts.values())


class _ServingLaunchMetricsProvider:
    """``pathway_serving_launches_total{stage=}`` counter family: one
    series per dispatch stage on the serving search path (``fused`` /
    ``prep`` / ``score`` / ``topk`` / ``rescore`` / ``wire``)."""

    def stats(self) -> dict:
        return {"serving_launches": launch_totals()}

    def openmetrics_lines(self) -> list[str]:
        from ..internals.metrics_names import escape_label_value

        with _totals_lock:
            items = sorted(_LAUNCH_TOTALS.items())
        if not items:
            return []
        lines = ["# TYPE pathway_serving_launches_total counter"]
        for stage, n in items:
            lines.append(
                f'pathway_serving_launches_total{{stage="'
                f'{escape_label_value(stage)}"}} {n}'
            )
        return lines


def _ensure_provider() -> None:
    global _provider_registered
    if _provider_registered:
        return
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once(
        "serving_launches", _ServingLaunchMetricsProvider
    )
    _provider_registered = True


def record_launch(stage: str, n: int = 1) -> None:
    """Count one serving-path device dispatch.  Rides the current
    :func:`serving_tick` (if one is open) AND the process-lifetime
    ``pathway_serving_launches_total{stage=}`` counters."""
    if not launch_accounting_enabled():
        return
    _ensure_provider()
    with _totals_lock:
        _LAUNCH_TOTALS[stage] = _LAUNCH_TOTALS.get(stage, 0) + n
    tick = getattr(_tls, "tick", None)
    if tick is not None:
        tick.counts[stage] = tick.counts.get(stage, 0) + n


@contextlib.contextmanager
def serving_tick():
    """Scope one serving tick's launch ledger: yields the :class:`_Tick`
    (``.counts`` maps stage → dispatches, ``.total`` sums them) and, on
    exit, records a ``serving.tick`` flight-recorder span whose attrs
    carry the per-tick launch counts — the ≤2-launches-per-tick pin is
    readable straight off the trace.  Reentrant: a nested tick folds
    into the outermost one (one span per logical tick)."""
    outer = getattr(_tls, "tick", None)
    if outer is not None:
        yield outer
        return
    tick = _Tick()
    _tls.tick = tick
    try:
        yield tick
    finally:
        _tls.tick = None
        if tick.counts and launch_accounting_enabled():
            from ..internals.flight_recorder import record_span

            attrs: dict[str, Any] = {"launches": tick.total}
            for stage, n in sorted(tick.counts.items()):
                attrs[f"launches.{stage}"] = n
            record_span(
                "serving.tick",
                "serve",
                tick._t0_wall,
                (time.monotonic() - tick._t0_mono) * 1000.0,
                attrs=attrs,
            )


def launch_totals() -> dict[str, int]:
    """Process-lifetime launch counters (stage → count), a snapshot."""
    with _totals_lock:
        return dict(_LAUNCH_TOTALS)


def reset_launch_metrics() -> None:
    """Test hook: zero the process-lifetime launch counters."""
    with _totals_lock:
        _LAUNCH_TOTALS.clear()


# ---------------------------------------------------------------------------
# shared stage bodies (one arithmetic, three formulations)
# ---------------------------------------------------------------------------


def _l2_normalize(q: jax.Array) -> jax.Array:
    """Row L2 normalize, f32.  ``x*x`` is bitwise ``abs(x)**2`` for f32,
    so this matches ``jnp.linalg.norm``-based callers exactly — one
    arithmetic shared by the megakernel (in VMEM) and the XLA bodies."""
    norm = jnp.sqrt(jnp.sum(q * q, axis=-1, keepdims=True))
    return q / jnp.maximum(norm, 1e-30)


def _prep_body(q: jax.Array, q_b: int, normalize: bool) -> jax.Array:
    """f32 widen → optional L2 normalize → pad to the Q bucket (the
    ``knn._prep_queries`` math, here inlined into the fused jits so
    query prep stops being its own dispatch)."""
    q = q.astype(jnp.float32)
    if normalize:
        q = _l2_normalize(q)
    if q_b > q.shape[0]:
        q = jnp.pad(q, ((0, q_b - q.shape[0]), (0, 0)))
    return q


def _merge_topk(cand_s, cand_i, k: int):
    """Online top-k merge: select the k best of ``cand_s`` (ties toward
    the lower candidate POSITION — running buffer first, then ascending
    slot — which reproduces ``lax.top_k``'s stable lowest-index-first
    order over the full row).  Vectorized compare/select/reduce only, so
    the body lowers on Mosaic (no sort, no gather)."""
    bq, w = cand_s.shape
    pos = lax.broadcasted_iota(jnp.int32, (bq, w), 1)
    lane = lax.broadcasted_iota(jnp.int32, (bq, k), 1)
    best_s0 = jnp.full((bq, k), _UNFILLED, jnp.float32)
    best_i0 = jnp.zeros((bq, k), jnp.int32)

    def body(t, carry):
        cs, bs, bi = carry
        m = jnp.max(cs, axis=1)
        # first-occurrence argmax via masked position-min (ties resolve
        # toward the earlier candidate, the stable-top_k tie rule)
        first = jnp.min(jnp.where(cs == m[:, None], pos, w), axis=1)
        hit = pos == first[:, None]
        sel = jnp.sum(jnp.where(hit, cand_i, 0), axis=1)
        bs = jnp.where(lane == t, m[:, None], bs)
        bi = jnp.where(lane == t, sel[:, None], bi)
        # taken entries drop strictly below every live sentinel
        cs = jnp.where(hit, -jnp.inf, cs)
        return cs, bs, bi

    _, best_s, best_i = lax.fori_loop(0, k, body, (cand_s, best_s0, best_i0))
    return best_s, best_i


# ---------------------------------------------------------------------------
# Pallas megakernel (dense f32/bf16 rows + int8 codes variants)
# ---------------------------------------------------------------------------


def pallas_fused_topk(
    q: jax.Array,  # [q_b, D] f32 (widened+padded by the jit wrapper)
    vectors: jax.Array,  # [N, D] f32/bf16
    valid: jax.Array,  # [N] f32 {0,1}
    *,
    k: int,
    metric: str,
    normalize: bool,
    qdt: str,
    block_n: int,
    interpret: bool,
):
    """Dense serving megakernel: ONE launch from raw query block to
    ``(top-k scores, top-k slots)``.  Grid streams corpus blocks minor;
    the query tile normalizes into the (revisited) ``qn`` output at the
    first block and stays VMEM-resident; the running top-k lives in the
    revisited output blocks, merged online per block — the ``[Q, N]``
    score matrix never exists."""
    from jax.experimental import pallas as pl

    q_b, d = q.shape
    n = vectors.shape[0]
    block_q = min(q_b, 256)
    cdt = _DTYPES[qdt]

    def kernel(q_ref, v_ref, m_ref, qn_ref, s_ref, i_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            qf = q_ref[:].astype(jnp.float32)
            if normalize:
                qf = _l2_normalize(qf)
            qn_ref[:] = qf
            s_ref[:] = jnp.full((block_q, k), _UNFILLED, jnp.float32)
            i_ref[:] = lax.broadcasted_iota(jnp.int32, (block_q, k), 1)

        qc = qn_ref[:].astype(cdt)
        scores = jnp.dot(
            qc, v_ref[:].astype(cdt).T, preferred_element_type=jnp.float32
        )
        masked = jnp.where(m_ref[:][None, :] > 0, scores, _MASKED)
        gidx = j * block_n + lax.broadcasted_iota(
            jnp.int32, (block_q, block_n), 1
        )
        cand_s = jnp.concatenate([s_ref[:], masked], axis=1)
        cand_i = jnp.concatenate([i_ref[:], gidx], axis=1)
        best_s, best_i = _merge_topk(cand_s, cand_i, k)
        i_ref[:] = best_i

        @pl.when(j == pl.num_programs(1) - 1)
        def _final():
            # sentinel → -inf: bit-identical to the reference's
            # where(valid, s, -inf) masking at the output surface
            s_ref[:] = jnp.where(best_s <= _MASKED, -jnp.inf, best_s)

        @pl.when(j < pl.num_programs(1) - 1)
        def _carry():
            s_ref[:] = best_s

    grid = (q_b // block_q, n // block_n)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((q_b, d), jnp.float32),
            jax.ShapeDtypeStruct((q_b, k), jnp.float32),
            jax.ShapeDtypeStruct((q_b, k), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
        ],
        out_specs=(
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * q_b * n * d,
            bytes_accessed=(
                n * d * vectors.dtype.itemsize + n * 4 + q_b * d * 4
                + q_b * k * 8
            ),
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, vectors, valid)


def pallas_fused_quant_topk(
    q: jax.Array,  # [q_b, D] f32
    codes: jax.Array,  # [N, D] int8
    scales: jax.Array,  # [N] f32
    valid: jax.Array,  # [N] f32 {0,1}
    *,
    c: int,
    normalize: bool,
    block_n: int,
    interpret: bool,
):
    """Quantized serving megakernel: normalize in VMEM, asymmetric
    int8 dequant-in-register scoring (``scale_v * (q · codes_v)``, the
    ``quantized_scoring`` math — HBM only ever moves 1 byte/element),
    online top-c merge across the code-block grid.  Returns
    ``(cand scores, cand slots, normalized queries)`` — the third
    output feeds the rescore-ring pass without re-normalizing."""
    from jax.experimental import pallas as pl

    q_b, d = q.shape
    n = codes.shape[0]
    block_q = min(q_b, 256)
    ct = compute_dtype()

    def kernel(q_ref, c_ref, sc_ref, m_ref, qn_ref, s_ref, i_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            qf = q_ref[:].astype(jnp.float32)
            if normalize:
                qf = _l2_normalize(qf)
            qn_ref[:] = qf
            s_ref[:] = jnp.full((block_q, c), _UNFILLED, jnp.float32)
            i_ref[:] = lax.broadcasted_iota(jnp.int32, (block_q, c), 1)

        dots = jnp.dot(
            qn_ref[:].astype(ct), c_ref[:].astype(ct).T,
            preferred_element_type=jnp.float32,
        )
        scored = dots * sc_ref[:][None, :]
        masked = jnp.where(m_ref[:][None, :] > 0, scored, _MASKED)
        gidx = j * block_n + lax.broadcasted_iota(
            jnp.int32, (block_q, block_n), 1
        )
        cand_s = jnp.concatenate([s_ref[:], masked], axis=1)
        cand_i = jnp.concatenate([i_ref[:], gidx], axis=1)
        best_s, best_i = _merge_topk(cand_s, cand_i, c)
        i_ref[:] = best_i

        @pl.when(j == pl.num_programs(1) - 1)
        def _final():
            s_ref[:] = jnp.where(best_s <= _MASKED, -jnp.inf, best_s)

        @pl.when(j < pl.num_programs(1) - 1)
        def _carry():
            s_ref[:] = best_s

    grid = (q_b // block_q, n // block_n)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((q_b, d), jnp.float32),
            jax.ShapeDtypeStruct((q_b, c), jnp.float32),
            jax.ShapeDtypeStruct((q_b, c), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
        ],
        out_specs=(
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, c), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, c), lambda i, j: (i, 0)),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * q_b * n * d,
            bytes_accessed=n * d + n * 8 + q_b * d * 4 + q_b * c * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, codes, scales, valid)


# ---------------------------------------------------------------------------
# fused jits (ONE dispatch each; the Pallas wrappers fold widen+pad into
# the same launch as the kernel)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "q_b", "metric", "normalize", "qdt"),
)
def _xla_fused_dense(q, vectors, valid, *, k, q_b, metric, normalize, qdt):
    qn = _prep_body(q, q_b, normalize)
    s = _dense_scores(qn.astype(_DTYPES[qdt]), vectors, metric)
    s = jnp.where(valid[None, :], s, -jnp.inf)
    return lax.top_k(s, k)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "q_b", "metric", "normalize", "qdt", "block_n", "interpret",
    ),
)
def _pallas_fused_dense(
    q, vectors, valid, *, k, q_b, metric, normalize, qdt, block_n, interpret
):
    del metric  # cos/dot share the dot body; validate gated l2sq out
    qp = q.astype(jnp.float32)
    if q_b > qp.shape[0]:
        qp = jnp.pad(qp, ((0, q_b - qp.shape[0]), (0, 0)))
    _qn, scores, idx = pallas_fused_topk(
        qp,
        vectors,
        valid.astype(jnp.float32),
        k=k,
        metric="dot",
        normalize=normalize,
        qdt=qdt,
        block_n=block_n,
        interpret=interpret,
    )
    return scores, idx


@functools.partial(
    jax.jit,
    static_argnames=("c", "k", "q_b", "metric", "normalize", "use_cache"),
)
def _xla_fused_quant(
    q, codes, scales, valid, cache_vecs, cache_map,
    *, c, k, q_b, metric, normalize, use_cache,
):
    from .quantized_scoring import _rescore_body

    qn = _prep_body(q, q_b, normalize)
    s = _reference_scores(qn, codes, scales, valid, metric)
    cand_s, cand_i = lax.top_k(s, c)
    if not use_cache:
        return cand_s[:, :k], cand_i[:, :k]
    return _rescore_body(qn, cand_s, cand_i, cache_vecs, cache_map, k, metric)


@functools.partial(
    jax.jit,
    static_argnames=("c", "q_b", "normalize", "block_n", "interpret"),
)
def _pallas_fused_quant(
    q, codes, scales, valid, *, c, q_b, normalize, block_n, interpret
):
    qp = q.astype(jnp.float32)
    if q_b > qp.shape[0]:
        qp = jnp.pad(qp, ((0, q_b - qp.shape[0]), (0, 0)))
    return pallas_fused_quant_topk(
        qp,
        codes,
        scales,
        valid.astype(jnp.float32),
        c=c,
        normalize=normalize,
        block_n=block_n,
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# staged reference formulation (the legacy separate-launch chain)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("k",))
def _staged_topk(s, *, k):
    return lax.top_k(s, k)


@functools.partial(jax.jit, static_argnames=("metric",))
def _staged_dense_scores(q, vectors, valid, *, metric):
    s = _dense_scores(q, vectors, metric)
    return jnp.where(valid[None, :], s, -jnp.inf)


@functools.partial(jax.jit, static_argnames=("metric",))
def _staged_quant_scores(q, codes, scales, valid, *, metric):
    return _reference_scores(q, codes, scales, valid, metric)


def dense_reference_search(q, vectors, valid, *, k, metric, qdt):
    """Separate-launch legacy chain (the A/B baseline): the full
    ``[Q, N]`` masked score matrix materializes in HBM between two
    dispatches.  ``q`` arrives prepped (normalized + padded)."""
    s = _staged_dense_scores(
        jnp.asarray(q, dtype=_DTYPES[qdt]), vectors, valid, metric=metric
    )
    record_launch("score")
    out = _staged_topk(s, k=k)
    record_launch("topk")
    return out


def quant_reference_search(
    q, codes, scales, valid, cache_vecs, cache_map,
    *, c, k, metric, use_cache,
):
    """Quantized legacy chain: asymmetric scores / top-c / rescore as
    three separate dispatches (+1 for prep upstream = the ≥4-launch
    baseline the megakernel collapses)."""
    qf = jnp.asarray(q, dtype=jnp.float32)
    s = _staged_quant_scores(qf, codes, scales, valid, metric=metric)
    record_launch("score")
    cand_s, cand_i = _staged_topk(s, k=c)
    record_launch("topk")
    if not use_cache:
        return cand_s[:, :k], cand_i[:, :k]
    out = rescore_topk(
        qf, cand_s, cand_i, cache_vecs, cache_map, k=k, metric=metric
    )
    record_launch("rescore")
    return out


# ---------------------------------------------------------------------------
# fused dispatchers (what the index search path calls)
# ---------------------------------------------------------------------------


def dense_fused_search(
    q, vectors, valid, *, k, q_b, metric, normalize, qdt, mode
):
    """One-launch dense search: raw (device or host) queries in,
    ``(scores[q_b,k], slots[q_b,k])`` out — normalize, pad, score and
    top-k all inside a single dispatch (megakernel or fused XLA per
    :func:`pick_serving_impl`)."""
    impl = pick_serving_impl(mode, vectors.shape[0], metric)
    record_launch("fused")
    if impl == "pallas":
        block_n = validate_serving_geometry(vectors.shape[0], metric)
        return _pallas_fused_dense(
            q, vectors, valid,
            k=k, q_b=q_b, metric=metric, normalize=normalize, qdt=qdt,
            block_n=block_n,
            interpret=jax.default_backend() != "tpu",
        )
    return _xla_fused_dense(
        q, vectors, valid,
        k=k, q_b=q_b, metric=metric, normalize=normalize, qdt=qdt,
    )


def quant_fused_search(
    q, codes, scales, valid, cache_vecs, cache_map,
    *, c, k, q_b, metric, normalize, use_cache, mode,
):
    """Fused quantized search: megakernel stage-1 (top-c) + the
    rescore-ring handoff as the only second launch, or — on the XLA
    lowering — the whole funnel (normalize → asymmetric scores → top-c
    → rescore) under ONE jit.  Either way ≤2 launches per tick."""
    impl = pick_serving_impl(mode, codes.shape[0], metric)
    record_launch("fused")
    if impl == "pallas":
        block_n = validate_serving_geometry(codes.shape[0], metric)
        qn, cand_s, cand_i = _pallas_fused_quant(
            q, codes, scales, valid,
            c=c, q_b=q_b, normalize=normalize, block_n=block_n,
            interpret=jax.default_backend() != "tpu",
        )
        if not use_cache:
            return cand_s[:, :k], cand_i[:, :k]
        out = rescore_topk(
            qn, cand_s, cand_i, cache_vecs, cache_map, k=k, metric=metric
        )
        record_launch("rescore")
        return out
    return _xla_fused_quant(
        q, codes, scales, valid, cache_vecs, cache_map,
        c=c, k=k, q_b=q_b, metric=metric, normalize=normalize,
        use_cache=use_cache,
    )


# observable compile counts: the fused serving sites share the
# bucket_q/bucket_k flatness contract (heterogeneous (Q, k) serving
# traffic lands on the bounded static grid, pinned by test)
from ..internals.flight_recorder import instrument_jit as _instrument_jit

_xla_fused_dense = _instrument_jit(_xla_fused_dense, "serving.fused_topk")
_pallas_fused_dense = _instrument_jit(
    _pallas_fused_dense, "serving.fused_topk_pallas"
)
_xla_fused_quant = _instrument_jit(_xla_fused_quant, "serving.fused_quant")
_pallas_fused_quant = _instrument_jit(
    _pallas_fused_quant, "serving.fused_quant_pallas"
)
_staged_topk = _instrument_jit(_staged_topk, "serving.reference_topk")
_staged_dense_scores = _instrument_jit(
    _staged_dense_scores, "serving.reference_scores"
)
_staged_quant_scores = _instrument_jit(
    _staged_quant_scores, "serving.reference_quant_scores"
)
