"""Device-side numeric ops: distance/top-k kernels, HBM KNN index, LSH.

This is the TPU replacement for the reference's CPU-side index math
(src/external_integration/brute_force_knn_integration.rs blocked ndarray
matmuls; stdlib/ml/classifiers/_knn_lsh.py numpy LSH).
"""

from .topk import masked_topk_scores, topk_search
from .knn import DeviceKnnIndex
from .lsh import LshProjector

__all__ = ["masked_topk_scores", "topk_search", "DeviceKnnIndex", "LshProjector"]
