"""Pallas fused attention for the sentence-encoder geometry.

TPU-native replacement for the HBM-round-tripping attention chain
(reference runs torch SDPA inside its embedder UDFs,
xpacks/llm/embedders.py:270; the torch kernel is cuDNN flash attention —
this is the TPU equivalent for OUR geometry).

Design (see /opt/skills/guides/pallas_guide.md):

* Encoder sequences are short (SEQ_BUCKETS caps at 512), so one
  (batch, head) tile's whole Q/K/V fits VMEM with room to spare —
  the kernel computes QK^T → mask → softmax → AV entirely in VMEM and
  writes only the [seq, head_dim] output to HBM.  No S² intermediate
  ever touches HBM, which is the entire memory win of "flash" attention;
  the streaming/online-softmax machinery only pays off when S² outgrows
  VMEM (seq ≳ 2k), which this encoder never reaches.  (For the packed
  ragged layout — one launch per tick, near-zero padding — see
  ops/ragged_attention.py, which DOES stream kv blocks.)
* Softmax accumulates in f32 regardless of input dtype (bf16 on chip).
* grid = (batch·heads,): programs tile over the FLATTENED batch×head
  axis — one grid dimension Mosaic can pipeline freely instead of a
  (batch, heads) nest whose inner dimension is tiny (12 heads), and the
  same geometry the ragged kernel launches with.  Each program owns one
  head of one row: the MXU sees [seq, head_dim] × [head_dim, seq] and
  [seq, seq] × [seq, head_dim] matmuls back-to-back.  head_dim 32
  underfills the 128-lane tile (pallas pads); the matmuls still land on
  the MXU and the S×S softmax — the part XLA-CPU/HBM handles worst —
  stays vectorized.
* Padding mask is per-key ([batch, kv]); the encoder never uses causal
  or pairwise masks.

Falls back to interpret mode off-TPU so the same code path is testable
on the CPU mesh (tests/test_models.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .ragged_attention import validate_attention_geometry

__all__ = ["flash_attention"]

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _attn_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, sm_scale: float):
    q = q_ref[0].astype(jnp.float32)  # [sq, dh]
    k = k_ref[0].astype(jnp.float32)  # [skv, dh]
    v = v_ref[0].astype(jnp.float32)  # [skv, dh]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * sm_scale
    mask = m_ref[0, 0]  # [skv]
    s = jnp.where(mask[None, :] != 0, s, _NEG_INF)
    s = s - jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = jax.lax.dot(
        p, v, preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("heads", "sm_scale", "interpret"))
def _flash(q, k, v, kv_mask, heads: int, sm_scale: float, interpret: bool):
    # q/k/v arrive flattened [batch*heads, seq, dh]: ONE grid dimension
    # tiling batch×head programs (launch-geometry rework, ISSUE 9)
    bh, sq, dh = q.shape
    skv = k.shape[1]
    grid = (bh,)

    def spec(seq):
        return pl.BlockSpec((1, seq, dh), lambda i: (i, 0, 0))

    # Mosaic requires each of a block's last two dims to be a multiple of
    # the dtype tile OR the full array dim.  A (1, skv) block over a
    # (batch, skv) mask violates that (second-minor 1 ∉ {32k, batch}), so
    # the mask rides as [batch, 1, skv]: block (1, 1, skv) has second-minor
    # == full dim 1 and minor == skv (a 128-multiple bucket) — both legal.
    # Programs i..i+heads-1 share row i // heads of the mask.
    mask_spec = pl.BlockSpec((1, 1, skv), lambda i: (i // heads, 0, 0))
    return pl.pallas_call(
        functools.partial(_attn_kernel, sm_scale=sm_scale),
        grid=grid,
        in_specs=[spec(sq), spec(skv), spec(skv), mask_spec],
        out_specs=spec(sq),
        out_shape=jax.ShapeDtypeStruct((bh, sq, dh), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * skv * dh,
            bytes_accessed=(bh * (sq + 2 * skv) * dh + bh * sq * dh)
            * q.dtype.itemsize,
            transcendentals=bh * sq * skv,
        ),
        interpret=interpret,
    )(q, k, v, kv_mask)


def flash_attention(
    query, key, value, kv_mask=None, sm_scale=None, pre_scaled: bool = False
):
    """Fused attention over flax layout ``[batch, seq, heads, head_dim]``.

    ``kv_mask``: optional per-key padding mask ``[batch, kv_len]`` (nonzero
    = attend).  Returns ``[batch, q_len, heads, head_dim]`` in the input
    dtype.  Off-TPU the kernel runs in pallas interpret mode (slow but
    exact) so correctness is testable on the CPU mesh.

    ``pre_scaled=True`` declares the caller already folded the softmax
    scale into ``query`` — combining it with an explicit ``sm_scale``
    raises instead of silently double-scaling (flax does NOT pre-scale
    when a custom ``attention_fn`` is supplied, but direct callers have
    been bitten).  Geometry is validated up front: a ``head_dim`` the
    128-lane MXU tile can't divide fails here with the knob named
    instead of deep inside Mosaic lowering.
    """
    if pre_scaled:
        if sm_scale is not None:
            raise ValueError(
                "flash_attention: pre_scaled=True with an explicit sm_scale "
                "would double-scale the logits — pass one or the other"
            )
        sm_scale = 1.0
    elif sm_scale is None:
        sm_scale = 1.0 / math.sqrt(query.shape[-1])
    validate_attention_geometry(
        int(query.shape[-1]), float(sm_scale), knob="attention_impl='pallas'"
    )
    if kv_mask is None:
        kv_mask = jnp.ones(key.shape[:2], jnp.int32)
    # int32 (not int8): sub-word dtypes hit stricter Mosaic tiling rules
    # and buy nothing here (mask is batch×skv ≤ a few KB per block)
    kv_mask = kv_mask.astype(jnp.int32)[:, None, :]
    batch, sq, heads, dh = query.shape
    skv = key.shape[1]
    # [b, s, h, d] → [b, h, s, d] → [b·h, s, d]
    q = jnp.transpose(query, (0, 2, 1, 3)).reshape(batch * heads, sq, dh)
    k = jnp.transpose(key, (0, 2, 1, 3)).reshape(batch * heads, skv, dh)
    v = jnp.transpose(value, (0, 2, 1, 3)).reshape(batch * heads, skv, dh)
    interpret = jax.default_backend() != "tpu"
    out = _flash(q, k, v, kv_mask, heads, float(sm_scale), interpret)
    out = out.reshape(batch, heads, sq, dh)
    return jnp.transpose(out, (0, 2, 1, 3))
