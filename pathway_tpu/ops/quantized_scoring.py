"""int8 symmetric-scale quantization + asymmetric-distance scoring.

The brute-force KNN scan is memory-bandwidth-bound: every search streams
the whole ``[N, D]`` resident matrix out of HBM, so bytes-per-vector is
the lever for both corpus scale and docs/s (EdgeRAG's
compression-for-retrieval observation; the banked bf16-wire A/B already
showed precision reduction paying on this exact path).  This module holds
the quantized half of ``DeviceKnnIndex``:

* **codes** — one int8 code per element with ONE f32 scale per vector
  (symmetric scalar quantization: ``v ≈ codes * scale``,
  ``scale = max|v| / 127``).  4x fewer HBM bytes than f32; for
  L2-normalized embedding rows the per-element error is ≤ scale/2
  ≈ 0.4 % of the row's max component, which keeps recall@10 ≥ 0.95
  against the f32 oracle without any rescoring;
* **asymmetric distance** — queries stay full precision (f32 host-side,
  bf16 on the MXU) and score directly against the int8 codes:
  ``score(q, v) = scale_v * (q · codes_v)``.  Only the index side is
  quantized, so query error never compounds with code error;
* **Pallas kernel** — tiles the score computation through VMEM exactly
  like ``ops/topk.pallas_masked_scores``: the int8 code tiles stream out
  of HBM (the 4x byte win IS the speedup — the dot itself runs bf16 on
  the MXU with f32 accumulation, scale + tombstone mask in the epilogue);
* **rescore cache** — a small f32 ring of the most recently written rows
  (``PATHWAY_INDEX_RESCORE_CACHE``), the latency-critical slice
  VectorLiteRAG argues deserves its own resource tier.  Stage 1 takes
  top-``c`` candidates from the quantized scores
  (``c = bucket_k(max(k, PATHWAY_INDEX_RESCORE_DEPTH))``); stage 2
  rescores candidates present in the cache against their exact f32 rows
  and re-ranks.  Rows not in the cache keep their quantized score, so
  the cache only ever sharpens the ranking.

Off-TPU an XLA reference computes the same masked scale*dot scores
(``PATHWAY_QUANT_KERNEL=auto|pallas|reference``, the
``PATHWAY_RAGGED_KERNEL`` idiom): ``auto`` picks the Pallas kernel on
TPU and the reference elsewhere, ``pallas`` forces the kernel (interpret
mode off-TPU — how tier-1 exercises the real kernel body on CPU), and
``reference`` forces the XLA path everywhere.

Snapshot records: a quantized index persists ``(codes, scale)`` per row
through the PR 6 chunked-snapshot plane (``quantize_record_np`` /
``dequantize_record``) — restore streams codes straight back into HBM
with zero re-embeds AND zero re-quantization; legacy f32 snapshots load
into a quantized index by re-coding once through the normal upsert path.
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "INDEX_DTYPES",
    "index_dtype_default",
    "resolve_index_dtype",
    "kernel_mode",
    "rescore_depth_default",
    "rescore_cache_rows_default",
    "quantize_rows_np",
    "quantize_record_np",
    "is_quant_record",
    "dequantize_record",
    "quantized_scores",
    "host_exact_scores",
    "pallas_quantized_scores",
    "quant_search",
    "rescore_topk",
    "dequant_gather",
    "quant_among_topk_search",
]

NEG_INF = -jnp.inf

INDEX_DTYPES = ("f32", "bf16", "int8")

#: snapshot-record marker key (rides a plain dict so the PR 6 pickle
#: framing needs no format-version bump; readers that predate it never
#: see one because only int8 indexes write them)
QUANT_RECORD_KEY = "__pw_sq8__"


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def index_dtype_default() -> str:
    """``PATHWAY_INDEX_DTYPE``: resident-matrix storage dtype for every
    index built without an explicit ``index_dtype=`` — ``f32`` (default),
    ``bf16`` (half the bytes, same code path), or ``int8``
    (symmetric-scale codes + asymmetric-distance scoring)."""
    raw = os.environ.get("PATHWAY_INDEX_DTYPE", "f32").strip().lower()
    if raw in INDEX_DTYPES:
        return raw
    warnings.warn(
        f"PATHWAY_INDEX_DTYPE={raw!r} is not one of "
        f"{'/'.join(INDEX_DTYPES)} — using f32",
        stacklevel=2,
    )
    return "f32"


def resolve_index_dtype(index_dtype, dtype) -> str:
    """Resolve the storage-dtype knob: explicit ``index_dtype`` wins,
    else an explicit jnp ``dtype`` maps onto the equivalent knob value,
    else the ``PATHWAY_INDEX_DTYPE`` process default."""
    if index_dtype is not None:
        value = str(index_dtype).strip().lower()
        if value not in INDEX_DTYPES:
            raise ValueError(
                f"index_dtype={index_dtype!r} is not one of "
                f"{'/'.join(INDEX_DTYPES)}"
            )
        return value
    if dtype is not None:
        if jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16):
            return "bf16"
        return "f32"
    return index_dtype_default()


def kernel_mode() -> str:
    """``PATHWAY_QUANT_KERNEL``: ``auto`` (Pallas compiled on TPU, XLA
    reference elsewhere), ``pallas`` (force the kernel; interpret mode
    off-TPU — slow but exact, how tier-1 exercises it on CPU), or
    ``reference`` (force the XLA path everywhere)."""
    raw = os.environ.get("PATHWAY_QUANT_KERNEL", "auto").strip().lower()
    if raw in ("auto", "pallas", "reference"):
        return raw
    warnings.warn(
        f"PATHWAY_QUANT_KERNEL={raw!r} is not one of auto/pallas/reference"
        " — using auto",
        stacklevel=2,
    )
    return "auto"


def rescore_depth_default() -> int:
    """``PATHWAY_INDEX_RESCORE_DEPTH`` (default 32): how many stage-1
    quantized candidates survive into the exact-rescore stage.  The
    effective depth per search is ``bucket_k(max(k, depth))`` — a larger
    ``k`` always widens the funnel with it."""
    try:
        n = int(os.environ.get("PATHWAY_INDEX_RESCORE_DEPTH", "32"))
    except ValueError:
        n = 32
    return max(n, 1)


def rescore_cache_rows_default() -> int:
    """``PATHWAY_INDEX_RESCORE_CACHE`` (default 8192; 0 disables): rows
    of the f32 rescore ring.  Sized independently of capacity on purpose
    — it is the bounded full-precision tier, not a mirror."""
    try:
        n = int(os.environ.get("PATHWAY_INDEX_RESCORE_CACHE", "8192"))
    except ValueError:
        n = 8192
    return max(n, 0)


def compute_dtype():
    """Dtype the asymmetric dot runs in: bf16 on the MXU (codes convert
    lane-local from VMEM — HBM still reads int8 bytes), f32 elsewhere
    (emulated bf16 on XLA-CPU is pathologically slow and the reference
    doubles as the parity oracle)."""
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------


def quantize_rows_np(vecs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host canonical quantizer: ``codes[i] = round(v[i] / scale_i)``
    with ``scale_i = max|v[i]| / 127``.  Elementwise arithmetic only
    (exact max, IEEE divide, round-half-even), so given identical input
    bits it produces the same codes as the jitted device quantizer."""
    v = np.asarray(vecs, dtype=np.float32)
    if v.ndim == 1:
        v = v[None, :]
    amax = np.max(np.abs(v), axis=1)
    scales = (amax / np.float32(127.0)).astype(np.float32)
    safe = np.where(scales > 0, scales, np.float32(1.0))
    codes = np.clip(np.round(v / safe[:, None]), -127, 127).astype(np.int8)
    return codes, scales


def quantize_jnp(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Device half of the canonical quantizer (same arithmetic as
    :func:`quantize_rows_np`); ``v`` is f32 ``[n, d]``."""
    amax = jnp.max(jnp.abs(v), axis=1)
    scales = amax / np.float32(127.0)
    safe = jnp.where(scales > 0, scales, 1.0)
    codes = jnp.clip(jnp.round(v / safe[:, None]), -127, 127).astype(jnp.int8)
    return codes, scales


def quantize_record_np(vec: np.ndarray, normalize: bool) -> dict:
    """Snapshot representation of one quantized row: the codes + scale
    exactly as the index stores them (``normalize`` mirrors the cos
    insert-time L2 normalization so restore is re-coding-free)."""
    v = np.asarray(vec, dtype=np.float32).reshape(-1)
    if normalize:
        norm = float(np.linalg.norm(v))
        if norm > 0:
            v = v / norm
    codes, scales = quantize_rows_np(v)
    return {
        QUANT_RECORD_KEY: 1,
        "codes": codes[0],
        "scale": np.float32(scales[0]),
    }


def is_quant_record(obj) -> bool:
    return isinstance(obj, dict) and QUANT_RECORD_KEY in obj


def dequantize_record(rec: dict) -> np.ndarray:
    """f32 row back from a snapshot record (the int8→f32/bf16 load
    direction of the snapshot round trip)."""
    return rec["codes"].astype(np.float32) * np.float32(rec["scale"])


# ---------------------------------------------------------------------------
# scoring: XLA reference + Pallas kernel
# ---------------------------------------------------------------------------


def _reference_scores(q, codes, scales, valid, metric: str) -> jax.Array:
    """XLA asymmetric-distance scores ``[Q, N]`` (higher = better).  The
    per-row reduction is a plain length-D dot, so per-shard slices of
    this computation are bit-identical to the whole-matrix form — the
    sharded local search calls this SAME function on its shard slice,
    which is what the merge's bit-exact parity rests on."""
    ct = compute_dtype()
    dots = jnp.dot(
        q.astype(ct), codes.astype(ct).T, preferred_element_type=jnp.float32
    )
    s = dots * scales[None, :]
    if metric == "l2sq":
        # -||q - v||^2 with v = codes*scale: 2 q·v - ||q||^2 - ||v||^2.
        # The code norm reduces in int32 (exact for any dim < ~133k, and
        # XLA fuses the int8→int32 widen into the reduction — no [N, D]
        # f32 materialization on a per-search quantity)
        qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        sq = jnp.sum(
            jnp.square(codes.astype(jnp.int32)), axis=-1
        ).astype(jnp.float32)
        cn = sq * (scales.astype(jnp.float32) ** 2)
        s = 2.0 * s - qn - cn[None, :]
    elif metric not in ("cos", "dot"):
        raise ValueError(f"unknown metric {metric!r}")
    return jnp.where(valid[None, :], s, NEG_INF)


def pick_block_n(n: int, cap: int = 1024) -> int | None:
    """Largest power-of-two vector-block size dividing ``n`` (≥ 32, the
    int8 sublane tile) — None when no tile fits and the kernel must
    fall back to the reference."""
    b = cap
    while b >= 32:
        if n % b == 0:
            return b
        b //= 2
    return None


def pallas_quantized_scores(
    q: jax.Array,  # [Q, D] f32 (cast to compute dtype in-kernel)
    codes: jax.Array,  # [N, D] int8
    scales: jax.Array,  # [N] f32
    valid: jax.Array,  # [N] f32 {0,1}
    *,
    block_n: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Tiled asymmetric-distance kernel: for each (query-block,
    code-block) grid cell, stream the int8 code tile from HBM, dot it
    against the resident query tile on the MXU (bf16 x bf16 → f32
    accumulate; the int8→bf16 convert is lane-local in VMEM so HBM only
    ever moves 1 byte/element), then scale + tombstone-mask in the
    epilogue.  Same launch geometry as ``ops/topk.pallas_masked_scores``
    — the grid iterates code blocks minor so each query tile stays
    resident while code tiles stream."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nq, d = q.shape
    n = codes.shape[0]
    block_q = min(nq, 256)
    if block_n is None:
        block_n = pick_block_n(n)
    assert block_n is not None and n % block_n == 0, "pad codes to block multiples"
    assert nq % block_q == 0, "pad queries to block multiples"
    ct = compute_dtype()
    qc = q.astype(ct)

    def kernel(q_ref, c_ref, s_ref, m_ref, o_ref):
        dots = jnp.dot(
            q_ref[:], c_ref[:].astype(q_ref.dtype).T,
            preferred_element_type=jnp.float32,
        )
        scored = dots * s_ref[:][None, :]
        o_ref[:] = jnp.where(m_ref[:][None, :] > 0, scored, NEG_INF)

    grid = (nq // block_q, n // block_n)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((nq, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((block_q, block_n), lambda i, j: (i, j)),
        cost_estimate=pl.CostEstimate(
            flops=2 * nq * n * d,
            bytes_accessed=n * d + n * 8 + nq * d * qc.dtype.itemsize + nq * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(qc, codes, scales, valid.astype(jnp.float32))


def quantized_scores(
    q, codes, scales, valid, metric: str, mode: str
) -> jax.Array:
    """Masked asymmetric scores ``[Q, N]``, dispatching kernel vs
    reference per ``mode`` (a static string under jit).  l2sq always
    takes the reference (the kernel is cos/dot-only, like the f32 tiled
    path); ``auto`` requires a real TPU and a fitting tile."""
    use_kernel = False
    if metric in ("cos", "dot") and pick_block_n(codes.shape[0]) is not None:
        if mode == "pallas":
            use_kernel = True
        elif mode == "auto" and jax.default_backend() == "tpu":
            use_kernel = True
    if use_kernel:
        return pallas_quantized_scores(q, codes, scales, valid)
    return _reference_scores(q, codes, scales, valid, metric)


# ---------------------------------------------------------------------------
# rescore stage
# ---------------------------------------------------------------------------


def _rescore_body(q, cand_scores, cand_idx, cache_vecs, cache_map, k, metric):
    """Stage 2: re-rank the top-c candidates, replacing the quantized
    score with the exact f32 score wherever the row is resident in the
    rescore cache.  Invalid candidates (tombstones / -inf pads) keep
    -inf — a deleted row must never resurrect through a stale cache
    entry."""
    rows = cache_map[cand_idx]  # [Q, C]
    present = (rows >= 0) & (cand_scores > NEG_INF)
    r = cache_vecs.shape[0]
    safe = jnp.clip(rows, 0, max(r - 1, 0))
    vecs = cache_vecs[safe]  # [Q, C, D]
    dots = jnp.einsum(
        "qd,qcd->qc", q.astype(jnp.float32), vecs,
        preferred_element_type=jnp.float32,
    )
    if metric == "l2sq":
        qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        vn = jnp.sum(vecs ** 2, axis=-1)
        exact = 2.0 * dots - qn - vn
    else:
        exact = dots
    final = jnp.where(present, exact, cand_scores)
    scores, pos = lax.top_k(final, k)
    idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    return scores, idx


rescore_topk = functools.partial(
    jax.jit, static_argnames=("k", "metric")
)(_rescore_body)


@functools.partial(
    jax.jit, static_argnames=("c", "k", "metric", "mode", "use_cache")
)
def quant_search(
    q,  # [Q, D] f32, pre-normalized for cos
    codes,  # [N, D] int8
    scales,  # [N] f32
    valid,  # [N] bool
    cache_vecs,  # [R, D] f32
    cache_map,  # [N] int32, -1 = not cached
    *,
    c: int,
    k: int,
    metric: str,
    mode: str,
    use_cache: bool,
):
    """One fused quantized search: asymmetric scores over all N codes →
    top-c candidates → exact rescore of cache-resident candidates →
    top-k.  ``c``/``k`` arrive bucketed (``bucket_k``) so heterogeneous
    serving (Q, k) stays on a bounded compile grid."""
    s = quantized_scores(q, codes, scales, valid, metric, mode)
    cand_scores, cand_idx = lax.top_k(s, c)
    if not use_cache:
        return cand_scores[:, :k], cand_idx[:, :k]
    return _rescore_body(q, cand_scores, cand_idx, cache_vecs, cache_map, k, metric)


# ---------------------------------------------------------------------------
# host rescore (tiered merge)
# ---------------------------------------------------------------------------


def host_exact_scores(q: np.ndarray, rows: np.ndarray, metric: str) -> np.ndarray:
    """Exact f32 scores of ONE query against gathered host-resident rows
    (``[C, D]`` → ``[C]``, higher = better) — the rescore-against-host
    half of the tiered index's merge: candidates from the HBM hot tick
    and the routed cold partitions all take their FINAL score from the
    host f32 mirror through this one function, so a key's score can
    never depend on which tier currently holds it (the invariant the
    migration-parity tests pin).  Plain numpy on purpose: the candidate
    set is bounded (top-k + probe budget), and host arithmetic is
    deterministic across restarts."""
    q = np.asarray(q, dtype=np.float32).reshape(-1)
    rows = np.asarray(rows, dtype=np.float32)
    dots = rows @ q
    if metric in ("cos", "dot"):
        return dots
    if metric == "l2sq":
        qn = np.float32(np.dot(q, q))
        vn = np.einsum("cd,cd->c", rows, rows)
        return 2.0 * dots - qn - vn
    raise ValueError(f"unknown metric {metric!r}")


# ---------------------------------------------------------------------------
# candidate-subset paths (LSH rescoring)
# ---------------------------------------------------------------------------


@jax.jit
def dequant_gather(codes, scales, idx):
    """Gathered rows dequantized to f32 (``[..., D]``) — the LSH
    candidate-rescoring paths score small gathered subsets, where the
    f32 materialization is bounded by the candidate budget."""
    return codes[idx].astype(jnp.float32) * scales[idx][..., None]


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def quant_among_topk_search(
    queries,  # [Q, D]
    codes,  # [N, D] int8
    scales,  # [N] f32
    valid,  # [N] bool
    idx,  # [Q, C] candidate slots
    pad_valid,  # [Q, C]
    k: int,
    metric: str = "cos",
):
    """Quantized twin of ``ops/topk.among_topk_search``: per-query
    candidate subsets scored against dequantized rows in ONE device
    call."""
    sub = codes[idx].astype(jnp.float32) * scales[idx][..., None]
    v = valid[idx] & pad_valid
    dots = jnp.einsum(
        "qd,qcd->qc", queries.astype(jnp.float32), sub,
        preferred_element_type=jnp.float32,
    )
    if metric in ("cos", "dot"):
        s = dots
    elif metric == "l2sq":
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        vn = jnp.sum(sub ** 2, axis=-1)
        s = 2.0 * dots - qn - vn
    else:
        raise ValueError(f"unknown metric {metric!r}")
    s = jnp.where(v, s, NEG_INF)
    return lax.top_k(s, k)


# observable compile counts: the quantized search sites share the same
# bucket_q/bucket_k flatness contract as knn.topk_search
from ..internals.flight_recorder import instrument_jit as _instrument_jit

quant_search = _instrument_jit(quant_search, "knn.quant_search")
rescore_topk = _instrument_jit(rescore_topk, "knn.quant_rescore")
quant_among_topk_search = _instrument_jit(
    quant_among_topk_search, "knn.quant_among_topk_search"
)
