"""Masked distance + top-k search kernels.

reference semantics: src/external_integration/brute_force_knn_integration.rs
(``fill_cos_distances``:69, ``fill_l2sq_distances``:91, blocked matmul with
``auxiliary_space`` bound, top-k via OrderedFloat sort).

TPU design: one fused XLA computation — score matrix on the MXU
(``queries @ vectors.T`` in bf16/f32), tombstone masking fused into the
matmul epilogue, ``lax.top_k`` on device.  A Pallas variant tiles the score
computation through VMEM for the case where the index matrix is too large
for XLA's fusion to stay in VMEM; both produce identical results and the
index picks per-backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "masked_topk_scores",
    "topk_search",
    "pallas_masked_scores",
    "bucket_k",
    "bucket_q",
]

NEG_INF = -jnp.inf


def bucket_k(k: int, cap: int) -> int:
    """Round ``k`` up to the next power of two, clamped to ``cap``.

    ``k`` is a static argument of the jitted top-k searches, so every
    distinct serving ``k`` would otherwise trigger a fresh XLA compile;
    bucketing it the same way the query/candidate dims are bucketed keeps
    compiled shapes stable — callers slice the returned (sorted) rows
    back down to the requested ``k``."""
    k = max(1, k)
    return min(cap, 1 << (k - 1).bit_length())


def bucket_q(n: int, lo: int = 8) -> int:
    """Round a query-batch size up to the next power of two (≥ ``lo``).

    Serving traffic arrives in ragged batches (whatever the scheduler
    tick collected); padding the Q dim to buckets keeps the compiled
    top-k variants to O(log) — callers slice the padded rows back off."""
    return max(lo, 1 << (max(1, n) - 1).bit_length())


def _scores(queries: jax.Array, vectors: jax.Array, metric: str) -> jax.Array:
    """Similarity scores, higher = better.  cos assumes rows pre-normalized."""
    if metric in ("cos", "dot"):
        return jnp.dot(
            queries, vectors.T, preferred_element_type=jnp.float32
        )
    if metric == "l2sq":
        # -||q - v||^2 = 2 q·v - ||q||^2 - ||v||^2 (negated: higher better)
        dots = jnp.dot(queries, vectors.T, preferred_element_type=jnp.float32)
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        vn = jnp.sum(vectors.astype(jnp.float32) ** 2, axis=-1)
        return 2.0 * dots - qn - vn[None, :]
    raise ValueError(f"unknown metric {metric!r}")


@functools.partial(jax.jit, static_argnames=("metric",))
def masked_topk_scores(
    queries: jax.Array,  # [Q, D]
    vectors: jax.Array,  # [N, D]
    valid: jax.Array,  # [N] bool — tombstone mask (False = deleted/free slot)
    metric: str = "cos",
) -> jax.Array:
    s = _scores(queries, vectors, metric)
    return jnp.where(valid[None, :], s, NEG_INF)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def topk_search(
    queries: jax.Array,
    vectors: jax.Array,
    valid: jax.Array,
    k: int,
    metric: str = "cos",
) -> tuple[jax.Array, jax.Array]:
    """Returns (scores[Q,k], indices[Q,k]); deleted slots never surface
    (their score is -inf — callers drop -inf results host-side)."""
    s = masked_topk_scores(queries, vectors, valid, metric)
    return lax.top_k(s, k)


# compile counting (pathway_xla_compile_total{site=...}): the serving
# guarantee that bucket_q/bucket_k keep compiled-program counts flat under
# heterogeneous (Q, k) traffic becomes an observable series instead of a
# test-only _cache_size() probe
from ..internals.flight_recorder import instrument_jit as _instrument_jit

topk_search = _instrument_jit(topk_search, "knn.topk_search")


# ---------------------------------------------------------------------------
# Pallas tiled variant (HBM-resident index streamed through VMEM)
# ---------------------------------------------------------------------------


def pallas_masked_scores(
    queries: jax.Array,  # [Q, D] — Q, D multiples of tile sizes
    vectors: jax.Array,  # [N, D]
    valid: jax.Array,  # [N] float32 {0,1}
    *,
    block_n: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Tiled score kernel: for each (query-block, vector-block) grid cell,
    compute q·vᵀ on the MXU and apply the tombstone mask in the epilogue.

    Used when the index matrix exceeds what XLA keeps fused in VMEM; grid
    iterates vector blocks in the minor dimension so each query tile stays
    resident while index tiles stream from HBM.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        # the Mosaic backend exists on TPU only; elsewhere (CPU mesh in
        # tests) the interpreter executes the same kernel
        interpret = jax.default_backend() != "tpu"

    q, d = queries.shape
    n = vectors.shape[0]
    block_q = min(q, 256)
    assert n % block_n == 0 and q % block_q == 0, "pad inputs to block multiples"

    def kernel(q_ref, v_ref, m_ref, o_ref):
        scores = jnp.dot(
            q_ref[:], v_ref[:].T, preferred_element_type=jnp.float32
        )
        masked = jnp.where(m_ref[:][None, :] > 0, scores, NEG_INF)
        o_ref[:] = masked

    grid = (q // block_q, n // block_n)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((q, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((block_q, block_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(queries, vectors, valid.astype(jnp.float32))


#: index sizes from which the tiled Pallas path pays for itself (smaller
#: matrices stay fused in VMEM by XLA on their own)
PALLAS_MIN_ROWS = 4096


def pallas_topk_search(
    queries: jax.Array,
    vectors: jax.Array,
    valid: jax.Array,
    k: int,
    metric: str = "cos",
) -> tuple[jax.Array, jax.Array]:
    """Tiled-score variant of :func:`topk_search` (cos/dot only — l2sq
    falls back).  Queries are padded to the query-block multiple."""
    q = queries.shape[0]
    block_q = 256
    if q > block_q and q % block_q:
        pad = block_q - q % block_q
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, queries.shape[1]), queries.dtype)]
        )
    scores = pallas_masked_scores(queries, vectors, valid)[:q]
    return lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def among_topk_search(
    queries: jax.Array,  # [Q, D]
    vectors: jax.Array,  # [N, D] full index matrix
    valid: jax.Array,  # [N] tombstone mask
    idx: jax.Array,  # [Q, C] per-query candidate slot indices
    pad_valid: jax.Array,  # [Q, C] False on padding entries
    k: int,
    metric: str = "cos",
):
    """Per-query candidate-subset top-k in ONE device call.

    The LSH rescoring path (reference: _knn_lsh.py:219-256 rescores each
    query's bucket union) previously dispatched one gather+top-k per
    query; over a remote chip that is a full RPC round trip each.  Here
    all Q candidate sets ride one gather ([Q, C, D]) and one batched
    matvec.
    """
    sub = vectors[idx]  # [Q, C, D]
    v = valid[idx] & pad_valid
    dots = jnp.einsum(
        "qd,qcd->qc", queries, sub, preferred_element_type=jnp.float32
    )
    if metric in ("cos", "dot"):
        s = dots
    elif metric == "l2sq":
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        vn = jnp.sum(sub.astype(jnp.float32) ** 2, axis=-1)
        s = 2.0 * dots - qn - vn
    else:
        raise ValueError(f"unknown metric {metric!r}")
    s = jnp.where(v, s, NEG_INF)
    return lax.top_k(s, k)


among_topk_search = _instrument_jit(among_topk_search, "knn.among_topk_search")
