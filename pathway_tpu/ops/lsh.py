"""LSH (random-projection) approximate KNN.

reference semantics: python/pathway/stdlib/ml/classifiers/_knn_lsh.py
(random projections :50-56, band/bucket grouping :64, candidate generation
via flatten+groupby :135, numpy rescoring with np.argpartition :219-256).

TPU design: signatures for all vectors are computed on device in one matmul
(``vectors @ projections > 0`` packed into per-band int64 bucket ids);
buckets are a host-side dict (pointer sets are tiny); exact rescoring of the
candidate set runs through the same fused masked top-k as the brute-force
index.  Cosine and euclidean metrics as in the reference.
"""

from __future__ import annotations

import functools
from typing import Hashable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LshProjector"]


@functools.partial(jax.jit, static_argnames=("n_or", "n_and"))
def _band_signatures(vecs: jax.Array, projections: jax.Array, n_or: int, n_and: int) -> jax.Array:
    """[B, n_or] int32 bucket ids: sign-bit signatures packed per band."""
    bits = (jnp.dot(vecs, projections.T) > 0).astype(jnp.int32)  # [B, n_or*n_and]
    bits = bits.reshape(vecs.shape[0], n_or, n_and)
    weights = (2 ** jnp.arange(n_and, dtype=jnp.int32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1)


class LshProjector:
    """Banded random-projection bucketing (reference: _knn_lsh.py
    ``lsh_projection`` / generate_band_projections)."""

    def __init__(self, dim: int, n_or: int = 8, n_and: int = 10, seed: int = 0):
        self.dim = dim
        self.n_or = n_or
        self.n_and = n_and
        key = jax.random.PRNGKey(seed)
        self.projections = jax.random.normal(key, (n_or * n_and, dim), dtype=jnp.float32)

    def signatures(self, vectors) -> np.ndarray:
        v = jnp.asarray(np.atleast_2d(np.asarray(vectors, dtype=np.float32)))
        return np.asarray(_band_signatures(v, self.projections, self.n_or, self.n_and))
