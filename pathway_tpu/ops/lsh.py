"""LSH (random-projection) approximate KNN + tier routing.

reference semantics: python/pathway/stdlib/ml/classifiers/_knn_lsh.py
(random projections :50-56, band/bucket grouping :64, candidate generation
via flatten+groupby :135, numpy rescoring with np.argpartition :219-256).

TPU design: signatures for all vectors are computed on device in one matmul
(``vectors @ projections > 0`` packed into per-band int64 bucket ids);
buckets are a host-side dict (pointer sets are tiny); exact rescoring of the
candidate set runs through the same fused masked top-k as the brute-force
index.  Cosine and euclidean metrics as in the reference.

Since the tiered index (``pathway_tpu/tiering``) this module is also the
ROUTING stage for the host-RAM cold tier: :class:`PartitionRouter` holds a
small ``[C, D]`` matrix of seeded random unit centroids (spherical LSH —
one random hyperplane codebook instead of banded sign bits), assigns every
vector to its best-scoring centroid's partition, and routes a query to the
top-``n_probe`` partitions with one tiny device matmul.  A search then
probes only the routed cold partitions instead of the whole host matrix.

Both the projector and the router are DETERMINISTIC functions of their
``spec()`` (dim, shape params, seed) — the spec rides the index snapshot's
delta-chunk header so a restored process routes queries to the very same
partitions (see stdlib/indexing/lowering.py).
"""

from __future__ import annotations

import functools
from typing import Hashable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LshProjector", "PartitionRouter"]


@functools.partial(jax.jit, static_argnames=("n_or", "n_and"))
def _band_signatures(vecs: jax.Array, projections: jax.Array, n_or: int, n_and: int) -> jax.Array:
    """[B, n_or] int32 bucket ids: sign-bit signatures packed per band."""
    bits = (jnp.dot(vecs, projections.T) > 0).astype(jnp.int32)  # [B, n_or*n_and]
    bits = bits.reshape(vecs.shape[0], n_or, n_and)
    weights = (2 ** jnp.arange(n_and, dtype=jnp.int32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1)


class LshProjector:
    """Banded random-projection bucketing (reference: _knn_lsh.py
    ``lsh_projection`` / generate_band_projections)."""

    def __init__(self, dim: int, n_or: int = 8, n_and: int = 10, seed: int = 0):
        self.dim = dim
        self.n_or = n_or
        self.n_and = n_and
        self.seed = int(seed)
        key = jax.random.PRNGKey(self.seed)
        self.projections = jax.random.normal(key, (n_or * n_and, dim), dtype=jnp.float32)

    def signatures(self, vectors) -> np.ndarray:
        v = jnp.asarray(np.atleast_2d(np.asarray(vectors, dtype=np.float32)))
        return np.asarray(_band_signatures(v, self.projections, self.n_or, self.n_and))

    # -- snapshot spec ---------------------------------------------------
    # The projections are a pure function of (dim, n_or, n_and, seed):
    # persisting the spec in the index snapshot's delta-chunk header is
    # enough for a restored process to rebuild bit-identical projections
    # and therefore route every query to the same buckets.
    def spec(self) -> dict:
        return {
            "kind": "lsh",
            "dim": self.dim,
            "n_or": self.n_or,
            "n_and": self.n_and,
            "seed": self.seed,
        }

    @classmethod
    def from_spec(cls, spec: dict) -> "LshProjector":
        if spec.get("kind") != "lsh":
            raise ValueError(f"not an LshProjector spec: {spec!r}")
        return cls(
            dim=int(spec["dim"]),
            n_or=int(spec["n_or"]),
            n_and=int(spec["n_and"]),
            seed=int(spec["seed"]),
        )


# ---------------------------------------------------------------------------
# tier routing: seeded random-centroid partitions (spherical LSH / IVF-lite)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_probe",))
def _route_topk(q: jax.Array, centroids: jax.Array, n_probe: int) -> jax.Array:
    """Top-``n_probe`` partition ids per query: one [Q, C] matmul +
    top-k over the (tiny, HBM-resident) centroid matrix."""
    scores = jnp.dot(q, centroids.T, preferred_element_type=jnp.float32)
    _, idx = jax.lax.top_k(scores, n_probe)
    return idx


@jax.jit
def _assign_argmax(v: jax.Array, centroids: jax.Array) -> jax.Array:
    """Best-scoring centroid per vector (partition assignment)."""
    return jnp.argmax(
        jnp.dot(v, centroids.T, preferred_element_type=jnp.float32), axis=-1
    ).astype(jnp.int32)


class PartitionRouter:
    """Seeded random-centroid partitioner for the cold tier.

    ``C`` random unit centroids partition the vector space; a vector
    belongs to the partition of its highest-scoring centroid, and a query
    probes the top-``n_probe`` partitions by the same score — dot against
    unit centroids, which for unit centroids is monotone with negative L2
    distance too, so one scoring rule covers cos/dot/l2sq.  Scoring runs
    on device (one ``[Q, C]`` matmul over a matrix that is kilobytes),
    per the tiering design: routing is device work, the probe it selects
    is host work.
    """

    def __init__(self, dim: int, n_partitions: int = 64, seed: int = 0):
        self.dim = int(dim)
        self.n_partitions = int(n_partitions)
        self.seed = int(seed)
        key = jax.random.PRNGKey(self.seed)
        c = jax.random.normal(key, (self.n_partitions, dim), dtype=jnp.float32)
        norm = jnp.linalg.norm(c, axis=1, keepdims=True)
        self.centroids = c / jnp.maximum(norm, 1e-30)

    def assign(self, vectors) -> np.ndarray:
        """Partition id per vector, ``[B]`` int32."""
        v = jnp.asarray(np.atleast_2d(np.asarray(vectors, dtype=np.float32)))
        return np.asarray(_assign_argmax(v, self.centroids))

    def route(self, queries, n_probe: int) -> np.ndarray:
        """Top-``n_probe`` partition ids per query, ``[Q, n_probe]``."""
        n_probe = max(1, min(int(n_probe), self.n_partitions))
        q = jnp.asarray(np.atleast_2d(np.asarray(queries, dtype=np.float32)))
        return np.asarray(_route_topk(q, self.centroids, n_probe))

    # -- snapshot spec ---------------------------------------------------
    def spec(self) -> dict:
        return {
            "kind": "router",
            "dim": self.dim,
            "n_partitions": self.n_partitions,
            "seed": self.seed,
        }

    @classmethod
    def from_spec(cls, spec: dict) -> "PartitionRouter":
        if spec.get("kind") != "router":
            raise ValueError(f"not a PartitionRouter spec: {spec!r}")
        return cls(
            dim=int(spec["dim"]),
            n_partitions=int(spec["n_partitions"]),
            seed=int(spec["seed"]),
        )
