"""Device-error classification for fault containment.

A live serving tick or ingest upsert can die three ways on an
accelerator: the XLA runtime throws (compilation/execution failure), HBM
allocation fails (``_grow``/``_apply_staged`` doubling past free memory),
or a host↔device transfer breaks (preempted TPU, dead PCIe link).  The
serving loop and the engine must never die to any of them — the
containment contract (ROADMAP: "degrade gracefully, don't fail closed")
is:

* **transient** — a single bad batch (injected chaos fault, flaky
  dispatch): trip the serving circuit breaker, degrade to the lexical
  mirror, retry via the breaker's half-open probe;
* **fatal** — the device arrays themselves are suspect (OOM, XLA runtime
  error, transfer failure): additionally rebuild the index's device
  state from the host mirror / snapshot (``DeviceKnnIndex.
  rebuild_device_arrays``) before the next probe, so recovery does not
  depend on the poisoned buffers.

Classification is name/message-based on purpose: importing
``jaxlib.xla_extension`` types here would couple the hot error path to a
specific jaxlib layout, and the strings below are stable across the
versions this repo targets.
"""

from __future__ import annotations

__all__ = ["classify_device_error", "TRANSIENT", "FATAL"]

TRANSIENT = "transient"
FATAL = "fatal"

#: exception type names raised by the XLA runtime / array transfer layer
_FATAL_TYPE_NAMES = (
    "XlaRuntimeError",
    "JaxRuntimeError",
    "InternalError",
)

#: message fragments that mean the device or its memory is gone bad
_FATAL_FRAGMENTS = (
    "resource_exhausted",
    "out of memory",
    "failed to allocate",
    "allocation failure",
    "transfer failed",
    "transfer from device",
    "device or resource busy",
    "failed precondition",
    "data_loss",
)


def classify_device_error(exc: BaseException) -> str | None:
    """``"fatal"`` / ``"transient"`` for device-plane failures, ``None``
    for everything else (plain Python bugs keep their normal routing)."""
    from ..testing.faults import FaultInjected

    if isinstance(exc, FaultInjected):
        # chaos-injected faults on device-plane sites model a flaky
        # dispatch (breaker-and-degrade territory) unless flagged fatal,
        # which models corrupted device state (quarantine/replay
        # territory); non-device sites keep their local containment
        if exc.site.startswith("device.") or exc.site == "kv.alloc":
            return FATAL if getattr(exc, "fatal", False) else TRANSIENT
        return None
    msg = str(exc).lower()
    for t in type(exc).__mro__:
        if t.__name__ in _FATAL_TYPE_NAMES:
            return FATAL
    if any(frag in msg for frag in _FATAL_FRAGMENTS):
        return FATAL
    if isinstance(exc, MemoryError):
        return FATAL
    return None
