"""``pw.persistence`` — checkpoint/resume + UDF caching.

reference: python/pathway/persistence/__init__.py (``Backend.filesystem/
s3/mock``:13-86, ``Config.simple_config``:107) over the Rust KV trait
``PersistenceBackend`` (src/persistence/backends/mod.rs:50), input
snapshots (input_snapshot.rs), operator snapshots (operator_snapshot.rs)
and metadata (state.rs:35).

Host-plane design: persistence stays on the host (the HBM index is derived
state — rebuilt by replaying the snapshot through the jit pipeline, or
restored from its own device-array dump).  Three cooperating pieces:

* a KV backend (filesystem / memory / mock — same trait shape as the
  reference);
* input snapshots: committed connector entries + per-subject offsets
  written per micro-batch, replayed before live reading on restart
  (``Entry::{Snapshot,RewindFinishSentinel}`` semantics,
  src/connectors/mod.rs:100-104);
* UDF caching: ``PersistenceMode.UDF_CACHING`` routes ``DefaultCache``
  through the configured backend (reference: vector_store.py:564-567).
"""

from __future__ import annotations

import enum
import hashlib
import json
import os
import pickle
import threading
from typing import Any, Iterable

__all__ = ["Backend", "Config", "PersistenceMode", "KVStorage"]


class PersistenceMode(enum.Enum):
    """reference: src/connectors/mod.rs:107 ``PersistenceMode``"""

    BATCH = "batch"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    UDF_CACHING = "udf_caching"
    SELECTIVE_PERSISTING = "selective_persisting"
    SPEEDRUN_REPLAY = "speedrun_replay"


class KVStorage:
    """KV trait (reference: persistence/backends/mod.rs:50 — get/put/
    list_keys/remove over fs, S3 or memory)."""

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class FilesystemKV(KVStorage):
    # keys are percent-encoded into flat filenames: injective (unlike a bare
    # '/'→'__' swap) and reversible via unquote
    def __init__(self, root: str):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)

    @staticmethod
    def _escape(key: str) -> str:
        from urllib.parse import quote

        return quote(key, safe="")

    @staticmethod
    def _unescape(name: str) -> str:
        from urllib.parse import unquote

        return unquote(name)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, self._escape(key))

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list_keys(self, prefix: str = "") -> list[str]:
        keys = (
            self._unescape(name)
            for name in os.listdir(self.root)
            if not name.endswith(".tmp")
        )
        return sorted(k for k in keys if k.startswith(prefix))


class MemoryKV(KVStorage):
    def __init__(self):
        self._store: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._store.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._store[key] = value

    def remove(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def list_keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._store if k.startswith(prefix))


class S3KV(KVStorage):
    """Object-store KV over a boto3-style S3 client (reference:
    src/persistence/backends/s3.rs — put_object/get_object/delete_object/
    list_objects under one key prefix).  The client is injectable so tests
    (and minio/moto deployments) can supply their own."""

    def __init__(self, client: Any, bucket: str, prefix: str = ""):
        self.client = client
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    @staticmethod
    def _is_missing(exc: Exception) -> bool:
        # only a key-level absence reads as "no snapshot"; bucket
        # misconfiguration or transient/client failures must surface, not
        # silently recover-from-scratch (duplicating side effects)
        if type(exc).__name__ == "NoSuchKey":
            return True
        code = getattr(exc, "response", {}) or {}
        code = code.get("Error", {}).get("Code") if isinstance(code, dict) else None
        return code in ("NoSuchKey", "404", "NotFound")

    def get(self, key: str) -> bytes | None:
        try:
            obj = self.client.get_object(Bucket=self.bucket, Key=self._key(key))
        except Exception as exc:  # noqa: BLE001 — classify boto3 error codes
            if self._is_missing(exc):
                return None
            raise
        body = obj["Body"]
        return body.read() if hasattr(body, "read") else body

    def put(self, key: str, value: bytes) -> None:
        self.client.put_object(Bucket=self.bucket, Key=self._key(key), Body=value)

    def remove(self, key: str) -> None:
        try:
            self.client.delete_object(Bucket=self.bucket, Key=self._key(key))
        except Exception as exc:  # noqa: BLE001
            if not self._is_missing(exc):
                raise

    def list_keys(self, prefix: str = "") -> list[str]:
        full = self._key(prefix)
        out: list[str] = []
        token: str | None = None
        while True:
            kwargs = dict(Bucket=self.bucket, Prefix=full)
            if token:
                kwargs["ContinuationToken"] = token
            resp = self.client.list_objects_v2(**kwargs)
            for item in resp.get("Contents", []):
                key = item["Key"]
                if self.prefix:
                    key = key[len(self.prefix) + 1 :]
                out.append(key)
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        return sorted(out)


class AzureBlobKV(KVStorage):
    """KV over an azure-storage-blob ContainerClient (reference:
    persistence/__init__.py azure backend); client injectable for tests."""

    def __init__(self, container_client: Any, prefix: str = ""):
        self.container = container_client
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    @staticmethod
    def _is_missing(exc: Exception) -> bool:
        # a transient network/auth failure must NOT look like a missing
        # blob — that would silently restart recovery from scratch
        if type(exc).__name__ in ("ResourceNotFoundError", "FileNotFoundError"):
            return True
        return getattr(exc, "status_code", None) == 404

    def get(self, key: str) -> bytes | None:
        try:
            return self.container.download_blob(self._key(key)).readall()
        except Exception as exc:  # noqa: BLE001 — classify Azure error kinds
            if self._is_missing(exc):
                return None
            raise

    def put(self, key: str, value: bytes) -> None:
        self.container.upload_blob(self._key(key), value, overwrite=True)

    def remove(self, key: str) -> None:
        try:
            self.container.delete_blob(self._key(key))
        except Exception as exc:  # noqa: BLE001
            if not self._is_missing(exc):
                raise

    def list_keys(self, prefix: str = "") -> list[str]:
        full = self._key(prefix)
        names = [b.name for b in self.container.list_blobs(name_starts_with=full)]
        if self.prefix:
            names = [n[len(self.prefix) + 1 :] for n in names]
        return sorted(names)


class Backend:
    """Factory wrapper (reference: persistence/__init__.py:13)."""

    def __init__(self, storage: KVStorage, fs_path: str | None = None):
        self._storage = storage
        self.fs_path = fs_path

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(FilesystemKV(os.fspath(path)), fs_path=os.fspath(path))

    @classmethod
    def memory(cls) -> "Backend":
        return cls(MemoryKV())

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        """reference: persistence/__init__.py:71 / backends/mock.rs"""
        return cls(MemoryKV())

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings: Any = None,
        *,
        client: Any = None,
    ) -> "Backend":
        """``root_path`` is ``s3://bucket/prefix`` or a bare prefix when
        ``bucket_settings``/``client`` carries the bucket (reference:
        persistence/__init__.py:40-66 Backend.s3 + backends/s3.rs).
        Pass ``client`` to inject a boto3-compatible client (minio, moto)."""
        bucket = None
        prefix = root_path or ""
        if prefix.startswith("s3://"):
            rest = prefix[len("s3://"):]
            bucket, _, prefix = rest.partition("/")
        if bucket_settings is not None:
            bucket = getattr(bucket_settings, "bucket_name", None) or bucket
            if client is None and hasattr(bucket_settings, "client"):
                client = bucket_settings.client()
        if client is None:
            try:
                import boto3
            except ImportError as exc:
                raise ImportError(
                    "S3 persistence backend requires boto3 (or pass client=)"
                ) from exc
            client = boto3.client("s3")
        if not bucket:
            raise ValueError("S3 backend: bucket name missing (s3://bucket/... )")
        return cls(S3KV(client, bucket, prefix))

    @classmethod
    def azure(
        cls, root_path: str = "", *, container_client: Any = None, **kwargs
    ) -> "Backend":
        if container_client is None:
            raise ImportError(
                "Azure persistence backend requires an azure-storage-blob "
                "ContainerClient (pass container_client=)"
            )
        return cls(AzureBlobKV(container_client, root_path))

    @property
    def storage(self) -> KVStorage:
        return self._storage


class Config:
    """reference: persistence/__init__.py:88 ``Config`` +
    ``simple_config``:107."""

    def __init__(
        self,
        backend: Backend,
        *,
        persistence_mode: "PersistenceMode | str" = PersistenceMode.PERSISTING,
        snapshot_interval_ms: int = 0,
        continue_after_replay: bool = True,
    ):
        if isinstance(persistence_mode, str):
            persistence_mode = PersistenceMode[persistence_mode.upper()]
        self.backend = backend
        self.persistence_mode = persistence_mode
        self.snapshot_interval_ms = snapshot_interval_ms
        self.continue_after_replay = continue_after_replay

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend, **kwargs)


# ---------------------------------------------------------------------------
# active-run context: set by pw.run, consulted by DefaultCache and the
# streaming driver's snapshot writer
# ---------------------------------------------------------------------------

_active_stack: list["Config"] = []
_active_lock = threading.Lock()


#: on-disk format version.  Bump whenever key derivation or the snapshot
#: layout changes incompatibly — replaying a snapshot whose row keys were
#: derived by an older scheme against freshly-derived keys silently
#: duplicates rows instead of replacing them.  History: 1 = rounds 1-3
#: (FNV fast mix covered raw-int tuples); 2 = round 4 (raw-int tuples
#: route through BLAKE2b, ADVICE r3).
FORMAT_VERSION = 2
_FORMAT_KEY = "format/version"


def check_format_version(storage: "KVStorage") -> None:
    """Stamp a fresh store with the current format version; refuse a store
    written by an incompatible one (reference: persistence metadata
    version gate, persistence/state.rs:35)."""
    raw = storage.get(_FORMAT_KEY)
    if raw is None:
        if storage.list_keys("snap/") or storage.list_keys("opstate/"):
            raise RuntimeError(
                "persistent storage holds snapshots written before format "
                f"versioning (current version {FORMAT_VERSION}); their row "
                "keys are incompatible with this build's key derivation — "
                "resuming would silently duplicate rows. Clear the storage "
                "location (or point persistence at a fresh one) and rerun."
            )
        storage.put(_FORMAT_KEY, str(FORMAT_VERSION).encode())
        return
    found = int(raw.decode())
    if found != FORMAT_VERSION:
        raise RuntimeError(
            f"persistent storage format version {found} does not match "
            f"this build's version {FORMAT_VERSION} — snapshot row keys "
            "are incompatible. Clear the storage location (or point "
            "persistence at a fresh one) and rerun."
        )


def activate(config: "Config | None") -> None:
    """Push a run's config; ``deactivate`` removes exactly that config, so a
    run ending never clears a concurrently-running server's config (runs can
    overlap when servers run on threads — the top of the stack wins while
    they do)."""
    if config is not None:
        check_format_version(config.backend.storage)
        with _active_lock:
            _active_stack.append(config)


def deactivate(config: "Config | None") -> None:
    if config is not None:
        with _active_lock:
            for i in range(len(_active_stack) - 1, -1, -1):
                if _active_stack[i] is config:
                    del _active_stack[i]
                    break


def active_config() -> "Config | None":
    with _active_lock:
        return _active_stack[-1] if _active_stack else None


def udf_cache_storage() -> KVStorage | None:
    """Backend KV for UDF caching when a config with UDF_CACHING (or full
    persistence) is active."""
    cfg = active_config()
    if cfg is None:
        return None
    if cfg.persistence_mode in (
        PersistenceMode.UDF_CACHING,
        PersistenceMode.PERSISTING,
        PersistenceMode.OPERATOR_PERSISTING,
    ):
        return cfg.backend.storage
    return None


# ---------------------------------------------------------------------------
# input snapshots (reference: persistence/input_snapshot.rs:56-283)
# ---------------------------------------------------------------------------


class InputSnapshotWriter:
    """Per-subject event log + offset frontier, chunked per micro-batch."""

    def __init__(self, storage: KVStorage, persistent_id: str):
        self.storage = storage
        self.pid = persistent_id
        self._chunk = 0
        existing = storage.list_keys(f"snap/{persistent_id}/chunk-")
        if existing:
            self._chunk = (
                max(int(k.rsplit("-", 1)[1]) for k in existing) + 1
            )

    def write_batch(self, entries: list, offsets: Any) -> None:
        payload = pickle.dumps({"entries": entries, "offsets": offsets})
        self.storage.put(f"snap/{self.pid}/chunk-{self._chunk:08d}", payload)
        self._chunk += 1

    def frontier(self) -> Any:
        """Latest stored offsets, or None if no snapshot exists."""
        keys = self.storage.list_keys(f"snap/{self.pid}/chunk-")
        if not keys:
            return None
        data = self.storage.get(keys[-1])
        return pickle.loads(data)["offsets"] if data else None


class InputSnapshotReader:
    """Replays all stored chunks (``Entry::Snapshot`` …
    ``RewindFinishSentinel`` replay, src/connectors/mod.rs:100-104)."""

    def __init__(self, storage: KVStorage, persistent_id: str):
        self.storage = storage
        self.pid = persistent_id

    def replay(self) -> Iterable[list]:
        for key in self.storage.list_keys(f"snap/{self.pid}/chunk-"):
            data = self.storage.get(key)
            if data:
                yield pickle.loads(data)["entries"]

    def last_offsets(self) -> Any:
        keys = self.storage.list_keys(f"snap/{self.pid}/chunk-")
        if not keys:
            return None
        data = self.storage.get(keys[-1])
        return pickle.loads(data)["offsets"] if data else None


# ---------------------------------------------------------------------------
# operator snapshots (reference: persistence/operator_snapshot.rs:21-37)
# ---------------------------------------------------------------------------


class OperatorSnapshot:
    """State dump for stateful operators keyed by persistent_id."""

    def __init__(self, storage: KVStorage):
        self.storage = storage

    def save(self, persistent_id: str, state: Any) -> None:
        self.storage.put(f"opstate/{persistent_id}", pickle.dumps(state))

    def load(self, persistent_id: str) -> Any:
        data = self.storage.get(f"opstate/{persistent_id}")
        return pickle.loads(data) if data else None
