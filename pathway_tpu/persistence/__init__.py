"""``pw.persistence`` — checkpoint/resume + UDF caching.

reference: python/pathway/persistence/__init__.py (``Backend.filesystem/
s3/mock``:13-86, ``Config.simple_config``:107) over the Rust KV trait
``PersistenceBackend`` (src/persistence/backends/mod.rs:50), input
snapshots (input_snapshot.rs), operator snapshots (operator_snapshot.rs)
and metadata (state.rs:35).

Host-plane design: persistence stays on the host (the HBM index is derived
state — rebuilt by replaying the snapshot through the jit pipeline, or
restored from its own device-array dump).  Three cooperating pieces:

* a KV backend (filesystem / memory / mock — same trait shape as the
  reference);
* input snapshots: committed connector entries + per-subject offsets
  written per micro-batch, replayed before live reading on restart
  (``Entry::{Snapshot,RewindFinishSentinel}`` semantics,
  src/connectors/mod.rs:100-104);
* UDF caching: ``PersistenceMode.UDF_CACHING`` routes ``DefaultCache``
  through the configured backend (reference: vector_store.py:564-567).
* operator snapshots: stateful operators (deduplicate, persistent
  groupby state, request/reply zips, and the live vector index — whose
  deltas carry ALREADY-COMPUTED embeddings so restore costs zero
  encoder calls) checkpoint through :class:`ChunkedOperatorSnapshot` —
  per-commit **delta chunks** with background merge compaction
  (reference: operator_snapshot.rs:21-37 chunked writes keyed by
  finalized time, compaction at :337).

Chunked operator-snapshot on-disk format (format version >= 2)::

    opstate/{pid}/chunk-NNNNNNNN   (NNNNNNNN = zero-padded decimal seq)

Every chunk (operator and input-snapshot alike) is framed for
integrity: ``b"PWSC" + blake2b-16(payload) + payload``.  A corrupt or
truncated chunk fails restore with :class:`SnapshotCorruption` (key
name, expected/actual digest) instead of an unpickling crash; frameless
chunks written by earlier builds still read (the framing is
backward-compatible, FORMAT_VERSION unchanged).

Each chunk payload is a pickled dict.  Delta chunks are
``{"kind": "delta", "time": t, "upserts": {k: v}, "deletes": [k, ...]}``
— the net state-key changes of one finalized engine timestamp, so a
commit costs O(changed keys), not O(state).  Compaction merges the run
of chunks into one ``{"kind": "base", "time": t, "state": {...}}`` chunk
written at the *next* sequence number, then removes the merged chunks;
because the base is written before anything is deleted, a crash at any
point leaves a readable store.  Restore replays base + later deltas in
sequence order.

Migration: the pre-chunk format stored one pickled blob of the whole
state at ``opstate/{pid}`` (see :class:`OperatorSnapshot`, kept as the
legacy writer).  :meth:`ChunkedOperatorSnapshot.load` treats such a blob
as the implicit base below every chunk, so old stores restore unchanged;
the first compaction folds the blob into a base chunk and removes it.
"""

from __future__ import annotations

import enum
import hashlib
import json
import os
import pickle
import threading
from typing import Any, Iterable

__all__ = [
    "Backend",
    "Config",
    "PersistenceMode",
    "KVStorage",
    "ChunkedOperatorSnapshot",
    "OperatorSnapshot",
    "SnapshotCorruption",
]


class SnapshotCorruption(RuntimeError):
    """A snapshot chunk failed its integrity check (corrupt or truncated).

    Raised with the chunk's key and the expected/actual digests so the
    operator can locate the bad object instead of debugging a pickle
    traceback from the middle of a restore."""


#: integrity framing for snapshot chunks: ``MAGIC + blake2b-16(payload)
#: + payload``.  Chunks written before this framing existed (no magic)
#: are read as-is — the format stays backward compatible, so
#: FORMAT_VERSION is unchanged.
_CHUNK_MAGIC = b"PWSC"
_CHUNK_DIGEST_SIZE = 16


def _seal_chunk(payload: bytes) -> bytes:
    digest = hashlib.blake2b(payload, digest_size=_CHUNK_DIGEST_SIZE).digest()
    return _CHUNK_MAGIC + digest + payload


def _open_chunk(key: str, data: bytes) -> bytes:
    """Verify and strip the integrity frame; legacy frameless chunks pass
    through.  A corrupt or truncated chunk raises :class:`SnapshotCorruption`
    naming the key and both digests."""
    if not data.startswith(_CHUNK_MAGIC):
        return data  # legacy chunk written before checksum framing
    head = len(_CHUNK_MAGIC) + _CHUNK_DIGEST_SIZE
    if len(data) < head:
        raise SnapshotCorruption(
            f"snapshot chunk {key!r} is truncated: {len(data)} bytes is "
            f"shorter than the {head}-byte integrity header. The chunk was "
            "cut off mid-write — restore from a replica or remove the key "
            "to fall back to replay."
        )
    expected = data[len(_CHUNK_MAGIC):head]
    payload = data[head:]
    actual = hashlib.blake2b(payload, digest_size=_CHUNK_DIGEST_SIZE).digest()
    if actual != expected:
        raise SnapshotCorruption(
            f"snapshot chunk {key!r} failed its integrity check: expected "
            f"blake2b {expected.hex()}, got {actual.hex()} over "
            f"{len(payload)} payload bytes. The chunk is corrupt or "
            "truncated — restore it from a replica or remove the key to "
            "fall back to replay."
        )
    return payload


class PersistenceMode(enum.Enum):
    """reference: src/connectors/mod.rs:107 ``PersistenceMode``"""

    BATCH = "batch"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    UDF_CACHING = "udf_caching"
    SELECTIVE_PERSISTING = "selective_persisting"
    SPEEDRUN_REPLAY = "speedrun_replay"


class KVStorage:
    """KV trait (reference: persistence/backends/mod.rs:50 — get/put/
    list_keys/remove over fs, S3 or memory)."""

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class FilesystemKV(KVStorage):
    # keys are percent-encoded into flat filenames: injective (unlike a bare
    # '/'→'__' swap) and reversible via unquote
    def __init__(self, root: str):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._sweep_stale_tmp()

    #: tmp files younger than this are never touched — cheap first
    #: filter before the pid-liveness check
    _TMP_STALE_S = 60.0

    def _sweep_stale_tmp(self) -> None:
        """Remove orphaned ``*.tmp`` files left by writers that died
        between write and ``os.replace`` (a sudden kill mid-``put``).
        Age alone is not proof of death — under heavy load a live writer
        can stall arbitrarily long mid-``put``, and deleting its tmp
        would make its ``os.replace`` die silently — so a file is only
        swept when the pid embedded in its name (``{key}.{pid}-{tid}.tmp``)
        is no longer alive on this host.  Unparseable names (the old
        fixed ``.tmp`` suffix) sweep on age alone."""
        import time as _t

        cutoff = _t.time() - self._TMP_STALE_S
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if not name.endswith(".tmp"):
                continue
            path = os.path.join(self.root, name)
            try:
                if os.path.getmtime(path) >= cutoff:
                    continue
                pid = int(name[: -len(".tmp")].rsplit(".", 1)[-1].split("-", 1)[0])
                os.kill(pid, 0)  # raises ProcessLookupError if dead
            except (ValueError, ProcessLookupError):
                try:
                    os.remove(path)
                except OSError:
                    pass  # concurrent sweep — fine
            except OSError:
                pass  # writer alive (or liveness unknowable): leave it

    @staticmethod
    def _escape(key: str) -> str:
        from urllib.parse import quote

        return quote(key, safe="")

    @staticmethod
    def _unescape(name: str) -> str:
        from urllib.parse import unquote

        return unquote(name)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, self._escape(key))

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        # unique tmp name per writer: two processes putting the same key
        # concurrently (e.g. both stamping format/version on a fresh
        # store at startup) must not race on one shared tmp file — with a
        # fixed name the loser's os.replace throws FileNotFoundError
        # after the winner's replace consumed the tmp
        tmp = f"{path}.{os.getpid()}-{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list_keys(self, prefix: str = "") -> list[str]:
        keys = (
            self._unescape(name)
            for name in os.listdir(self.root)
            if not name.endswith(".tmp")
        )
        return sorted(k for k in keys if k.startswith(prefix))


class MemoryKV(KVStorage):
    def __init__(self):
        self._store: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._store.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._store[key] = value

    def remove(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def list_keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._store if k.startswith(prefix))


class S3KV(KVStorage):
    """Object-store KV over a boto3-style S3 client (reference:
    src/persistence/backends/s3.rs — put_object/get_object/delete_object/
    list_objects under one key prefix).  The client is injectable so tests
    (and minio/moto deployments) can supply their own."""

    def __init__(self, client: Any, bucket: str, prefix: str = ""):
        self.client = client
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    @staticmethod
    def _is_missing(exc: Exception) -> bool:
        # only a key-level absence reads as "no snapshot"; bucket
        # misconfiguration or transient/client failures must surface, not
        # silently recover-from-scratch (duplicating side effects)
        if type(exc).__name__ == "NoSuchKey":
            return True
        code = getattr(exc, "response", {}) or {}
        code = code.get("Error", {}).get("Code") if isinstance(code, dict) else None
        return code in ("NoSuchKey", "404", "NotFound")

    def get(self, key: str) -> bytes | None:
        try:
            obj = self.client.get_object(Bucket=self.bucket, Key=self._key(key))
        except Exception as exc:  # noqa: BLE001 — classify boto3 error codes
            if self._is_missing(exc):
                return None
            raise
        body = obj["Body"]
        return body.read() if hasattr(body, "read") else body

    def put(self, key: str, value: bytes) -> None:
        self.client.put_object(Bucket=self.bucket, Key=self._key(key), Body=value)

    def remove(self, key: str) -> None:
        try:
            self.client.delete_object(Bucket=self.bucket, Key=self._key(key))
        except Exception as exc:  # noqa: BLE001
            if not self._is_missing(exc):
                raise

    def list_keys(self, prefix: str = "") -> list[str]:
        full = self._key(prefix)
        out: list[str] = []
        token: str | None = None
        while True:
            kwargs = dict(Bucket=self.bucket, Prefix=full)
            if token:
                kwargs["ContinuationToken"] = token
            resp = self.client.list_objects_v2(**kwargs)
            for item in resp.get("Contents", []):
                key = item["Key"]
                if self.prefix:
                    key = key[len(self.prefix) + 1 :]
                out.append(key)
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        return sorted(out)


class AzureBlobKV(KVStorage):
    """KV over an azure-storage-blob ContainerClient (reference:
    persistence/__init__.py azure backend); client injectable for tests."""

    def __init__(self, container_client: Any, prefix: str = ""):
        self.container = container_client
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    @staticmethod
    def _is_missing(exc: Exception) -> bool:
        # a transient network/auth failure must NOT look like a missing
        # blob — that would silently restart recovery from scratch
        if type(exc).__name__ in ("ResourceNotFoundError", "FileNotFoundError"):
            return True
        return getattr(exc, "status_code", None) == 404

    def get(self, key: str) -> bytes | None:
        try:
            return self.container.download_blob(self._key(key)).readall()
        except Exception as exc:  # noqa: BLE001 — classify Azure error kinds
            if self._is_missing(exc):
                return None
            raise

    def put(self, key: str, value: bytes) -> None:
        self.container.upload_blob(self._key(key), value, overwrite=True)

    def remove(self, key: str) -> None:
        try:
            self.container.delete_blob(self._key(key))
        except Exception as exc:  # noqa: BLE001
            if not self._is_missing(exc):
                raise

    def list_keys(self, prefix: str = "") -> list[str]:
        full = self._key(prefix)
        names = [b.name for b in self.container.list_blobs(name_starts_with=full)]
        if self.prefix:
            names = [n[len(self.prefix) + 1 :] for n in names]
        return sorted(names)


class Backend:
    """Factory wrapper (reference: persistence/__init__.py:13)."""

    def __init__(self, storage: KVStorage, fs_path: str | None = None):
        self._storage = storage
        self.fs_path = fs_path

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(FilesystemKV(os.fspath(path)), fs_path=os.fspath(path))

    @classmethod
    def memory(cls) -> "Backend":
        return cls(MemoryKV())

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        """reference: persistence/__init__.py:71 / backends/mock.rs"""
        return cls(MemoryKV())

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings: Any = None,
        *,
        client: Any = None,
    ) -> "Backend":
        """``root_path`` is ``s3://bucket/prefix`` or a bare prefix when
        ``bucket_settings``/``client`` carries the bucket (reference:
        persistence/__init__.py:40-66 Backend.s3 + backends/s3.rs).
        Pass ``client`` to inject a boto3-compatible client (minio, moto)."""
        bucket = None
        prefix = root_path or ""
        if prefix.startswith("s3://"):
            rest = prefix[len("s3://"):]
            bucket, _, prefix = rest.partition("/")
        if bucket_settings is not None:
            bucket = getattr(bucket_settings, "bucket_name", None) or bucket
            if client is None and hasattr(bucket_settings, "client"):
                client = bucket_settings.client()
        if client is None:
            try:
                import boto3
            except ImportError as exc:
                raise ImportError(
                    "S3 persistence backend requires boto3 (or pass client=)"
                ) from exc
            client = boto3.client("s3")
        if not bucket:
            raise ValueError("S3 backend: bucket name missing (s3://bucket/... )")
        return cls(S3KV(client, bucket, prefix))

    @classmethod
    def azure(
        cls, root_path: str = "", *, container_client: Any = None, **kwargs
    ) -> "Backend":
        if container_client is None:
            raise ImportError(
                "Azure persistence backend requires an azure-storage-blob "
                "ContainerClient (pass container_client=)"
            )
        return cls(AzureBlobKV(container_client, root_path))

    @property
    def storage(self) -> KVStorage:
        return self._storage


class Config:
    """reference: persistence/__init__.py:88 ``Config`` +
    ``simple_config``:107."""

    def __init__(
        self,
        backend: Backend,
        *,
        persistence_mode: "PersistenceMode | str" = PersistenceMode.PERSISTING,
        snapshot_interval_ms: int = 0,
        continue_after_replay: bool = True,
    ):
        if isinstance(persistence_mode, str):
            persistence_mode = PersistenceMode[persistence_mode.upper()]
        self.backend = backend
        self.persistence_mode = persistence_mode
        self.snapshot_interval_ms = snapshot_interval_ms
        self.continue_after_replay = continue_after_replay

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend, **kwargs)


# ---------------------------------------------------------------------------
# active-run context: set by pw.run, consulted by DefaultCache and the
# streaming driver's snapshot writer
# ---------------------------------------------------------------------------

_active_stack: list["Config"] = []
_active_lock = threading.Lock()


#: on-disk format version.  Bump whenever key derivation or the snapshot
#: layout changes incompatibly — replaying a snapshot whose row keys were
#: derived by an older scheme against freshly-derived keys silently
#: duplicates rows instead of replacing them.  History: 1 = rounds 1-3
#: (FNV fast mix covered raw-int tuples); 2 = round 4 (raw-int tuples
#: route through BLAKE2b, ADVICE r3).
FORMAT_VERSION = 2
_FORMAT_KEY = "format/version"


def check_format_version(storage: "KVStorage") -> None:
    """Stamp a fresh store with the current format version; refuse a store
    written by an incompatible one (reference: persistence metadata
    version gate, persistence/state.rs:35)."""
    raw = storage.get(_FORMAT_KEY)
    if raw is None:
        if storage.list_keys("snap/") or storage.list_keys("opstate/"):
            raise RuntimeError(
                "persistent storage holds snapshots written before format "
                f"versioning (current version {FORMAT_VERSION}); their row "
                "keys are incompatible with this build's key derivation — "
                "resuming would silently duplicate rows. Clear the storage "
                "location (or point persistence at a fresh one) and rerun."
            )
        storage.put(_FORMAT_KEY, str(FORMAT_VERSION).encode())
        return
    found = int(raw.decode())
    if found != FORMAT_VERSION:
        raise RuntimeError(
            f"persistent storage format version {found} does not match "
            f"this build's version {FORMAT_VERSION} — snapshot row keys "
            "are incompatible. Clear the storage location (or point "
            "persistence at a fresh one) and rerun."
        )


def activate(config: "Config | None") -> None:
    """Push a run's config; ``deactivate`` removes exactly that config, so a
    run ending never clears a concurrently-running server's config (runs can
    overlap when servers run on threads — the top of the stack wins while
    they do)."""
    if config is not None:
        check_format_version(config.backend.storage)
        with _active_lock:
            _active_stack.append(config)


def deactivate(config: "Config | None") -> None:
    if config is not None:
        with _active_lock:
            for i in range(len(_active_stack) - 1, -1, -1):
                if _active_stack[i] is config:
                    del _active_stack[i]
                    break


def active_config() -> "Config | None":
    with _active_lock:
        return _active_stack[-1] if _active_stack else None


def udf_cache_storage() -> KVStorage | None:
    """Backend KV for UDF caching when a config with UDF_CACHING (or full
    persistence) is active."""
    cfg = active_config()
    if cfg is None:
        return None
    if cfg.persistence_mode in (
        PersistenceMode.UDF_CACHING,
        PersistenceMode.PERSISTING,
        PersistenceMode.OPERATOR_PERSISTING,
    ):
        return cfg.backend.storage
    return None


# ---------------------------------------------------------------------------
# input snapshots (reference: persistence/input_snapshot.rs:56-283)
# ---------------------------------------------------------------------------


class InputSnapshotWriter:
    """Per-subject event log + offset frontier, chunked per micro-batch."""

    def __init__(self, storage: KVStorage, persistent_id: str):
        self.storage = storage
        self.pid = persistent_id
        self._chunk = 0
        existing = storage.list_keys(f"snap/{persistent_id}/chunk-")
        if existing:
            self._chunk = (
                max(int(k.rsplit("-", 1)[1]) for k in existing) + 1
            )

    def write_batch(self, entries: list, offsets: Any) -> None:
        payload = pickle.dumps({"entries": entries, "offsets": offsets})
        self.storage.put(
            f"snap/{self.pid}/chunk-{self._chunk:08d}", _seal_chunk(payload)
        )
        self._chunk += 1

    def frontier(self) -> Any:
        """Latest stored offsets, or None if no snapshot exists."""
        keys = self.storage.list_keys(f"snap/{self.pid}/chunk-")
        if not keys:
            return None
        data = self.storage.get(keys[-1])
        return pickle.loads(_open_chunk(keys[-1], data))["offsets"] if data else None


class InputSnapshotReader:
    """Replays all stored chunks (``Entry::Snapshot`` …
    ``RewindFinishSentinel`` replay, src/connectors/mod.rs:100-104)."""

    def __init__(self, storage: KVStorage, persistent_id: str):
        self.storage = storage
        self.pid = persistent_id

    def replay(self) -> Iterable[list]:
        for key in self.storage.list_keys(f"snap/{self.pid}/chunk-"):
            data = self.storage.get(key)
            if data:
                yield pickle.loads(_open_chunk(key, data))["entries"]

    def last_offsets(self) -> Any:
        keys = self.storage.list_keys(f"snap/{self.pid}/chunk-")
        if not keys:
            return None
        data = self.storage.get(keys[-1])
        return pickle.loads(_open_chunk(keys[-1], data))["offsets"] if data else None


# ---------------------------------------------------------------------------
# operator snapshots (reference: persistence/operator_snapshot.rs:21-37)
# ---------------------------------------------------------------------------


class OperatorSnapshot:
    """Legacy whole-state dump for stateful operators keyed by
    persistent_id (single pickled blob per save — O(state) bytes per
    commit).  Kept for migration: :class:`ChunkedOperatorSnapshot.load`
    reads blobs written by this writer."""

    def __init__(self, storage: KVStorage):
        self.storage = storage

    def save(self, persistent_id: str, state: Any) -> None:
        self.storage.put(f"opstate/{persistent_id}", pickle.dumps(state))

    def load(self, persistent_id: str) -> Any:
        data = self.storage.get(f"opstate/{persistent_id}")
        return pickle.loads(data) if data else None


class ChunkedOperatorSnapshot:
    """Incremental operator-state plane: per-commit delta chunks +
    merge compaction (module docstring documents the on-disk format;
    reference: persistence/operator_snapshot.rs:21-37, compaction :337).

    Writers call :meth:`save_delta` once per finalized timestamp with the
    net upserted/deleted state keys — O(delta) bytes per commit instead
    of the O(state) the legacy :class:`OperatorSnapshot` paid.  Once the
    delta entries written since the last base exceed the live state size
    (the same amortization argument as ``DeviceKnnIndex._maybe_compact``:
    a compaction writes O(live) entries and is charged to the >= live
    delta entries that made it necessary), the chunk run is merged into
    one base chunk — total stored bytes stay O(live state).  Compaction
    runs on a background thread by default so the engine's commit path
    never blocks on the merge.
    """

    #: compact when delta entries since the last base exceed this
    #: multiple of the live entry count (1.0 == dead fraction ~50%)
    COMPACT_DEAD_RATIO = 1.0
    #: never compact a run shorter than this many chunks (a tiny state
    #: would otherwise compact on every commit)
    MIN_COMPACT_CHUNKS = 4

    def __init__(self, storage: KVStorage, *, background: bool = True):
        self.storage = storage
        self.background = background
        self._master = threading.Lock()
        # pid -> [next_seq, delta_entries_since_base, compaction_inflight,
        #         delta_chunks_since_base]
        self._meta: dict[str, list] = {}
        # per-pid reentrant lock guarding sequence assignment and meta;
        # the merge itself runs OUTSIDE it (chunks are immutable and the
        # base's sequence number is reserved up front), so a commit's
        # save_delta never blocks on an in-flight O(state) merge
        self._pid_locks: dict[str, threading.RLock] = {}
        #: only chunks at or below this finalized time may be folded by
        #: compaction (None = no bound).  The streaming driver advances it
        #: after each durable commit record so a crash can still truncate
        #: the uncommitted tail (``truncate_after``) without a base having
        #: swallowed it.
        self._committed_time: int | None = None
        #: newest chunk header seen by the latest restore/load, per pid
        #: (``last_restored_header``)
        self._restored_headers: dict[str, dict | None] = {}
        #: write-side counters (surfaced by benchmarks/checkpoint_bench.py)
        self.bytes_written = 0
        self.chunks_written = 0
        self.compactions = 0
        self._compact_threads: list[threading.Thread] = []

    def mark_committed(self, time: int) -> None:
        """Advance the compaction bound: chunks up to ``time`` are covered
        by a durable commit record and safe to fold into a base."""
        with self._master:
            if self._committed_time is None or time > self._committed_time:
                self._committed_time = time

    def _prefix(self, pid: str) -> str:
        return f"opstate/{pid}/chunk-"

    def _pid_lock(self, pid: str) -> threading.RLock:
        with self._master:
            lock = self._pid_locks.get(pid)
            if lock is None:
                lock = self._pid_locks[pid] = threading.RLock()
            return lock

    def _meta_for(self, pid: str) -> list:
        meta = self._meta.get(pid)
        if meta is None:
            existing = self.storage.list_keys(self._prefix(pid))
            nxt = (
                max(int(k.rsplit("-", 1)[1]) for k in existing) + 1
                if existing
                else 0
            )
            # entries-since-base is unknown for a pre-existing store; the
            # chunk count stands in (conservative: compacts sooner)
            meta = self._meta[pid] = [nxt, 0, False, len(existing)]
        return meta

    def _put_chunk(self, pid: str, payload: bytes) -> None:
        # caller holds the pid lock
        meta = self._meta_for(pid)
        seq = meta[0]
        meta[0] += 1
        self.storage.put(f"{self._prefix(pid)}{seq:08d}", _seal_chunk(payload))
        with self._master:
            self.bytes_written += len(payload)
            self.chunks_written += 1

    def save_delta(
        self,
        persistent_id: str,
        time: int,
        upserts: dict,
        deletes: Iterable = (),
        *,
        live_entries: int | None = None,
        header: dict | None = None,
    ) -> None:
        """Append one finalized-time delta chunk; may schedule compaction.

        ``header`` (optional) is a small writer-owned dict riding the
        chunk next to the delta — the index plane persists its routing
        state there (LSH projector / partition-router specs), so a
        restored process routes queries to the same partitions.  An
        extra dict key in the pickled chunk: readers that predate it
        ignore it, FORMAT_VERSION unchanged.  Replay keeps the
        newest-by-time header (compaction folds it into the base)."""
        deletes = list(deletes)
        if not upserts and not deletes:
            return
        chunk = {"kind": "delta", "time": time, "upserts": upserts, "deletes": deletes}
        if header is not None:
            chunk["header"] = header
        payload = pickle.dumps(chunk)
        want_compact = False
        with self._pid_lock(persistent_id):
            meta = self._meta_for(persistent_id)
            self._put_chunk(persistent_id, payload)
            meta[1] += len(upserts) + len(deletes)
            meta[3] += 1
            # both floors must clear: enough dead entries to amortize the
            # O(live) base write, AND a run of at least MIN_COMPACT_CHUNKS
            # chunks (a tiny state would otherwise compact every commit)
            if (
                not meta[2]
                and live_entries is not None
                and meta[3] >= self.MIN_COMPACT_CHUNKS
                and meta[1] >= int(self.COMPACT_DEAD_RATIO * live_entries)
            ):
                meta[2] = True
                want_compact = True
        if want_compact:
            if self.background:
                th = threading.Thread(
                    target=self._compact_guarded,
                    args=(persistent_id,),
                    daemon=True,
                    name="pw-snapshot-compact",
                )
                th.start()
                with self._master:
                    self._compact_threads = [
                        t for t in self._compact_threads if t.is_alive()
                    ] + [th]
            else:
                self._compact_guarded(persistent_id)

    def save_base(self, persistent_id: str, time: int, state: dict) -> None:
        """Write the full state as one base chunk (first save of a fresh
        run, or a compaction result)."""
        payload = pickle.dumps({"kind": "base", "time": time, "state": state})
        with self._pid_lock(persistent_id):
            self._put_chunk(persistent_id, payload)
            meta = self._meta_for(persistent_id)
            meta[1] = 0
            meta[3] = 0

    def wait_compactions(self, timeout: float = 10.0) -> None:
        """Join in-flight background merges (tests / orderly shutdown)."""
        with self._master:
            threads = list(self._compact_threads)
        for th in threads:
            th.join(timeout=timeout)

    def _compact_guarded(self, pid: str) -> None:
        try:
            self.compact_now(pid)
        finally:
            with self._pid_lock(pid):
                self._meta_for(pid)[2] = False

    def compact_now(self, persistent_id: str) -> None:
        """Merge the committed prefix of the chunk run (and any legacy
        blob) into one base chunk at a sequence number reserved up front,
        then remove the merged keys.

        The per-pid lock is held only to snapshot the key list and reserve
        the base's sequence — the O(state) read/merge/write itself runs
        unlocked, so a concurrent commit's ``save_delta`` never stalls on
        it (its chunks land at sequences *after* the reserved base and
        replay on top).  Crash-safe: the base lands *before* anything is
        deleted, so restore reads a consistent state at every point.

        Chunks newer than the committed-time bound (``mark_committed``)
        are left in place — folding them into a base would make it
        impossible for :meth:`truncate_after` to drop an uncommitted tail
        after a crash.  The streaming driver always triggers compaction
        from ``save_delta`` *before* the tick's commit record lands, so
        the just-written chunk is routinely past the bound; folding the
        committed prefix (instead of abandoning the merge, which would
        let the store grow O(history)) keeps compaction effective.
        :meth:`load` replays the surviving newer deltas on top of the
        base by finalized time, which is strictly monotone per pid.
        """
        prefix = self._prefix(persistent_id)
        legacy_key = f"opstate/{persistent_id}"
        with self._pid_lock(persistent_id):
            meta = self._meta_for(persistent_id)
            old_keys = self.storage.list_keys(prefix)
            legacy = self.storage.get(legacy_key)
            if not old_keys and legacy is None:
                return
            base_seq = meta[0]
            meta[0] += 1
        with self._master:
            bound = self._committed_time
        folded_keys: list[str] = []
        folded_chunks: list[dict] = []
        folded_entries = 0
        folded_bases = 0
        for key in old_keys:
            data = self.storage.get(key)
            if not data:
                continue
            chunk = pickle.loads(_open_chunk(key, data))
            if bound is not None and chunk.get("time", 0) > bound:
                continue  # uncommitted tail — stays as-is this round
            folded_keys.append(key)
            folded_chunks.append(chunk)
            if chunk["kind"] == "base":
                folded_bases += 1
            else:
                folded_entries += len(chunk["upserts"]) + len(chunk["deletes"])
        if legacy is None and folded_entries == 0 and folded_bases <= 1:
            return  # nothing to merge — don't rewrite a lone base forever
        state, last_time, header = self._replay(
            folded_chunks, pickle.loads(legacy) if legacy else {}
        )
        base_chunk = {"kind": "base", "time": last_time, "state": state}
        if header is not None:
            # the newest folded header survives compaction in the base
            base_chunk["header"] = header
        payload = pickle.dumps(base_chunk)
        self.storage.put(f"{prefix}{base_seq:08d}", _seal_chunk(payload))
        with self._pid_lock(persistent_id):
            meta = self._meta_for(persistent_id)
            meta[1] = max(0, meta[1] - folded_entries)
            meta[3] = max(0, meta[3] - len(folded_keys))
        with self._master:
            self.bytes_written += len(payload)
            self.chunks_written += 1
            self.compactions += 1
        for key in folded_keys:
            self.storage.remove(key)
        if legacy is not None:
            self.storage.remove(legacy_key)

    def truncate_after(self, persistent_id: str, time: int) -> None:
        """Remove chunks written after finalized ``time`` — the restart
        path drops a crashed run's uncommitted tail (its input offsets
        were never recorded, so the data replays and would double-apply
        if the orphaned chunks survived)."""
        with self._pid_lock(persistent_id):
            for key in self.storage.list_keys(self._prefix(persistent_id)):
                data = self.storage.get(key)
                if not data:
                    continue
                if pickle.loads(_open_chunk(key, data)).get("time", 0) > time:
                    self.storage.remove(key)

    def load(self, persistent_id: str) -> dict | None:
        """Replay the newest base + later deltas; a legacy single-blob
        snapshot (``opstate/{pid}``) acts as the base below every chunk.

        The newest base is the one at the highest sequence number (a
        crash between a compaction's base write and its removals can
        leave the folded run behind).  Deltas replay on top when their
        finalized time exceeds the base's — prefix compaction can leave
        an uncommitted-tail delta at a LOWER sequence than the base that
        later folded older chunks, so sequence order alone is not the
        replay order; per-pid delta times are strictly monotone (the
        driver resumes engine time past :meth:`restore`'s returned time),
        so time is."""
        return self.restore(persistent_id)[0]

    def last_restored_header(self, persistent_id: str) -> dict | None:
        """The newest chunk header folded by the most recent
        :meth:`restore`/:meth:`load` of ``persistent_id`` (None when no
        chunk carried one) — the streaming driver re-applies it to the
        index node before the restored rows flow back in."""
        return self._restored_headers.get(persistent_id)

    def restore(
        self,
        persistent_id: str,
        committed_time: int | None = None,
        *,
        on_chunk: Any = None,
    ) -> tuple[dict | None, int]:
        """Single-scan restart path: read every chunk once, drop chunks
        newer than ``committed_time`` (a crashed run's uncommitted tail —
        its input offsets were never recorded, so the data replays and
        would double-apply), replay the rest as :meth:`load` does.

        ``on_chunk(key, n_entries, read_ms)`` (optional) is called per
        replayed chunk — the streaming driver feeds it into the restore
        progress surfaced on ``/v1/health`` and the flight recorder.

        Returns ``(state | None, newest_folded_time)``.  The driver MUST
        resume engine time past the returned time in every persistence
        mode: replay orders deltas by finalized time, so a later run
        re-using earlier times would make a stale delta win (engine times
        restart from 1 per run unless resumed)."""
        import time as _time

        keys = self.storage.list_keys(self._prefix(persistent_id))
        legacy = self.storage.get(f"opstate/{persistent_id}")
        chunks = []
        with self._pid_lock(persistent_id):
            for key in keys:
                t0 = _time.monotonic()
                data = self.storage.get(key)
                if not data:
                    continue
                chunk = pickle.loads(_open_chunk(key, data))
                if (
                    committed_time is not None
                    and chunk.get("time", 0) > committed_time
                ):
                    self.storage.remove(key)
                    continue
                chunks.append(chunk)
                if on_chunk is not None:
                    n = (
                        len(chunk.get("state", ()))
                        if chunk["kind"] == "base"
                        else len(chunk.get("upserts", ()))
                        + len(chunk.get("deletes", ()))
                    )
                    on_chunk(key, n, (_time.monotonic() - t0) * 1000.0)
        if not chunks and legacy is None:
            self._restored_headers.pop(persistent_id, None)
            return None, 0
        state, last_time, header = self._replay(
            chunks, pickle.loads(legacy) if legacy else {}
        )
        self._restored_headers[persistent_id] = header
        return state, max(last_time, 0)

    @staticmethod
    def _replay(chunks: list[dict], state: dict) -> tuple[dict, int, dict | None]:
        """Merge ``chunks`` (sequence order) over ``state``: the newest
        base — the one at the highest sequence — wins, then deltas whose
        finalized time exceeds the base's replay on top in time order.
        Sequence order alone is NOT the replay order: prefix compaction
        can leave an uncommitted-tail delta at a LOWER sequence than a
        base that later folded older chunks; per-pid delta times are
        strictly monotone, so time disambiguates.  Returns the merged
        state, the newest folded time (-1 when ``chunks`` is empty —
        below every real engine time, so any later delta applies), and
        the newest-by-time chunk header (None when no chunk carried
        one)."""
        base_time = -1
        header: dict | None = None
        header_time = -1
        for chunk in chunks:
            if chunk["kind"] == "base":
                state = dict(chunk["state"])
                base_time = chunk.get("time", 0)
                if chunk.get("header") is not None:
                    header = chunk["header"]
                    header_time = base_time
        last_time = base_time
        deltas = [c for c in chunks if c["kind"] != "base"]
        deltas.sort(key=lambda c: c.get("time", 0))
        for chunk in deltas:
            if chunk.get("time", 0) > base_time:
                state.update(chunk["upserts"])
                for k in chunk["deletes"]:
                    state.pop(k, None)
                last_time = max(last_time, chunk.get("time", 0))
                if (
                    chunk.get("header") is not None
                    and chunk.get("time", 0) > header_time
                ):
                    header = chunk["header"]
                    header_time = chunk.get("time", 0)
        return state, last_time, header

    def chunk_count(self, persistent_id: str) -> int:
        return len(self.storage.list_keys(self._prefix(persistent_id)))
