"""Column utilities (reference: python/pathway/stdlib/utils/col.py)."""

from __future__ import annotations

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.schema import SchemaMetaclass
from ...internals.table import Table

__all__ = ["unpack_col", "apply_all_rows", "multiapply_all_rows", "flatten_column"]


def unpack_col(column: ColumnReference, *unpacked_columns, schema: SchemaMetaclass | None = None) -> Table:
    """Unpack a tuple column into named columns (reference: col.py unpack_col)."""
    table = column.table
    if schema is not None:
        names = schema.column_names()
        dtypes = [schema[n].dtype for n in names]
    else:
        names = [c if isinstance(c, str) else c.name for c in unpacked_columns]
        dtypes = [dt.ANY] * len(names)
    exprs = {}
    for i, (n, t) in enumerate(zip(names, dtypes)):
        exprs[n] = ApplyExpression(lambda v, _i=i: v[_i], t, column)
    return table._select_exprs(exprs, universe=table._universe)


def apply_all_rows(*cols, fun, result_col_name: str) -> Table:
    """Apply ``fun`` over entire columns at once (reference: col.py)."""
    raise NotImplementedError("apply_all_rows lands with batched-UDF support")


def multiapply_all_rows(*cols, fun, result_col_names) -> Table:
    raise NotImplementedError("multiapply_all_rows lands with batched-UDF support")


def flatten_column(column: ColumnReference, origin_id: str = "origin_id") -> Table:
    return column.table.flatten(column, origin_id=origin_id)
