"""Column utilities (reference: python/pathway/stdlib/utils/col.py)."""

from __future__ import annotations

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.schema import SchemaMetaclass
from ...internals.table import Table

__all__ = ["unpack_col", "apply_all_rows", "multiapply_all_rows", "flatten_column"]


def unpack_col(column: ColumnReference, *unpacked_columns, schema: SchemaMetaclass | None = None) -> Table:
    """Unpack a tuple column into named columns (reference: col.py unpack_col).

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.utils.col import unpack_col
    >>> t = pw.debug.table_from_markdown('''
    ... a | b
    ... 1 | x
    ... 2 | y
    ... ''')
    >>> packed = t.select(pair=pw.make_tuple(t.a, t.b))
    >>> pw.debug.compute_and_print(
    ...     unpack_col(packed.pair, "num", "tag"), include_id=False)
    num | tag
    1 | x
    2 | y
    """
    table = column.table
    if schema is not None:
        names = schema.column_names()
        dtypes = [schema[n].dtype for n in names]
    else:
        names = [c if isinstance(c, str) else c.name for c in unpacked_columns]
        dtypes = [dt.ANY] * len(names)
    exprs = {}
    for i, (n, t) in enumerate(zip(names, dtypes)):
        exprs[n] = ApplyExpression(lambda v, _i=i: v[_i], t, column)
    return table._select_exprs(exprs, universe=table._universe)


def multiapply_all_rows(*cols, fun, result_col_names) -> Table:
    """Apply ``fun`` to all the data of the selected columns at once,
    returning several output columns re-keyed by the original row ids
    (reference: col.py:211-274 — gather whole columns into one group,
    apply, scatter back).  Meant for infrequent runs on small tables."""
    import pathway_tpu as pw

    assert len(cols) > 0
    table = cols[0].table
    names = [c if isinstance(c, str) else c.name for c in result_col_names]

    packed = table.select(
        __one__=0,
        __rid__=pw.this.id,
        __vals__=pw.make_tuple(*cols),
    )

    def compute(rows):
        rows = list(rows)
        ids = [r[0] for r in rows]
        col_lists = [list(c) for c in zip(*(r[1] for r in rows))] or [
            [] for _ in cols
        ]
        outs = fun(*col_lists)
        return tuple(
            (rid,) + tuple(out[i] for out in outs) for i, rid in enumerate(ids)
        )

    grouped = packed.groupby(packed["__one__"]).reduce(
        __rows__=pw.apply_with_type(
            compute,
            tuple,
            # sorted for a deterministic id<->value pairing across recomputes
            pw.reducers.sorted_tuple(
                pw.make_tuple(packed["__rid__"], packed["__vals__"])
            ),
        ),
    )
    flat = grouped.flatten(grouped["__rows__"])
    exprs = {"__rid__": flat["__rows__"].get(0)}
    for i, n in enumerate(names):
        exprs[n] = flat["__rows__"].get(i + 1)
    out = flat._select_exprs(exprs, universe=flat._universe)
    out = out.with_id(out["__rid__"])
    return out[names]


def apply_all_rows(*cols, fun, result_col_name) -> Table:
    """Single-output-column variant of :func:`multiapply_all_rows`
    (reference: col.py:276-318)."""

    def fun_wrapped(*col_lists):
        return (fun(*col_lists),)

    return multiapply_all_rows(
        *cols, fun=fun_wrapped, result_col_names=[result_col_name]
    )


def flatten_column(column: ColumnReference, origin_id: str = "origin_id") -> Table:
    return column.table.flatten(column, origin_id=origin_id)
