"""Row filtering helpers (reference: python/pathway/stdlib/utils/filtering.py)."""

from __future__ import annotations

__all__ = ["argmax_rows", "argmin_rows"]


def argmax_rows(table, *on, what):
    """Keep, per group of ``on``, the row maximizing ``what``
    (reference: filtering.py ``argmax_rows``).

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.utils.filtering import argmax_rows
    >>> t = pw.debug.table_from_markdown('''
    ... g | v
    ... a | 3
    ... a | 7
    ... b | 5
    ... ''')
    >>> pw.debug.compute_and_print(argmax_rows(t, t.g, what=t.v), include_id=False)
    g | v
    a | 7
    b | 5
    """
    import pathway_tpu as pw

    chooser = (
        table.groupby(*on)
        .reduce(argmax_id=pw.reducers.argmax(what))
        .with_id(pw.this.argmax_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(chooser)


def argmin_rows(table, *on, what):
    """Keep, per group of ``on``, the row minimizing ``what``
    (reference: filtering.py ``argmin_rows``)."""
    import pathway_tpu as pw

    chooser = (
        table.groupby(*on)
        .reduce(argmin_id=pw.reducers.argmin(what))
        .with_id(pw.this.argmin_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(chooser)
