"""Row filtering helpers (reference: python/pathway/stdlib/utils/filtering.py)."""

from __future__ import annotations

__all__ = ["argmax_rows", "argmin_rows"]


def argmax_rows(table, *on, what):
    """Keep, per group of ``on``, the row maximizing ``what``
    (reference: filtering.py ``argmax_rows``)."""
    import pathway_tpu as pw

    chooser = (
        table.groupby(*on)
        .reduce(argmax_id=pw.reducers.argmax(what))
        .with_id(pw.this.argmax_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(chooser)


def argmin_rows(table, *on, what):
    """Keep, per group of ``on``, the row minimizing ``what``
    (reference: filtering.py ``argmin_rows``)."""
    import pathway_tpu as pw

    chooser = (
        table.groupby(*on)
        .reduce(argmin_id=pw.reducers.argmin(what))
        .with_id(pw.this.argmin_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(chooser)
