from . import col

__all__ = ["col"]


def __getattr__(name):
    if name == "AsyncTransformer":
        from .async_transformer import AsyncTransformer

        return AsyncTransformer
    raise AttributeError(name)
