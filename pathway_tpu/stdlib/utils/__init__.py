from . import col

__all__ = ["col", "bucketing", "filtering", "pandas_transformer", "AsyncTransformer"]


def __getattr__(name):
    if name == "AsyncTransformer":
        from .async_transformer import AsyncTransformer

        return AsyncTransformer
    if name in ("bucketing", "filtering", "pandas_transformer"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
