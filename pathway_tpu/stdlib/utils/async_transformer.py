"""AsyncTransformer — non-row-wise async table transformation.

reference: python/pathway/stdlib/utils/async_transformer.py:282
(``AsyncTransformer`` with its own input/output streaming session,
``successful``/``failed``/``finished`` result views, ``with_options``).

Here the transformer rides the engine's AsyncMapNode (the same bounded
fan-out path as async UDFs): every input row awaits ``invoke`` concurrently
within a micro-batch; failures become rows of ``failed`` instead of
aborting the run.
"""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression, AsyncApplyExpression
from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from ...internals.udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    with_cache_strategy,
    with_retry_strategy,
)

__all__ = ["AsyncTransformer"]


class AsyncTransformer:
    """Subclass with ``output_schema`` and an async ``invoke``::

        class Upper(pw.AsyncTransformer, output_schema=OutSchema):
            async def invoke(self, text: str) -> dict:
                return {"result": text.upper()}

        out = Upper(input_table).successful
    """

    output_schema: SchemaMetaclass | None = None

    def __init_subclass__(cls, /, output_schema: SchemaMetaclass | None = None, **kw):
        super().__init_subclass__(**kw)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(self, input_table: Table, *, instance: Any = None):
        if self.output_schema is None:
            raise ValueError(
                "AsyncTransformer subclass must declare output_schema"
            )
        self.input_table = input_table
        self._capacity: int | None = None
        self._retry_strategy: AsyncRetryStrategy | None = None
        self._cache_strategy: CacheStrategy | None = None
        self._built: dict[str, Table] | None = None

    def with_options(
        self,
        capacity: int | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
    ) -> "AsyncTransformer":
        """reference: async_transformer.py ``with_options``"""
        self._capacity = capacity
        self._retry_strategy = retry_strategy
        self._cache_strategy = cache_strategy
        self._built = None
        return self

    async def invoke(self, *args, **kwargs) -> dict:
        raise NotImplementedError

    # -- wiring --
    def _build(self) -> dict[str, Table]:
        if self._built is not None:
            return self._built
        table = self.input_table
        in_cols = table.column_names()
        out_cols = list(self.output_schema.column_names())

        inner = self.invoke
        if self._retry_strategy is not None:
            inner = with_retry_strategy(inner, self._retry_strategy)
        if self._cache_strategy is not None:
            inner = with_cache_strategy(inner, self._cache_strategy)

        async def call(*vals):
            try:
                result = await inner(**dict(zip(in_cols, vals)))
                return ("ok", tuple(result.get(n) for n in out_cols))
            except Exception as exc:  # noqa: BLE001 — routed to .failed
                return ("error", str(exc))

        expr = AsyncApplyExpression(
            call, dt.ANY, *[table[c] for c in in_cols]
        )
        expr.capacity = self._capacity  # type: ignore[attr-defined]
        raw = table.select(_result=expr)

        ok = raw.filter(
            ApplyExpression(lambda r: r[0] == "ok", dt.BOOL, raw["_result"])
        )
        successful = ok._select_exprs(
            {
                n: ApplyExpression(
                    lambda r, i=i: r[1][i],
                    self.output_schema[n].dtype,
                    ok["_result"],
                )
                for i, n in enumerate(out_cols)
            },
            universe=ok._universe,
        )
        failed = raw.filter(
            ApplyExpression(lambda r: r[0] == "error", dt.BOOL, raw["_result"])
        )
        failed = failed._select_exprs(
            {
                "error": ApplyExpression(
                    lambda r: r[1], dt.STR, failed["_result"]
                )
            },
            universe=failed._universe,
        )
        finished = raw._select_exprs(
            {
                "ok": ApplyExpression(lambda r: r[0] == "ok", dt.BOOL, raw["_result"]),
            },
            universe=raw._universe,
        )
        self._built = dict(successful=successful, failed=failed, finished=finished)
        return self._built

    @property
    def successful(self) -> Table:
        """Rows whose ``invoke`` completed, with ``output_schema`` columns."""
        return self._build()["successful"]

    @property
    def failed(self) -> Table:
        """Rows whose ``invoke`` raised, with the error string."""
        return self._build()["failed"]

    @property
    def finished(self) -> Table:
        """All processed rows with an ``ok`` flag."""
        return self._build()["finished"]

    @property
    def output_table(self) -> Table:
        """reference: async_transformer.py:477 ``output_table``"""
        return self.successful
