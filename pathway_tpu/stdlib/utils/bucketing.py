"""Time bucketing helpers (reference: python/pathway/stdlib/utils/bucketing.py)."""

from __future__ import annotations

import datetime

__all__ = ["truncate_to_minutes"]


def truncate_to_minutes(time: datetime.datetime) -> datetime.datetime:
    return time - datetime.timedelta(
        seconds=time.second, microseconds=time.microsecond
    )
