"""``@pw.pandas_transformer`` — run a pandas function as a table operator.

reference: python/pathway/stdlib/utils/pandas_transformer.py:15
(``pandas_transformer`` decorator).  Each input table is packed into one
row (sorted tuple of its rows), converted to a ``pandas.DataFrame``
indexed by row keys, handed to the user function, and the resulting
frame is exploded back into a table — the frame's index becomes the
output universe (non-Pointer indexes are hashed through ``ref_scalar``).
"""

from __future__ import annotations

__all__ = ["pandas_transformer"]


def _to_frames(packed_rows, input_tables):
    import pandas as pd

    frames = []
    for packed, table in zip(packed_rows, input_tables):
        names = table.column_names()
        idx = [r[0] for r in packed]
        cols = {
            n: [r[1 + i] for r in packed] for i, n in enumerate(names)
        }
        # object dtype keeps Pointer keys intact (pandas would silently
        # collapse an int subclass into an int64 index)
        frames.append(pd.DataFrame(cols, index=pd.Index(idx, dtype=object)))
    return frames


def pandas_transformer(output_schema, output_universe: str | int | None = None):
    """Decorator (reference: pandas_transformer.py:15).  ``output_universe``
    names (or indexes) the argument whose universe the result reuses.

    Example:

    >>> import pandas as pd
    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ...   | foo | bar
    ... 0 | 10  | 100
    ... 1 | 20  | 200
    ... ''')
    >>> class Output(pw.Schema):
    ...     total: int
    >>> @pw.pandas_transformer(output_schema=Output, output_universe=0)
    ... def sum_cols(frame) -> pd.DataFrame:
    ...     return pd.DataFrame(frame.sum(axis=1))
    >>> pw.debug.compute_and_print(sum_cols(t), include_id=False)
    total
    110
    220
    """
    import functools
    import inspect

    def decorator(func):
        arg_names = list(inspect.signature(func).parameters)

        def universe_index() -> int | None:
            if output_universe is None:
                return None
            if isinstance(output_universe, str):
                try:
                    return arg_names.index(output_universe)
                except ValueError:
                    raise ValueError(
                        f"wrong output universe. No argument of name: "
                        f"{output_universe}"
                    )
            if output_universe < 0 or output_universe >= len(arg_names):
                raise ValueError("wrong output universe. Index out of range")
            return output_universe

        @functools.wraps(func)
        def wrapper(*inputs):
            import pandas as pd

            import pathway_tpu as pw
            from pathway_tpu.internals.keys import ref_scalar
            from pathway_tpu.internals.value import Pointer
            from pathway_tpu.stdlib.utils.col import unpack_col

            uni_idx = universe_index()
            out_names = output_schema.column_names()

            if not inputs:
                result = func()
                if isinstance(result, pd.Series):
                    result = pd.DataFrame(result)
                result.columns = out_names
                from pathway_tpu.debug import table_from_pandas

                return table_from_pandas(result)

            def as_tuple(*args):
                return args

            packed_tables = []
            for i, table in enumerate(inputs):
                cols = [table[n] for n in table.column_names()]
                tupled = table.select(all_cols=pw.apply(as_tuple, table.id, *cols))
                packed_tables.append(
                    tupled.reduce(
                        **{f"_{i}": pw.reducers.sorted_tuple(tupled.all_cols)}
                    )
                )
            combined = packed_tables[0]
            for extra in packed_tables[1:]:
                aligned = extra.with_universe_of(combined)
                combined = combined.with_columns(
                    **{n: aligned[n] for n in aligned.column_names()}
                )

            def run(*packed_rows):
                frames = _to_frames(packed_rows, inputs)
                result = func(*frames)
                if isinstance(result, pd.Series):
                    result = pd.DataFrame(result)
                result.columns = out_names
                if uni_idx is not None and not result.index.equals(
                    frames[uni_idx].index
                ):
                    raise ValueError(
                        "resulting universe does not match the universe "
                        "of the indicated argument"
                    )
                if not result.index.is_unique:
                    raise ValueError(
                        "index of resulting DataFrame must be unique"
                    )
                rows = []
                for idx, row in zip(result.index, result.itertuples(index=False)):
                    key = idx if isinstance(idx, Pointer) else ref_scalar(idx)
                    rows.append((key, *row))
                return tuple(rows)

            applied = combined.select(
                all_rows=pw.apply(
                    run, *[combined[f"_{i}"] for i in range(len(inputs))]
                )
            )
            flattened = applied.flatten(pw.this.all_rows)
            output = unpack_col(flattened.all_rows, "pw_row_key", *out_names)
            output = output.with_id(output.pw_row_key).without(
                pw.this.pw_row_key
            )
            if uni_idx is not None:
                output = output.with_universe_of(inputs[uni_idx])
            return output

        return wrapper

    return decorator
