"""Community detection (reference: python/pathway/stdlib/graphs/louvain_communities/).

The reference implements one level of Louvain as iterated local moves over
a weighted graph inside ``pw.iterate``.  Here the local move is
label-propagation-style: every vertex adopts the community carrying the
highest total edge weight among its neighbors (its own community wins
ties, then the smaller label for determinism) — iterated to fixpoint or
``iteration_limit``.  One level of this is the move phase of Louvain; the
graph-coarsening phase composes via ``louvain_level`` reapplication.
"""

from __future__ import annotations

import pathway_tpu as pw
from ...internals.table import Table

__all__ = ["louvain_level"]


def louvain_level(edges: Table, iteration_limit: int = 20) -> Table:
    """``edges`` columns: u, v (Pointer), optional weight (float, default 1).
    Returns a table keyed by vertex with a ``community`` column."""
    has_weight = "weight" in edges.column_names()
    if not has_weight:
        edges = edges.select(
            edges.u, edges.v, weight=pw.apply_with_type(lambda *_: 1.0, float, edges.u)
        )
    # undirected: consider both directions
    fwd = edges.select(src=edges.u, dst=edges.v, w=edges.weight)
    rev = edges.select(src=edges.v, dst=edges.u, w=edges.weight)
    sym = fwd.concat_reindex(rev)

    vertices = sym.groupby(sym.src).reduce(v=sym.src)
    base = vertices.select(
        v=vertices.v,
        community=pw.apply_with_type(lambda v: v, pw.Pointer, vertices.v),
    )

    def one_step(communities: Table) -> Table:
        com = communities.with_id_from(communities.v)
        # each neighbor votes for its community with the edge weight
        votes = sym.select(
            dst=sym.dst,
            community=com.ix(sym.pointer_from(sym.src)).community,
            w=sym.w,
        )
        tallies = votes.groupby(votes.dst, votes.community).reduce(
            dst=votes.dst,
            community=votes.community,
            total=pw.reducers.sum(votes.w),
        )
        # strongest community per vertex; deterministic tie-break on the
        # smaller community key
        best = tallies.groupby(tallies.dst).reduce(
            v=tallies.dst,
            community=pw.apply_with_type(
                lambda pairs: max(pairs, key=lambda p: (p[0], -p[1].value))[1],
                pw.Pointer,
                pw.reducers.tuple(pw.make_tuple(tallies.total, tallies.community)),
            ),
        )
        return best.with_id_from(best.v)

    result = pw.iterate(one_step, iteration_limit=iteration_limit, communities=base)
    return result
