"""Bellman-Ford shortest paths (reference: python/pathway/stdlib/graphs/bellman_ford/)."""

from __future__ import annotations

import math

import pathway_tpu as pw
from ...internals.table import Table

__all__ = ["bellman_ford"]


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """``vertices`` has column ``is_source`` (bool); ``edges`` has (u, v,
    dist).  Returns per-vertex ``dist_from_source`` (inf when unreachable)."""

    start = vertices.select(
        dist_from_source=pw.if_else(vertices.is_source, 0.0, math.inf)
    )

    def step(state: Table) -> Table:
        relaxed = edges.select(
            vertex=edges.v,
            candidate=state.ix(edges.u).dist_from_source + edges.dist,
        )
        best = relaxed.groupby(relaxed.vertex, id=relaxed.vertex).reduce(
            candidate=pw.reducers.min(relaxed.candidate)
        )
        return state.select(
            dist_from_source=pw.if_else(
                best.ix(state.id, optional=True).candidate.is_not_none()
                & (best.ix(state.id, optional=True).candidate < state.dist_from_source),
                best.ix(state.id, optional=True).candidate.num.fill_na(math.inf),
                state.dist_from_source,
            )
        )

    return pw.iterate(step, state=start)
