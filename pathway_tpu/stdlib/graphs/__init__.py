"""Graph algorithms (reference: python/pathway/stdlib/graphs/: pagerank,
bellman_ford, louvain — all built on pw.iterate)."""

from . import pagerank, bellman_ford, louvain

__all__ = ["pagerank", "bellman_ford", "louvain"]
