"""PageRank over an edges table (reference: python/pathway/stdlib/graphs/pagerank.py)."""

from __future__ import annotations

import pathway_tpu as pw
from ...internals.table import Table

__all__ = ["pagerank"]


def pagerank(edges: Table, steps: int = 5) -> Table:
    """``edges`` has columns (u, v) of Pointer; returns table keyed by vertex
    id with a ``rank`` column (integer fixed-point, like the reference)."""
    degrees = edges.groupby(edges.u).reduce(u=edges.u, degree=pw.reducers.count())
    base = edges.groupby(edges.v).reduce(v=edges.v, rank=pw.apply_with_type(lambda *_: 1_000, int))

    def one_step(ranks: Table) -> Table:
        deg = degrees.with_id_from(degrees.u)
        r = ranks.with_id_from(ranks.v)
        flows = edges.select(
            edges.v,
            flow=r.ix(edges.pointer_from(edges.u), optional=True).rank.num.fill_na(1000)
            // deg.ix(edges.pointer_from(edges.u)).degree,
        )
        inflow = flows.groupby(flows.v).reduce(
            v=flows.v, rank=pw.cast(int, pw.reducers.sum(flows.flow) * 83 // 100 + 170)
        )
        return inflow

    result = pw.iterate(one_step, iteration_limit=steps, ranks=base)
    return result
