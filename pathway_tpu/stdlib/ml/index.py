"""Legacy KNNIndex API (the class named in the north star).

reference: python/pathway/stdlib/ml/index.py:9 — LSH-backed there
(``_knn_lsh.py``); here backed by the HBM brute-force/LSH device indexes via
DataIndex, keeping the ``get_nearest_items`` / ``get_nearest_items_asof_now``
surface (index.py:54,194).
"""

from __future__ import annotations

from typing import Any

from ...internals.expression import ColumnReference
from ...internals.table import Table
from ..indexing.data_index import DataIndex, _SCORE, _ID
from ..indexing.retrievers import BruteForceKnnFactory, LshKnnFactory

__all__ = ["KNNIndex"]


class KNNIndex:
    """K-nearest-neighbors index over an embeddings column."""

    def __init__(
        self,
        data_embedding: ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: ColumnReference | None = None,
    ):
        self.data = data
        self.data_embedding = data_embedding
        self._distance_type = distance_type
        if n_or * n_and <= 64 and distance_type in ("euclidean", "cosine"):
            # small LSH configs: keep the reference's approximate behavior
            factory: Any = LshKnnFactory(
                dimensions=n_dimensions,
                n_or=n_or,
                n_and=n_and,
                bucket_length=bucket_length,
                distance_type=distance_type,
            )
        else:
            metric = "cos" if distance_type.startswith("cos") else "l2sq"
            factory = BruteForceKnnFactory(dimensions=n_dimensions, metric=metric)
        self.index = DataIndex(
            data,
            factory,
            data_column=data_embedding,
            metadata_column=metadata,
        )

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: Any = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: Any = None,
    ) -> Table:
        """reference: ml/index.py:54"""
        return self._get(
            query_embedding, k, collapse_rows, with_distances, metadata_filter, live=True
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: Any = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: Any = None,
    ) -> Table:
        """reference: ml/index.py:194"""
        return self._get(
            query_embedding, k, collapse_rows, with_distances, metadata_filter, live=False
        )

    def _get(self, query_embedding, k, collapse_rows, with_distances, metadata_filter, live):
        method = self.index.query if live else self.index.query_as_of_now
        jr = method(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        right = jr._right
        cols = {}
        for n in self.data.column_names():
            cols[n] = right[n]
        if with_distances:
            # inner scores are similarities (higher=better); the reference
            # returns *distances* (ml/index.py) — convert so code ported from
            # the reference keeps its sort/threshold orientation:
            # cosine: 1 - cos_sim;  euclidean: ||q-v||^2 = -score
            if self._distance_type.startswith("cos"):
                conv = lambda scores: tuple(1.0 - s for s in scores)
            else:
                conv = lambda scores: tuple(-s for s in scores)
            from ...internals.expression import ApplyExpression
            from ...internals import dtype as dt

            cols["dist"] = ApplyExpression(conv, dt.List(dt.FLOAT), right[_SCORE])
        return jr._left._select_exprs(cols, universe=jr._left._universe)
