from . import index
from .index import KNNIndex

__all__ = ["index", "KNNIndex"]
