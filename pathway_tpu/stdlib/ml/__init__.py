from . import index, smart_table_ops
from .index import KNNIndex
from .smart_table_ops import (
    FuzzyJoinFeatureGeneration,
    FuzzyJoinNormalization,
    fuzzy_match_tables,
    fuzzy_match_with_hint,
    fuzzy_self_match,
    smart_fuzzy_match,
)

__all__ = [
    "index",
    "KNNIndex",
    "smart_table_ops",
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match_tables",
    "fuzzy_match_with_hint",
    "fuzzy_self_match",
    "smart_fuzzy_match",
    "classifiers",
    "datasets",
    "hmm",
    "utils",
    "classifier_accuracy",
]


def __getattr__(name):
    # heavier tails (sklearn/networkx-adjacent) import lazily
    if name in ("classifiers", "datasets", "hmm", "utils"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    if name == "classifier_accuracy":
        from .utils import classifier_accuracy

        return classifier_accuracy
    raise AttributeError(name)
