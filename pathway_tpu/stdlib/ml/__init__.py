from . import index, smart_table_ops
from .index import KNNIndex
from .smart_table_ops import fuzzy_match_tables, fuzzy_self_match

__all__ = [
    "index",
    "KNNIndex",
    "smart_table_ops",
    "fuzzy_match_tables",
    "fuzzy_self_match",
]
