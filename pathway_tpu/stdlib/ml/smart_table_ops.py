"""Fuzzy joins (reference: python/pathway/stdlib/ml/smart_table_ops/
``_fuzzy_join.py`` 470 LoC — feature extraction + weighted match scoring;
``fuzzy_match_tables``, ``fuzzy_self_match``, ``smart_fuzzy_match``).

Scoring follows the reference's shape: values decompose into normalized
token features, features are weighted by inverse frequency, and a pair's
score is the summed weight of shared features; each left row keeps its
best-scoring right row above the threshold.  The candidate generation +
scoring runs as one packed reduce per side (host-side; token sets are
tiny compared to the vector plane).
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict

from ...internals import dtype as dt
from ...internals.desugaring import resolve_expression
from ...internals.expression import ApplyExpression
from ...internals.table import Table

__all__ = ["fuzzy_match_tables", "fuzzy_self_match", "FuzzyJoinNormalization"]

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


class FuzzyJoinNormalization:
    """reference: _fuzzy_join.py normalization kinds."""

    WORD = "word"
    LETTERS = "letters"


def _features(value, normalization: str) -> list[str]:
    text = str(value or "").lower()
    if normalization == FuzzyJoinNormalization.LETTERS:
        return ["".join(sorted(_TOKEN_RE.findall(text)))]
    return _TOKEN_RE.findall(text)


def _score_pairs(
    left_items: list[tuple], right_items: list[tuple], normalization: str
) -> list[tuple]:
    """[(left_key, right_key, score)] — best right match per left row."""
    feature_count: Counter = Counter()
    left_feats = [(k, _features(v, normalization)) for k, v in left_items]
    right_feats = [(k, _features(v, normalization)) for k, v in right_items]
    for _, fs in left_feats:
        feature_count.update(set(fs))
    for _, fs in right_feats:
        feature_count.update(set(fs))

    postings: dict[str, list] = defaultdict(list)
    for k, fs in right_feats:
        for f in set(fs):
            postings[f].append(k)

    def weight(f: str) -> float:
        # rarer features weigh more (reference uses 1/count normalization)
        return 1.0 / math.sqrt(feature_count[f])

    out = []
    for lk, fs in left_feats:
        scores: dict = defaultdict(float)
        for f in set(fs):
            for rk in postings.get(f, ()):
                scores[rk] += weight(f)
        if scores:
            best_rk, best = max(scores.items(), key=lambda kv: (kv[1], repr(kv[0])))
            out.append((lk, best_rk, best))
    return out


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    left_column=None,
    right_column=None,
    threshold: float = 0.0,
    normalization: str = FuzzyJoinNormalization.WORD,
) -> Table:
    """Best fuzzy pairing between two tables' text columns
    (reference: smart_table_ops fuzzy_match_tables).  Returns columns
    (left, right, weight) with Pointer keys into the inputs."""
    import pathway_tpu as pw

    lcol = resolve_expression(
        left_column if left_column is not None else left_table[left_table.column_names()[0]],
        left_table,
    )
    rcol = resolve_expression(
        right_column if right_column is not None else right_table[right_table.column_names()[0]],
        right_table,
    )
    left_packed = left_table.reduce(
        items=pw.reducers.tuple(pw.make_tuple(left_table.id, lcol))
    )
    right_packed = right_table.reduce(
        items=pw.reducers.tuple(pw.make_tuple(right_table.id, rcol))
    )

    def match(litems, ritems) -> tuple:
        pairs = _score_pairs(list(litems or ()), list(ritems or ()), normalization)
        return tuple(p for p in pairs if p[2] > threshold)

    matches = left_packed.join(right_packed).select(
        pairs=ApplyExpression(match, dt.ANY, left_packed.items, right_packed.items)
    )
    flat = matches.flatten(matches.pairs)
    return flat._select_exprs(
        {
            "left": ApplyExpression(lambda p: p[0], dt.POINTER, flat.pairs),
            "right": ApplyExpression(lambda p: p[1], dt.POINTER, flat.pairs),
            "weight": ApplyExpression(lambda p: float(p[2]), dt.FLOAT, flat.pairs),
        },
        universe=flat._universe,
    )


def fuzzy_self_match(
    table: Table, column=None, *, threshold: float = 0.0,
    normalization: str = FuzzyJoinNormalization.WORD,
) -> Table:
    """Fuzzy matches within one table, excluding self-pairs
    (reference: smart_table_ops fuzzy_self_match)."""
    import pathway_tpu as pw

    col = resolve_expression(
        column if column is not None else table[table.column_names()[0]], table
    )
    packed = table.reduce(items=pw.reducers.tuple(pw.make_tuple(table.id, col)))

    def match(items) -> tuple:
        items = list(items or ())
        out = []
        for i, (lk, lv) in enumerate(items):
            others = items[:i] + items[i + 1 :]
            pairs = _score_pairs([(lk, lv)], others, normalization)
            out.extend(p for p in pairs if p[2] > threshold)
        # dedupe symmetric pairs
        seen = set()
        uniq = []
        for lk, rk, w in out:
            key = tuple(sorted((repr(lk), repr(rk))))
            if key not in seen:
                seen.add(key)
                uniq.append((lk, rk, w))
        return tuple(uniq)

    matches = packed.select(pairs=ApplyExpression(match, dt.ANY, packed.items))
    flat = matches.flatten(matches.pairs)
    return flat._select_exprs(
        {
            "left": ApplyExpression(lambda p: p[0], dt.POINTER, flat.pairs),
            "right": ApplyExpression(lambda p: p[1], dt.POINTER, flat.pairs),
            "weight": ApplyExpression(lambda p: float(p[2]), dt.FLOAT, flat.pairs),
        },
        universe=flat._universe,
    )
