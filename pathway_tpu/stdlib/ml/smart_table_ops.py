"""Fuzzy joins (reference: python/pathway/stdlib/ml/smart_table_ops/
``_fuzzy_join.py`` 470 LoC — feature extraction + weighted match scoring;
``fuzzy_match_tables``, ``fuzzy_self_match``, ``smart_fuzzy_match``,
``fuzzy_match_with_hint``).

Scoring follows the reference exactly: values decompose into features
(FuzzyJoinFeatureGeneration), each feature's weight is a function of its
occurrence count (FuzzyJoinNormalization: ``1/2^ceil(log2 cnt)``,
``1/ceil(log2(cnt+1))`` or raw count — _fuzzy_join.py:59-73), a pair's
score sums ``locc * rocc * weight(f)`` over shared features, and the
result keeps only MUTUAL best pairs: argmax per left then per right with
the reference's pseudoweight ``(weight, min_id, max_id)`` tiebreak
(_fuzzy_join.py:428-456).  ``by_hand_match`` pre-matched rows are
excluded from automatic matching and override the output
(_fuzzy_join.py:300-316).

The reference runs this as a dataflow of edge/feature tables with a
heavy/light feature split; here candidate generation + scoring run as
one packed reduce per side (host-side; token sets are tiny compared to
the vector plane) computing the same sum directly.
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict
from enum import IntEnum, auto

from ...internals import dtype as dt
from ...internals.desugaring import resolve_expression
from ...internals.expression import ApplyExpression
from ...internals.table import Table

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match_tables",
    "fuzzy_match_with_hint",
    "fuzzy_self_match",
    "smart_fuzzy_match",
]

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


class FuzzyJoinFeatureGeneration(IntEnum):
    """reference: _fuzzy_join.py:42 — how a value decomposes into
    features.  AUTO is our autoguess (lowercased word tokens — unlike the
    reference's case-sensitive split, 'Apple Inc' still matches 'apple
    incorporated'); TOKENIZE is the reference's exact whitespace split;
    LETTERS its lowercase alphanumeric characters."""

    AUTO = auto()
    TOKENIZE = auto()
    LETTERS = auto()

    @property
    def generate(self):
        cls = type(self)
        if self is cls.TOKENIZE:
            return lambda obj: str(obj).split()
        if self is cls.LETTERS:
            return lambda obj: [c.lower() for c in str(obj) if c.isalnum()]
        return lambda obj: _TOKEN_RE.findall(str(obj or "").lower())


class FuzzyJoinNormalization(IntEnum):
    """reference: _fuzzy_join.py:77 — feature weight as a function of its
    occurrence count."""

    WEIGHT = auto()
    LOGWEIGHT = auto()
    NONE = auto()

    @property
    def normalize(self):
        cls = type(self)
        if self is cls.WEIGHT:
            return lambda cnt: 0.0 if cnt == 0 else 1 / (2 ** math.ceil(math.log2(cnt)))
        if self is cls.NONE:
            return lambda cnt: float(cnt)
        return lambda cnt: 0.0 if cnt == 0 else 1 / math.ceil(math.log2(cnt + 1))


def _resolve_options(normalization, feature_generation):
    """Map legacy string spellings ("word"/"letters", rounds 1-3 of this
    port) onto the reference enums."""
    if normalization == "word":
        return FuzzyJoinNormalization.LOGWEIGHT, FuzzyJoinFeatureGeneration.AUTO
    if normalization == "letters":
        return FuzzyJoinNormalization.LOGWEIGHT, FuzzyJoinFeatureGeneration.LETTERS
    return (
        FuzzyJoinNormalization(normalization),
        FuzzyJoinFeatureGeneration(feature_generation),
    )


def _score_pairs(
    left_items: list[tuple],
    right_items: list[tuple],
    normalization: "FuzzyJoinNormalization",
    feature_generation: "FuzzyJoinFeatureGeneration",
    *,
    symmetric: bool = False,
    exclude_left: set | None = None,
    exclude_right: set | None = None,
    threshold: float = 0.0,
) -> list[tuple]:
    """[(left_key, right_key, score)] — the reference's mutual-best pairs.

    ``symmetric``: left_items IS right_items (self match); self-pairs are
    dropped and each unordered pair reported once (left < right)."""
    gen = feature_generation.generate
    norm = normalization.normalize
    exclude_left = exclude_left or set()
    exclude_right = exclude_right or set()

    left_feats = [
        (k, Counter(gen(v))) for k, v in left_items if k not in exclude_left
    ]
    if symmetric:
        right_feats = [
            (k, fs) for k, fs in left_feats if k not in exclude_right
        ]
    else:
        right_feats = [
            (k, Counter(gen(v)))
            for k, v in right_items
            if k not in exclude_right
        ]

    # occurrence counts over every edge (reference counts the concatenated
    # edge table, _fuzzy_join.py:356; for self match the edges exist once)
    cnt: Counter = Counter()
    for _, fs in left_feats:
        cnt.update(fs)
    if not symmetric:
        for _, fs in right_feats:
            cnt.update(fs)
    weight = {f: norm(c) for f, c in cnt.items()}

    postings: dict = defaultdict(list)
    for rk, fs in right_feats:
        for f, occ in fs.items():
            postings[f].append((rk, occ))

    scores: dict = defaultdict(float)
    for lk, fs in left_feats:
        for f, locc in fs.items():
            w = weight[f]
            for rk, rocc in postings.get(f, ()):
                if symmetric and rk == lk:
                    continue
                scores[(lk, rk)] += locc * rocc * w

    # mutual best with the reference's pseudoweight tiebreak: order pairs
    # by (weight, min_id, max_id) so ties resolve identically on both
    # sides (_fuzzy_join.py:428 weight_to_pseudoweight)
    def pseudo(lk, rk, w):
        a, b = (lk, rk) if lk < rk else (rk, lk)
        return (w, a, b)

    best_left: dict = {}
    for (lk, rk), w in scores.items():
        if w <= threshold:
            continue
        p = pseudo(lk, rk, w)
        if lk not in best_left or p > best_left[lk][0]:
            best_left[lk] = (p, rk, w)
    best_right: dict = {}
    for lk, (p, rk, w) in best_left.items():
        if rk not in best_right or p > best_right[rk][0]:
            best_right[rk] = (p, lk, w)

    out = []
    for rk, (p, lk, w) in best_right.items():
        if symmetric:
            # reference's final filter(left < right) (_fuzzy_join.py):
            # a pair surviving the double argmax only in the (c, b) with
            # c > b orientation is DROPPED, not normalized — matching
            # that exactly (ADVICE r4)
            if lk < rk:
                out.append((lk, rk, w))
        else:
            out.append((lk, rk, w))
    return out


def _pairs_output(flat):
    return flat._select_exprs(
        {
            "left": ApplyExpression(lambda p: p[0], dt.POINTER, flat.pairs),
            "right": ApplyExpression(lambda p: p[1], dt.POINTER, flat.pairs),
            "weight": ApplyExpression(lambda p: float(p[2]), dt.FLOAT, flat.pairs),
        },
        universe=flat._universe,
    )


def _pack_by_hand(by_hand_match):
    import pathway_tpu as pw

    if by_hand_match is None:
        return None
    return by_hand_match.reduce(
        items=pw.reducers.tuple(
            pw.make_tuple(
                by_hand_match.left, by_hand_match.right, by_hand_match.weight
            )
        )
    )


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    left_column=None,
    right_column=None,
    by_hand_match: Table | None = None,
    threshold: float = 0.0,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
) -> Table:
    """Best fuzzy pairing between two tables' text columns
    (reference: smart_table_ops ``fuzzy_match_tables``).  Returns columns
    (left, right, weight) with Pointer keys into the inputs;
    ``by_hand_match`` rows (left, right, weight) are taken as ground
    truth — excluded from matching and merged into the result."""
    lcol = resolve_expression(
        left_column if left_column is not None else left_table[left_table.column_names()[0]],
        left_table,
    )
    rcol = resolve_expression(
        right_column if right_column is not None else right_table[right_table.column_names()[0]],
        right_table,
    )
    normalization, feature_generation = _resolve_options(
        normalization, feature_generation
    )
    if (
        left_table is right_table
        and getattr(lcol, "name", None) is not None
        and getattr(lcol, "name", None) == getattr(rcol, "name", None)
    ):
        return fuzzy_self_match(
            left_table,
            lcol,
            by_hand_match=by_hand_match,
            threshold=threshold,
            normalization=normalization,
            feature_generation=feature_generation,
        )
    return _match_packed(
        left_table,
        lcol,
        right_table,
        rcol,
        by_hand_match,
        threshold,
        normalization,
        feature_generation,
    )


def smart_fuzzy_match(
    left_col,
    right_col,
    *,
    by_hand_match: Table | None = None,
    threshold: float = 0.0,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
) -> Table:
    """Column-level entry point (reference: _fuzzy_join.py:199
    ``smart_fuzzy_match``).  Detects self-match when both references name
    the same column of the same table."""
    import pathway_tpu as pw

    if not hasattr(left_col, "table") or not hasattr(right_col, "table"):
        raise TypeError(
            "smart_fuzzy_match takes column references; for computed "
            "expressions use fuzzy_match_tables(left_column=..., "
            "right_column=...)"
        )
    return fuzzy_match_tables(
        left_col.table,
        right_col.table,
        left_column=left_col,
        right_column=right_col,
        by_hand_match=by_hand_match,
        threshold=threshold,
        normalization=normalization,
        feature_generation=feature_generation,
    )


def _match_packed(
    left_table,
    lcol,
    right_table,
    rcol,
    by_hand_match,
    threshold,
    normalization,
    feature_generation,
):
    import pathway_tpu as pw

    left_packed = left_table.reduce(
        items=pw.reducers.tuple(pw.make_tuple(left_table.id, lcol))
    )
    right_packed = right_table.reduce(
        items=pw.reducers.tuple(pw.make_tuple(right_table.id, rcol))
    )
    hint_packed = _pack_by_hand(by_hand_match)

    def match(litems, ritems, hitems=()) -> tuple:
        hints = list(hitems or ())
        pairs = _score_pairs(
            list(litems or ()),
            list(ritems or ()),
            normalization,
            feature_generation,
            exclude_left={h[0] for h in hints},
            exclude_right={h[1] for h in hints},
            threshold=threshold,
        )
        return tuple(pairs) + tuple((h[0], h[1], float(h[2])) for h in hints)

    if hint_packed is None:
        matches = left_packed.join(right_packed).select(
            pairs=ApplyExpression(
                match, dt.ANY, left_packed.items, right_packed.items
            )
        )
    else:
        both = left_packed.join(right_packed).select(
            litems=left_packed.items, ritems=right_packed.items
        )
        # LEFT join: an EMPTY hint table must not wipe the automatic
        # matches (its packed reduce has zero rows)
        matches = both.join_left(hint_packed).select(
            pairs=ApplyExpression(
                match, dt.ANY, both.litems, both.ritems, hint_packed.items
            )
        )
    flat = matches.flatten(matches.pairs)
    return _pairs_output(flat)


def fuzzy_match_with_hint(
    left_col,
    right_col,
    by_hand_match: Table,
    *,
    threshold: float = 0.0,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
) -> Table:
    """reference: _fuzzy_join.py:282 — fuzzy match with a required table
    of hand-made matches (left, right, weight) that override automatic
    matching."""
    if by_hand_match is None:
        raise ValueError("fuzzy_match_with_hint requires by_hand_match")
    return smart_fuzzy_match(
        left_col,
        right_col,
        by_hand_match=by_hand_match,
        threshold=threshold,
        normalization=normalization,
        feature_generation=feature_generation,
    )


def fuzzy_self_match(
    table: Table,
    column=None,
    *,
    by_hand_match: Table | None = None,
    threshold: float = 0.0,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
) -> Table:
    """Fuzzy matches within one table, excluding self-pairs
    (reference: smart_table_ops ``fuzzy_self_match``)."""
    import pathway_tpu as pw

    normalization, feature_generation = _resolve_options(
        normalization, feature_generation
    )
    col = resolve_expression(
        column if column is not None else table[table.column_names()[0]], table
    )
    packed = table.reduce(items=pw.reducers.tuple(pw.make_tuple(table.id, col)))
    hint_packed = _pack_by_hand(by_hand_match)

    def match(items, hitems=()) -> tuple:
        hints = list(hitems or ())
        matched = {h[0] for h in hints} | {h[1] for h in hints}
        pairs = _score_pairs(
            list(items or ()),
            list(items or ()),
            normalization,
            feature_generation,
            symmetric=True,
            exclude_left=matched,
            exclude_right=matched,
            threshold=threshold,
        )
        return tuple(pairs) + tuple((h[0], h[1], float(h[2])) for h in hints)

    if hint_packed is None:
        matches = packed.select(
            pairs=ApplyExpression(match, dt.ANY, packed.items)
        )
    else:
        matches = packed.join_left(hint_packed).select(
            pairs=ApplyExpression(match, dt.ANY, packed.items, hint_packed.items)
        )
    flat = matches.flatten(matches.pairs)
    return _pairs_output(flat)
