"""Example datasets (reference: python/pathway/stdlib/ml/datasets/)."""

from . import classification

__all__ = ["classification"]
