"""Classification datasets as pathway tables
(reference: python/pathway/stdlib/ml/datasets/classification/__init__.py
``load_mnist_sample``/``load_mnist_stream``).

``load_mnist_sample`` fetches MNIST via sklearn's openml mirror — it
needs network access, exactly like the reference.  For air-gapped runs
(tests, TPU pods without egress) ``load_synthetic_sample`` produces a
deterministic gaussian-blob classification set with the same return
contract: (X_train, y_train, X_test, y_test) tables with ``data`` /
``label`` columns.
"""

from __future__ import annotations

import numpy as np

__all__ = ["load_mnist_sample", "load_mnist_stream", "load_synthetic_sample"]


def _as_tables(X_train, y_train, X_test, y_test):
    import pandas as pd

    from pathway_tpu.debug import table_from_pandas

    return (
        table_from_pandas(
            pd.DataFrame({"data": [np.asarray(r) for r in X_train]})
        ),
        table_from_pandas(pd.DataFrame({"label": list(y_train)})),
        table_from_pandas(
            pd.DataFrame({"data": [np.asarray(r) for r in X_test]})
        ),
        table_from_pandas(pd.DataFrame({"label": list(y_test)})),
    )


def load_mnist_sample(sample_size: int = 70000):
    """MNIST train/test split as four tables (reference behavior: fetches
    ``mnist_784`` from openml; requires network access)."""
    from sklearn.datasets import fetch_openml

    X, y = fetch_openml("mnist_784", version=1, return_X_y=True, as_frame=False)
    X = X / 255.0
    train_size = int(sample_size * 6 / 7)
    test_size = int(sample_size / 7)
    return _as_tables(
        X[:60000][:train_size],
        y[:60000][:train_size],
        X[60000:70000][:test_size],
        y[60000:70000][:test_size],
    )


#: the reference exposes the same alias (classification/__init__.py:42):
#: both names return static tables; stream them through pw.demo or a
#: connector if engine-timestamped arrival is needed
load_mnist_stream = load_mnist_sample


def load_synthetic_sample(
    sample_size: int = 700, d: int = 16, n_classes: int = 4, seed: int = 0
):
    """Offline stand-in for ``load_mnist_sample``: gaussian blobs with the
    same four-table return contract."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_classes, d)) * 4.0
    labels = rng.integers(0, n_classes, size=sample_size)
    X = centers[labels] + rng.standard_normal((sample_size, d))
    y = labels.astype(str)
    train = int(sample_size * 6 / 7)
    return _as_tables(X[:train], y[:train], X[train:], y[train:])
