"""Hidden Markov Model decoding as a custom reducer.

reference: python/pathway/stdlib/ml/hmm.py:11 ``create_hmm_reducer`` —
an accumulator running incremental Viterbi over an observation stream;
each engine timestamp yields the most likely state path decoded so far.

The graph argument is a ``networkx.DiGraph`` (or any object with the
same ``nodes``/``successors``/``get_edge_data``/``graph`` protocol):
nodes carry ``calc_emission_log_ppb(observation) -> float``, edges carry
``log_transition_ppb``, and ``graph.graph["start_nodes"]`` lists entry
states.  Plug the result into ``pw.reducers.udf_reducer``.
"""

from __future__ import annotations

from collections import deque

import numpy as np

__all__ = ["create_hmm_reducer"]


def create_hmm_reducer(
    graph, beam_size: int | None = None, num_results_kept: int | None = None
):
    """Build the accumulator class for ``pw.reducers.udf_reducer``
    (reference: ml/hmm.py:11)."""
    idx_of = {node: i for i, node in enumerate(graph.nodes())}
    node_of = {i: node for node, i in idx_of.items()}
    n_states = len(idx_of)
    effective_beam = beam_size if beam_size is not None else n_states + 1

    class HmmAccumulator:
        """Viterbi state: per-state log-probabilities + backpointers."""

        def __init__(self, observation):
            self.observation = observation
            self.ppb = np.full(n_states, -np.inf)
            self.backpointers: deque[np.ndarray] = deque()
            self.alive: list[int] = []
            for start in graph.graph["start_nodes"]:
                i = idx_of[start]
                self.ppb[i] = graph.nodes[start]["calc_emission_log_ppb"](
                    observation
                )
                self.alive.append(i)
            self.path_states = (node_of[int(self.ppb.argmax())],)

        @classmethod
        def from_row(cls, row):
            (observation,) = row
            return cls(observation)

        def __add__(self, other: "HmmAccumulator") -> "HmmAccumulator":
            # left fold in arrival order: `other` is always a fresh
            # single-observation accumulator (udf_reducer contract)
            observation = other.observation
            new_ppb = np.full(n_states, -np.inf)
            backptr = np.zeros(n_states, dtype=int)
            reachable: dict[int, tuple[float, int]] = {}
            for i in self.alive:
                src = node_of[i]
                base = self.ppb[i]
                for succ in graph.successors(src):
                    j = idx_of[succ]
                    score = base + graph.get_edge_data(src, succ)[
                        "log_transition_ppb"
                    ]
                    best = reachable.get(j)
                    if best is None or score > best[0]:
                        reachable[j] = (score, i)
            alive = []
            for j, (score, src_i) in reachable.items():
                emit = graph.nodes[node_of[j]]["calc_emission_log_ppb"](
                    observation
                )
                new_ppb[j] = emit + score
                backptr[j] = src_i
                alive.append(j)
            if len(alive) > effective_beam:
                costs = new_ppb[alive]
                keep = np.argpartition(costs, len(alive) - effective_beam)
                alive = [alive[s] for s in keep[-effective_beam:]]
            self.alive = alive
            self.ppb = new_ppb
            self.backpointers.append(backptr)
            if (
                num_results_kept is not None
                and len(self.backpointers) >= num_results_kept
            ):
                self.backpointers.popleft()
            path = [int(new_ppb.argmax())]
            for bp in reversed(self.backpointers):
                path.append(int(bp[path[-1]]))
            self.path_states = tuple(
                node_of[i] for i in reversed(path)
            )
            return self

        def retrieve(self) -> tuple:
            return self.path_states

    return HmmAccumulator
