"""k-approximate nearest neighbors (and classification) via LSH.

reference: python/pathway/stdlib/ml/classifiers/_knn_lsh.py
(``knn_lsh_classifier_train``:64, ``knn_lsh_generic_classifier_train``:135,
``knn_lsh_euclidean_classifier_train``:295, ``knn_lsh_classify``:306).

Redesign notes (not a translation): the reference unions candidate
buckets through L per-band join+update_rows rounds; here candidates come
from ONE flat (band, bucket) equi-join between data and queries — L rows
per side, a single join — followed by per-query dedup + batch distance
scoring in one pure UDF over numpy arrays (the same arithmetic the MXU
dense index in ``ops/knn.py`` uses, at bucket scale).
"""

from __future__ import annotations

from statistics import mode
from typing import Callable, Literal

import numpy as np

DistanceTypes = Literal["euclidean", "cosine"]

__all__ = [
    "knn_lsh_classifier_train",
    "knn_lsh_generic_classifier_train",
    "knn_lsh_euclidean_classifier_train",
    "knn_lsh_classify",
]


def _euclidean_distance(data: np.ndarray, query: np.ndarray) -> np.ndarray:
    return np.sum((data - query) ** 2, axis=1).astype(float)


def compute_cosine_dist(data: np.ndarray, query: np.ndarray) -> np.ndarray:
    return 1 - np.dot(data, query) / (
        np.linalg.norm(data, axis=1) * np.linalg.norm(query)
    )


def knn_lsh_classifier_train(
    data, L: int, type: DistanceTypes = "euclidean", **kwargs
):
    """Build the LSH index over ``data`` (column ``data`` holds vectors).
    Returns a query callable ``(queries, k, with_distances=False) -> Table``
    (reference: _knn_lsh.py:64)."""
    from ._lsh import (
        generate_cosine_lsh_bucketer,
        generate_euclidean_lsh_bucketer,
    )

    if type == "euclidean":
        projection = generate_euclidean_lsh_bucketer(
            kwargs["d"], kwargs["M"], L, kwargs["A"]
        )
        return knn_lsh_generic_classifier_train(
            data, projection, _euclidean_distance, L
        )
    elif type == "cosine":
        projection = generate_cosine_lsh_bucketer(kwargs["d"], kwargs["M"], L)
        return knn_lsh_generic_classifier_train(
            data, projection, compute_cosine_dist, L
        )
    raise ValueError(
        f"Not supported `type` {type} in knn_lsh_classifier_train. "
        "The allowed values are 'euclidean' and 'cosine'."
    )


def knn_lsh_euclidean_classifier_train(data, d, M, L, A):
    """reference: _knn_lsh.py:295."""
    from ._lsh import generate_euclidean_lsh_bucketer

    return knn_lsh_generic_classifier_train(
        data, generate_euclidean_lsh_bucketer(d, M, L, A),
        _euclidean_distance, L,
    )


def knn_lsh_generic_classifier_train(
    data, lsh_projection: Callable, distance_function: Callable, L: int
):
    """Index ``data`` with a generic bucketer; returns the query callable
    (reference: _knn_lsh.py:135)."""
    import pathway_tpu as pw
    from pathway_tpu.utils.jmespath_lite import compile_filter

    has_metadata = "metadata" in data.column_names()

    def flat_bands(table):
        flat = table.select(
            pairs=pw.apply(
                lambda v: tuple(
                    (i, int(b)) for i, b in enumerate(lsh_projection(v))
                ),
                table.data,
            )
        )
        flat = flat.flatten(pw.this.pairs, origin_id="origin_id")
        return flat.select(
            pw.this.origin_id,
            band=pw.apply(lambda p: p[0], pw.this.pairs),
            bucket=pw.apply(lambda p: p[1], pw.this.pairs),
        )

    data_flat = flat_bands(data)

    def lsh_perform_query(queries, k=None, with_distances: bool = False):
        if k is None and "k" not in queries.column_names():
            raise ValueError("pass k= or provide a `k` column on queries")
        q_flat = flat_bands(queries)
        cand = q_flat.join(
            data_flat,
            q_flat.band == data_flat.band,
            q_flat.bucket == data_flat.bucket,
        ).select(
            query_id=q_flat.origin_id,
            data_id=data_flat.origin_id,
        )
        # attach the candidate's vector (and metadata) so the scoring UDF
        # is a pure function of its row — retraction replay stays exact
        cand = cand.select(
            cand.query_id,
            cand.data_id,
            vec=data.ix(cand.data_id).data,
            meta=(
                data.ix(cand.data_id).metadata
                if has_metadata
                else pw.apply(lambda *_: None, cand.data_id)
            ),
        )
        per_query = cand.groupby(cand.query_id).reduce(
            cand.query_id,
            candidate_ids=pw.reducers.tuple(cand.data_id),
            candidate_vecs=pw.reducers.tuple(cand.vec),
            candidate_meta=pw.reducers.tuple(cand.meta),
        )
        enriched = per_query.with_id(
            per_query.query_id
        ).promise_universe_is_subset_of(queries)
        q_restricted = queries.restrict(enriched)

        @pw.udf(deterministic=True)
        def knns(query_vec, candidate_ids, candidate_vecs, candidate_meta,
                 k_val, metadata_filter) -> tuple:
            flt = None
            if metadata_filter is not None:
                try:
                    flt = compile_filter(metadata_filter)
                except Exception:
                    return ()
            seen = {}
            for cid, vec, meta in zip(
                candidate_ids, candidate_vecs, candidate_meta
            ):
                if cid in seen:
                    continue
                if flt is not None:
                    try:
                        if flt(getattr(meta, "value", meta)) is not True:
                            continue
                    except Exception:
                        continue
                seen[cid] = vec
            if not seen:
                return ()
            ids = list(seen.keys())
            arr = np.asarray(list(seen.values()), dtype=float)
            dists = distance_function(arr, np.asarray(query_vec, dtype=float))
            n = min(int(k_val), len(ids))
            top = np.argpartition(dists, n - 1)[:n]
            pairs = sorted(
                ((float(dists[i]), ids[i]) for i in top), key=lambda p: p[0]
            )
            return tuple((pid, d) for d, pid in pairs)

        has_filter = "metadata_filter" in queries.column_names()
        k_expr = (
            q_restricted.k if k is None
            else pw.apply(lambda *_: k, enriched.id)
        )
        filter_expr = (
            q_restricted.metadata_filter if has_filter
            else pw.apply(lambda *_: None, enriched.id)
        )
        knn_result = enriched.select(
            query_id=enriched.id,
            knns_ids_with_dists=knns(
                q_restricted.data,
                enriched.candidate_ids,
                enriched.candidate_vecs,
                enriched.candidate_meta,
                k_expr,
                filter_expr,
            ),
        )
        result = queries.join_left(
            knn_result, queries.id == knn_result.query_id
        ).select(
            knns_ids_with_dists=pw.coalesce(
                knn_result.knns_ids_with_dists, ()
            ),
            query_id=queries.id,
        )
        if not with_distances:
            result = result.select(
                pw.this.query_id,
                knns_ids=pw.apply(
                    lambda pairs: tuple(p[0] for p in pairs),
                    pw.this.knns_ids_with_dists,
                ),
            )
        return result

    return lsh_perform_query


def knn_lsh_classify(knn_model, data_labels, queries, k):
    """Label queries by majority vote over their k nearest neighbors
    (reference: _knn_lsh.py:306)."""
    import pathway_tpu as pw

    knns = knn_model(queries, k)
    flat = knns.filter(
        pw.apply(lambda ids: len(ids) > 0, knns.knns_ids)
    ).flatten(pw.this.knns_ids)
    flat = flat.select(
        flat.query_id,
        label=data_labels.ix(flat.knns_ids).label,
    )
    nonempty = flat.groupby(flat.query_id).reduce(
        flat.query_id,
        predicted_label=pw.apply(
            lambda labels: mode(labels), pw.reducers.tuple(flat.label)
        ),
    )
    nonempty = nonempty.with_id(nonempty.query_id).select(
        pw.this.predicted_label
    )
    empty = knns.with_id(knns.query_id).select(predicted_label=None)
    return empty.update_cells(nonempty.promise_universe_is_subset_of(empty))
