"""LSH classifiers, bucketers and clustering
(reference: python/pathway/stdlib/ml/classifiers/__init__.py)."""

from ._clustering_via_lsh import clustering_via_lsh
from ._knn_lsh import (
    compute_cosine_dist,
    knn_lsh_classifier_train,
    knn_lsh_classify,
    knn_lsh_euclidean_classifier_train,
    knn_lsh_generic_classifier_train,
)
from ._lsh import (
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
    lsh,
)

__all__ = [
    "clustering_via_lsh",
    "compute_cosine_dist",
    "generate_cosine_lsh_bucketer",
    "generate_euclidean_lsh_bucketer",
    "knn_lsh_classifier_train",
    "knn_lsh_classify",
    "knn_lsh_euclidean_classifier_train",
    "knn_lsh_generic_classifier_train",
    "lsh",
]
