"""(Pre)clustering via LSH bucket representatives.

reference: python/pathway/stdlib/ml/classifiers/_clustering_via_lsh.py
(``clustering_via_lsh``).  Bucket representatives (weighted centroids per
(band, bucketing) cell) are clustered with weighted k-means, then every
point takes the majority label over its buckets' representatives.
"""

from __future__ import annotations

import numpy as np

from ._lsh import lsh

__all__ = ["clustering_via_lsh"]


def _weighted_kmeans(
    data: np.ndarray, weights: np.ndarray, k: int, seed: int = 0,
    n_iter: int = 50,
) -> np.ndarray:
    """Small weighted k-means (k-means++ init).  sklearn is used when
    importable; this fallback keeps the API alive without it."""
    try:
        from sklearn.cluster import KMeans
    except ImportError:
        KMeans = None
    if KMeans is not None:
        # real fit errors (NaNs, bad weights) must propagate — only a
        # missing sklearn routes to the fallback implementation
        km = KMeans(n_clusters=min(k, len(data)), init="k-means++",
                    random_state=seed, n_init=10)
        km.fit(data, sample_weight=weights)
        return km.labels_
    rng = np.random.default_rng(seed)
    n = len(data)
    k = min(k, n)
    centers = [data[rng.integers(n)]]
    for _ in range(k - 1):
        d2 = np.min(
            [np.sum((data - c) ** 2, axis=1) for c in centers], axis=0
        )
        probs = d2 * weights
        total = probs.sum()
        if total <= 0:
            centers.append(data[rng.integers(n)])
            continue
        centers.append(data[rng.choice(n, p=probs / total)])
    centers_arr = np.asarray(centers)
    labels = np.zeros(n, dtype=int)
    for _ in range(n_iter):
        dists = ((data[:, None, :] - centers_arr[None, :, :]) ** 2).sum(-1)
        new_labels = dists.argmin(axis=1)
        if (new_labels == labels).all():
            break
        labels = new_labels
        for j in range(len(centers_arr)):
            mask = labels == j
            if mask.any():
                w = weights[mask]
                centers_arr[j] = (data[mask] * w[:, None]).sum(0) / w.sum()
    return labels


def clustering_via_lsh(data, bucketer, k: int):
    """Cluster ``data.data`` vectors into ``k`` groups
    (reference: _clustering_via_lsh.py ``clustering_via_lsh``)."""
    import pathway_tpu as pw
    from pathway_tpu.stdlib.utils.col import apply_all_rows

    flat = lsh(data, bucketer, origin_id="data_id", include_data=True)
    reps = (
        flat.groupby(flat.bucketing, flat.band)
        .reduce(
            flat.bucketing,
            flat.band,
            vec_sum=pw.apply(
                lambda t: np.sum(np.asarray(t, dtype=float), axis=0),
                pw.reducers.tuple(flat.data),
            ),
            count=pw.reducers.count(),
        )
        .select(
            pw.this.bucketing,
            pw.this.band,
            data=pw.apply(lambda s, c: s / c, pw.this.vec_sum, pw.this.count),
            weight=pw.this.count,
        )
    )

    def _cluster(vecs, weights):
        return [
            int(x)
            for x in _weighted_kmeans(
                np.asarray(list(vecs), dtype=float),
                np.asarray(list(weights), dtype=float),
                k,
            )
        ]

    labels = apply_all_rows(
        reps.data, reps.weight, fun=_cluster, result_col_name="label"
    ).with_universe_of(reps)
    reps = reps.select(
        reps.bucketing, reps.band, reps.weight, label=labels.label
    )
    votes = flat.join(
        reps,
        flat.bucketing == reps.bucketing,
        flat.band == reps.band,
    ).select(flat.data_id, reps.label)
    majority = (
        votes.groupby(votes.data_id)
        .reduce(
            votes.data_id,
            label=pw.apply(
                lambda ls: max(set(ls), key=ls.count),
                pw.reducers.tuple(votes.label),
            ),
        )
    )
    return majority.with_id(majority.data_id).select(pw.this.label)
