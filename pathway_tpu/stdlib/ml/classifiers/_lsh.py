"""LSH bucketers: map vectors to L band ids so similar items collide.

reference: python/pathway/stdlib/ml/classifiers/_lsh.py
(``generate_euclidean_lsh_bucketer``:31, ``generate_cosine_lsh_bucketer``:59,
``lsh``:82).  TPU-first shape: each bucketer is ONE (batch, d) x (d, M*L)
matmul over the whole batch — a single dense product instead of the
reference's per-row apply, so large batches ride the MXU when jax arrays
come in.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = [
    "generate_euclidean_lsh_bucketer",
    "generate_cosine_lsh_bucketer",
    "lsh",
]


def _fingerprint_rows(mat: np.ndarray) -> np.ndarray:
    """Collapse each row of ints to one stable 63-bit id (the reference
    engine fingerprints per band; any deterministic mix works)."""
    out = np.empty(mat.shape[0], dtype=np.int64)
    for i, row in enumerate(np.ascontiguousarray(mat, dtype=np.int64)):
        h = hashlib.blake2b(row.tobytes(), digest_size=8).digest()
        out[i] = int.from_bytes(h, "little") >> 1
    return out


def generate_euclidean_lsh_bucketer(
    d: int, M: int, L: int, A: float = 1.0, seed: int = 0
):
    """Euclidean LSH: project on M*L random lines, floor-divide by bucket
    width ``A``, AND the M ints per band into one id; L band ids out.

    Example:

    >>> import numpy as np
    >>> from pathway_tpu.stdlib.ml.classifiers import (
    ...     generate_euclidean_lsh_bucketer)
    >>> bucketer = generate_euclidean_lsh_bucketer(d=4, M=3, L=5, A=2.0)
    >>> near_a = bucketer(np.zeros(4))
    >>> near_b = bucketer(np.full(4, 0.01))   # a hair away: same buckets
    >>> far = bucketer(np.full(4, 100.0))     # far away: different buckets
    >>> near_a.shape, bool((near_a == near_b).all()), bool((near_a == far).any())
    ((5,), True, False)
    """
    gen = np.random.default_rng(seed=seed)
    lines = gen.standard_normal((d, M * L))
    lines = lines / np.linalg.norm(lines, axis=0)
    shift = gen.random(size=M * L) * A

    def bucketify(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        buckets = np.floor_divide(x @ lines + shift, A).astype(np.int64)
        if buckets.ndim == 1:
            return _fingerprint_rows(buckets.reshape(L, M))
        return np.stack(
            [_fingerprint_rows(b.reshape(L, M)) for b in buckets]
        )

    return bucketify


def generate_cosine_lsh_bucketer(d: int, M: int, L: int, seed: int = 0):
    """Cosine LSH: sign bits against M*L random hyperplanes, M bits packed
    per band; L band ids out."""
    gen = np.random.default_rng(seed=seed)
    planes = gen.standard_normal((d, M * L))
    powers = 2 ** np.arange(M, dtype=np.int64)

    def bucketify(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        signs = (x @ planes >= 0).astype(np.int64)
        if signs.ndim == 1:
            return signs.reshape(L, M) @ powers
        return np.einsum("blm,m->bl", signs.reshape(-1, L, M), powers)

    return bucketify


def lsh(data, bucketer, origin_id: str = "origin_id", include_data: bool = True):
    """Flat (band, bucketing) representation: L rows per input row
    (reference: _lsh.py:82 ``lsh``)."""
    import pathway_tpu as pw

    flat = data.select(
        buckets=pw.apply(
            lambda x: tuple(
                (i, int(b)) for i, b in enumerate(bucketer(x))
            ),
            data.data,
        )
    )
    flat = flat.flatten(pw.this.buckets, origin_id=origin_id)
    cols = {
        origin_id: flat[origin_id],
        "band": pw.apply(lambda p: p[0], flat.buckets),
        "bucketing": pw.apply(lambda p: p[1], flat.buckets),
    }
    if include_data:
        cols["data"] = data.ix(flat[origin_id]).data
    return flat.select(**cols)
