"""ML utilities (reference: python/pathway/stdlib/ml/utils.py).

``classifier_accuracy`` groups prediction/label matches so the result is
a two-row live table (match=True/False with counts) that stays current as
the underlying streams update.
"""

from __future__ import annotations

__all__ = ["classifier_accuracy"]


def classifier_accuracy(predicted_labels, exact_labels):
    """Counts of matching / non-matching predictions
    (reference: ml/utils.py:13)."""
    import pathway_tpu as pw

    # the reference promises the subset up front (ml/utils.py:14) — the
    # predictions' universe is derived from the queries, which share keys
    # with the labels table
    predicted_labels = predicted_labels.promise_universe_is_subset_of(
        exact_labels
    )
    comparative = predicted_labels.select(
        predicted_label=predicted_labels.predicted_label,
        label=exact_labels.restrict(predicted_labels).label,
    )
    comparative = comparative.select(
        comparative.predicted_label,
        comparative.label,
        match=comparative.label == comparative.predicted_label,
    )
    return comparative.groupby(comparative.match).reduce(
        cnt=pw.reducers.count(),
        value=comparative.match,
    )
