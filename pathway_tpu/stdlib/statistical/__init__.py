"""Statistical helpers (reference: python/pathway/stdlib/statistical/)."""

from __future__ import annotations

__all__ = ["interpolate"]


def interpolate(table, timestamp, *values, mode=None):
    raise NotImplementedError(
        "interpolate lands with the temporal/ordered milestone"
    )
