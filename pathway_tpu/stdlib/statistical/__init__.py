"""Statistical helpers (reference: python/pathway/stdlib/statistical/
``interpolate`` with ``InterpolateMode.LINEAR``)."""

from __future__ import annotations

import enum

from ...internals import dtype as dt
from ...internals.desugaring import resolve_expression
from ...internals.expression import ApplyExpression
from ...internals.table import Table

__all__ = ["interpolate", "InterpolateMode"]


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def interpolate(
    table: Table, timestamp, *values, mode: InterpolateMode | None = None
) -> Table:
    """Fill None cells by linear interpolation along ``timestamp`` order;
    edge gaps take the nearest known value (reference:
    stdlib/statistical/__init__.py interpolate).

    Implemented as a packed reduce + per-row rescan: the whole series is
    gathered once per micro-batch and each row looks up its neighbors in
    the packed copy — the diff engine re-runs this only when the series
    changes.
    """
    import pathway_tpu as pw

    if mode is not None and mode is not InterpolateMode.LINEAR:
        raise ValueError(f"unsupported interpolate mode {mode!r}")
    ts_e = resolve_expression(timestamp, table)
    value_refs = [resolve_expression(v, table) for v in values]
    names = [v.name for v in value_refs]

    packed = table.reduce(
        series=pw.reducers.tuple(pw.make_tuple(ts_e, *value_refs)),
    )

    def interp(ts, row_vals, series):
        pts = sorted(series or (), key=lambda p: p[0])
        out = []
        for i, v in enumerate(row_vals):
            if v is not None:
                out.append(v)
                continue
            known = [(p[0], p[1 + i]) for p in pts if p[1 + i] is not None]
            prev = next_ = None
            for t, kv in known:
                if t <= ts:
                    prev = (t, kv)
                elif next_ is None:
                    next_ = (t, kv)
                    break
            if prev is None and next_ is None:
                out.append(None)
            elif prev is None:
                out.append(next_[1])
            elif next_ is None:
                out.append(prev[1])
            elif next_[0] == prev[0]:
                out.append(prev[1])
            else:
                frac = (ts - prev[0]) / (next_[0] - prev[0])
                out.append(prev[1] + (next_[1] - prev[1]) * frac)
        return tuple(out)

    joined = table.join_left(packed, id=table.id)
    with_filled = joined.select(
        *[table[n] for n in table.column_names()],
        _filled=ApplyExpression(
            interp,
            dt.ANY,
            ts_e,
            pw.make_tuple(*value_refs),
            packed.series,
        ),
    )
    out_exprs = {}
    for n in table.column_names():
        if n in names:
            i = names.index(n)
            out_exprs[n] = ApplyExpression(
                lambda f, i=i: f[i], dt.Optional(dt.FLOAT), with_filled["_filled"]
            )
        else:
            out_exprs[n] = with_filled[n]
    return with_filled._select_exprs(out_exprs, universe=with_filled._universe)
