"""Inner index implementations + factories.

reference: python/pathway/stdlib/indexing/nearest_neighbors.py (USearchKnn:65,
BruteForceKnn:170, LshKnn:262; factories :428-560 with auto dim probing) and
src/external_integration/ (brute force, usearch HNSW, tantivy BM25).

TPU design: vector retrieval is exact brute-force or LSH over HBM via
``ops/`` (one fused MXU matmul + top-k beats HNSW graph walks on TPU for
realistic corpus sizes; the USearch factory name is kept for API parity and
maps to the HBM index).  BM25 is host-side (tiny state, string-heavy).
Metadata filtering applies the JMESPath-lite filter post-search with
oversampling, like DerivedFilteredSearchIndex (mod.rs:248-310).
"""

from __future__ import annotations

import math
import threading
from collections import Counter, defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Hashable

import numpy as np

from ...ops.knn import DeviceKnnIndex
from ...ops.lsh import LshProjector
from ...ops.quantized_scoring import is_quant_record
from ...ops.topk import topk_search
from ...utils.jmespath_lite import compile_filter

__all__ = [
    "InnerIndexImpl",
    "InnerIndexFactory",
    "BruteForceKnnFactory",
    "UsearchKnnFactory",
    "LshKnnFactory",
    "TantivyBM25Factory",
    "BM25Factory",
    "USearchMetricKind",
    "BruteForceKnnMetricKind",
]


class USearchMetricKind:
    COS = "cos"
    L2SQ = "l2sq"
    IP = "dot"


BruteForceKnnMetricKind = USearchMetricKind


class InnerIndexImpl:
    """Runtime index protocol consumed by the external-index operator
    (reference: src/external_integration/mod.rs:40 ``ExternalIndex`` trait)."""

    query_is_text = False

    def add(self, key: Hashable, data: Any, metadata: Any) -> None:
        raise NotImplementedError

    def add_batch(self, keys, datas, metadatas) -> None:
        """One flush's worth of adds; implementations that can stage a
        whole batch (one device scatter instead of N) override this."""
        for key, data, meta in zip(keys, datas, metadatas):
            self.add(key, data, meta)

    def remove(self, key: Hashable) -> None:
        raise NotImplementedError

    def search(
        self, queries: list[tuple[Any, int, str | None]]
    ) -> list[list[tuple[Hashable, float]]]:
        raise NotImplementedError


class _FilteredMixin:
    """Post-search metadata filtering with oversampling."""

    OVERSAMPLE = 4

    def __init__(self):
        self.metadata: dict[Hashable, Any] = {}
        self._filter_cache: dict[str, Callable] = {}

    def _store_meta(self, key, metadata):
        if metadata is not None:
            from ...internals.value import Json

            if isinstance(metadata, Json):
                metadata = metadata.value
            self.metadata[key] = metadata

    def _drop_meta(self, key):
        self.metadata.pop(key, None)

    def _filter_fn(self, expr: str) -> Callable:
        fn = self._filter_cache.get(expr)
        if fn is None:
            fn = self._filter_cache[expr] = compile_filter(expr)
        return fn

    def _apply_filter(
        self, results: list[tuple[Hashable, float]], flt: str | None, k: int
    ) -> list[tuple[Hashable, float]]:
        if flt is None:
            return results[:k]
        fn = self._filter_fn(flt)
        out = []
        for key, score in results:
            if fn(self.metadata.get(key)):
                out.append((key, score))
                if len(out) == k:
                    break
        return out


class BruteForceKnnIndex(_FilteredMixin, InnerIndexImpl):
    """Exact KNN in HBM (ops/knn.py) — replaces both the reference's
    brute-force index and, on TPU, the USearch HNSW one.

    With ``mesh`` the vector matrix is row-sharded over the mesh's data
    axis and queries merge across chips over ICI (parallel/index.py) —
    the multi-chip inversion of the reference's full-replica-per-worker
    design (src/engine/dataflow/operators/external_index.rs:95-98)."""

    def __init__(
        self,
        dim: int,
        metric: str = "cos",
        capacity: int = 1024,
        mesh=None,
        index_dtype: str | None = None,
        hot_rows: int | None = None,
    ):
        _FilteredMixin.__init__(self)
        if hot_rows is None:
            from ...tiering import tier_hot_rows_default

            hot_rows = tier_hot_rows_default()
        if hot_rows and hot_rows > 0:
            # tiered serving: HBM hot tier (per-shard when a mesh is
            # given) + routed host-RAM cold tier — the corpus is no
            # longer bounded by device HBM (pathway_tpu/tiering)
            from ...tiering import TieredKnnIndex

            self.index = TieredKnnIndex(
                dim=dim, hot_rows=int(hot_rows), metric=metric,
                capacity=capacity, mesh=mesh, index_dtype=index_dtype,
            )
        elif mesh is not None:
            from ...parallel.index import ShardedKnnIndex

            self.index = ShardedKnnIndex(
                dim=dim, mesh=mesh, metric=metric, capacity=capacity,
                index_dtype=index_dtype,
            )
        else:
            self.index = DeviceKnnIndex(
                dim=dim, metric=metric, capacity=capacity,
                index_dtype=index_dtype,
            )

    def add(self, key, data, metadata) -> None:
        if is_quant_record(data):
            self.index.upsert_coded(key, data)
        else:
            self.index.upsert(key, np.asarray(data, dtype=np.float32))
        self._store_meta(key, metadata)

    def add_batch(self, keys, datas, metadatas) -> None:
        """Batched add: one staged scatter for the whole flush.  A DEVICE
        array batch (the ingest pipeline's encoder output, rows beyond
        ``len(keys)`` being dispatch pads) is handed to the index without
        a host round trip (``DeviceKnnIndex.upsert_batch``).  Snapshot
        restore batches may carry quantized records (possibly mixed with
        raw f32 rows across a dtype transition) — records go straight to
        the coded staging path, zero re-quantization."""
        if hasattr(datas, "shape") and not isinstance(datas, np.ndarray):
            self.index.upsert_batch(list(keys), datas)  # device batch
        elif isinstance(datas, np.ndarray):
            self.index.upsert_batch(
                list(keys), datas.astype(np.float32, copy=False)
            )
        else:
            # stage in ORDER, flushing buffered raw rows before each
            # record — a key appearing twice in one batch (raw then
            # record or vice versa) must keep its LAST value, the same
            # last-write-wins contract upsert_batch documents
            raw_keys, raw_rows = [], []

            def _flush_raw():
                if raw_keys:
                    self.index.upsert_batch(list(raw_keys), np.stack(raw_rows))
                    raw_keys.clear()
                    raw_rows.clear()

            for key, data in zip(keys, datas):
                if is_quant_record(data):
                    _flush_raw()
                    self.index.upsert_coded(key, data)
                else:
                    raw_keys.append(key)
                    raw_rows.append(
                        np.asarray(data, dtype=np.float32).reshape(-1)
                    )
            _flush_raw()
        for key, meta in zip(keys, metadatas):
            self._store_meta(key, meta)

    def remove(self, key) -> None:
        self.index.remove(key)
        self._drop_meta(key)

    def search(self, queries):
        if not queries:
            return []
        vecs = np.stack([np.asarray(q[0], dtype=np.float32) for q in queries])
        return self.search_embedded(vecs, [(k, flt) for _, k, flt in queries])

    def search_embedded(self, vecs, specs):
        """Fused-path search over pre-embedded queries: ``vecs`` is the
        whole ``[Q, D]`` batch (numpy or device array) handed straight to
        the device index — the serving scheduler's embed→search tick
        never re-stages per-query rows on host.  ``specs`` is one
        ``(k, metadata_filter)`` pair per query."""
        if not specs:
            return []
        max_k = max(k for k, _ in specs)
        oversample = self.OVERSAMPLE if any(flt for _, flt in specs) else 1
        # n_valid: a fused device batch carries dispatch-pad rows past
        # len(specs) — skip their host-side result assembly entirely
        raw = self.index.search(vecs, max_k * oversample, n_valid=len(specs))
        return [
            self._apply_filter(row, flt, k)
            for row, (k, flt) in zip(raw, specs)
        ]

    # -- snapshot routing/placement protocol (tiered inner index) -------
    # ExternalIndexNode persists the routing spec in the delta-chunk
    # header and the tier placement as a reserved state row; these
    # delegations surface the inner index's half of that contract.
    def snapshot_header(self) -> dict | None:
        fn = getattr(self.index, "snapshot_header", None)
        return fn() if fn is not None else None

    def apply_snapshot_header(self, header: dict) -> None:
        fn = getattr(self.index, "apply_snapshot_header", None)
        if fn is not None:
            fn(header)

    @property
    def placement_dirty(self) -> bool:
        return bool(getattr(self.index, "placement_dirty", False))

    def placement_blob_if_dirty(self) -> dict | None:
        fn = getattr(self.index, "placement_blob_if_dirty", None)
        return fn() if fn is not None else None

    def restore_placement(self, blob: dict) -> None:
        fn = getattr(self.index, "restore_placement", None)
        if fn is not None:
            fn(blob)

    def finish_restore(self) -> None:
        fn = getattr(self.index, "finish_restore", None)
        if fn is not None:
            fn()


class LshKnnIndex(_FilteredMixin, InnerIndexImpl):
    """LSH bucketed KNN (reference: _knn_lsh.py semantics; device scoring)."""

    def __init__(
        self,
        dim: int,
        metric: str = "cos",
        n_or: int = 8,
        n_and: int = 10,
        bucket_length: float = 10.0,
        capacity: int = 1024,
        seed: int = 0,
    ):
        _FilteredMixin.__init__(self)
        self.projector = LshProjector(dim, n_or=n_or, n_and=n_and, seed=seed)
        self.index = DeviceKnnIndex(dim=dim, metric=metric, capacity=capacity)
        self.buckets: dict[tuple[int, int], set] = defaultdict(set)
        self.sig_of_key: dict[Hashable, np.ndarray] = {}
        self._pending: dict[Hashable, np.ndarray] = {}
        # serving threads query while an ingest thread adds — same
        # contract as DeviceKnnIndex (ops/knn.py), which this class wraps
        self._lock = threading.RLock()

    def add(self, key, data, metadata) -> None:
        # flatten up front: upsert accepts any shape via reshape(-1), and the
        # staging dict must stay np.stack-homogeneous for the batched flush
        vec = np.asarray(data, dtype=np.float32).reshape(-1)
        with self._lock:
            self.index.upsert(key, vec)
            # Signature computation is deferred and batched: one device
            # matmul per flush instead of one per add.  A per-add round trip
            # is ruinous when the chip is remote (observed: 30k adds never
            # finishing over a tunneled TPU, while one batched 30k x dim
            # matmul is milliseconds).
            self._pending[key] = vec
            self._store_meta(key, metadata)

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        keys = list(self._pending)
        vecs = np.stack([self._pending[k] for k in keys])
        # compute signatures BEFORE dropping the staging dict: a transient
        # device failure here must leave the flush retryable, not silently
        # strip these keys out of every future candidate set
        sigs = self.projector.signatures(vecs)
        for k in keys:
            self._pending.pop(k, None)
        for key, sig in zip(keys, sigs):
            old = self.sig_of_key.get(key)
            if old is not None:  # re-add: drop stale bucket entries
                for band, bucket in enumerate(old):
                    self.buckets[(band, int(bucket))].discard(key)
            self.sig_of_key[key] = sig
            for band, bucket in enumerate(sig):
                self.buckets[(band, int(bucket))].add(key)

    def remove(self, key) -> None:
        with self._lock:
            self._pending.pop(key, None)
            self.index.remove(key)
            sig = self.sig_of_key.pop(key, None)
            if sig is not None:
                for band, bucket in enumerate(sig):
                    self.buckets[(band, int(bucket))].discard(key)
            self._drop_meta(key)

    def search(self, queries):
        if not queries:
            return []
        vecs = np.stack([np.asarray(q[0], dtype=np.float32) for q in queries])
        # query signatures only read the (immutable) projections — no lock
        sigs = self.projector.signatures(vecs)
        # hold the lock just long enough to flush staged adds and snapshot
        # candidate sets; the single batched device rescoring call below
        # must NOT serialize ingest (search_among_batched resolves/filters
        # keys under DeviceKnnIndex's own lock, tolerating concurrent
        # removals)
        with self._lock:
            self._flush_pending()
            cand_lists = []
            for sig in sigs:
                candidates: set = set()
                for band, bucket in enumerate(sig):
                    candidates |= self.buckets.get((band, int(bucket)), set())
                cand_lists.append(list(candidates))
        # exact rescoring over the candidate sets only, ALL queries in one
        # device call (reference: _knn_lsh.py:219-256 knn candidate
        # rescoring).  The per-query form costs one RPC round trip each
        # on a remote chip — the dominant term in the measured 155-178
        # ms/query LSH numbers in benchmarks/KNN_CROSSOVER.md.
        kmax = max(
            q[1] * (self.OVERSAMPLE if q[2] else 1) for q in queries
        )
        raw_rows = self.index.search_among_batched(vecs, cand_lists, kmax)
        results = []
        for (data, k, flt), raw in zip(queries, raw_rows):
            oversample = self.OVERSAMPLE if flt else 1
            results.append(self._apply_filter(raw[: k * oversample], flt, k))
        return results

    # -- snapshot routing spec ------------------------------------------
    # Bugfix (ISSUE 12): the projector's seed/projections were not part
    # of any snapshot — a process restored from a snapshot written under
    # a different seed (or a changed code default) would bucket the SAME
    # vectors differently and route queries to the wrong partitions.
    # The spec now rides the index delta-chunk header (PR 6 framing,
    # FORMAT_VERSION-compatible) and is re-applied before restore.
    def snapshot_header(self) -> dict:
        return {"lsh": self.projector.spec()}

    def apply_snapshot_header(self, header: dict) -> None:
        spec = (header or {}).get("lsh")
        if not spec or self.projector.spec() == spec:
            return
        with self._lock:
            if self.sig_of_key or self._pending:
                # applied mid-life (not the usual empty-at-restore case):
                # existing signatures were computed under the old
                # projections and must not mix with new ones — the raw
                # vectors needed to recompute them are not retained, so
                # refuse (BEFORE touching the projector — a half-applied
                # swap would corrupt the very buckets the guard protects)
                raise RuntimeError(
                    "LSH projector spec can only be applied to an empty "
                    "index (restore order applies the header before rows)"
                )
            self.projector = LshProjector.from_spec(spec)


class BM25Index(_FilteredMixin, InnerIndexImpl):
    """Okapi BM25 full-text index, host-side
    (reference: src/external_integration/tantivy_integration.rs;
    stdlib/indexing/bm25.py:41 TantivyBM25)."""

    query_is_text = True

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        _FilteredMixin.__init__(self)
        self.k1 = k1
        self.b = b
        self.doc_terms: dict[Hashable, Counter] = {}
        self.doc_len: dict[Hashable, int] = {}
        self.postings: dict[str, set] = defaultdict(set)
        self.total_len = 0
        # the serving scheduler searches from its own thread while the
        # engine thread mutates — same contract as DeviceKnnIndex's lock
        self._lock = threading.RLock()

    @staticmethod
    def _terms(text: str) -> list[str]:
        import re

        return re.findall(r"\w+", str(text).lower())

    def add(self, key, data, metadata) -> None:
        with self._lock:
            if key in self.doc_terms:
                self.remove(key)
            terms = Counter(self._terms(data))
            self.doc_terms[key] = terms
            n = sum(terms.values())
            self.doc_len[key] = n
            self.total_len += n
            for t in terms:
                self.postings[t].add(key)
            self._store_meta(key, metadata)

    def remove(self, key) -> None:
        with self._lock:
            terms = self.doc_terms.pop(key, None)
            if terms is None:
                return
            self.total_len -= self.doc_len.pop(key, 0)
            for t in terms:
                self.postings[t].discard(key)
            self._drop_meta(key)

    def search(self, queries):
        with self._lock:
            return self._search_locked(queries)

    def _search_locked(self, queries):
        n_docs = len(self.doc_terms)
        if n_docs == 0:
            return [[] for _ in queries]
        avg_len = self.total_len / n_docs
        results = []
        for data, k, flt in queries:
            scores: dict[Hashable, float] = defaultdict(float)
            for term in self._terms(data):
                docs = self.postings.get(term)
                if not docs:
                    continue
                idf = math.log(1 + (n_docs - len(docs) + 0.5) / (len(docs) + 0.5))
                for key in docs:
                    tf = self.doc_terms[key][term]
                    dl = self.doc_len[key]
                    scores[key] += (
                        idf
                        * tf
                        * (self.k1 + 1)
                        / (tf + self.k1 * (1 - self.b + self.b * dl / avg_len))
                    )
            ranked = sorted(scores.items(), key=lambda kv: -kv[1])
            results.append(self._apply_filter(ranked, flt, k))
        return results


# ---------------------------------------------------------------------------
# factories (reference: nearest_neighbors.py:428-560; bm25.py:109)
# ---------------------------------------------------------------------------


@dataclass
class InnerIndexFactory:
    """Builds an InnerIndexImpl per run (reference:
    AbstractRetrieverFactory / ExternalIndexFactory)."""

    def build_inner_index(self) -> InnerIndexImpl:
        raise NotImplementedError

    # reference probes the embedder with "." to learn the dimension
    # (nearest_neighbors.py:411 _get_embed_dimensions)
    def _resolve_dim(self, dim, embedder) -> int:
        if dim is not None:
            return dim
        if embedder is not None:
            if hasattr(embedder, "get_embedding_dimension"):
                d = embedder.get_embedding_dimension()
                if d:
                    return d
            probe = _call_embedder(embedder, ".")
            return int(np.asarray(probe).reshape(-1).shape[0])
        raise ValueError("either dimensions or embedder must be provided")


def _call_embedder(embedder, text: str):
    import asyncio
    import inspect

    fn = getattr(embedder, "__wrapped__", embedder)
    if inspect.iscoroutinefunction(fn):
        return asyncio.run(fn(text))
    result = fn(text)
    if inspect.iscoroutine(result):
        return asyncio.run(result)
    return result


@dataclass
class BruteForceKnnFactory(InnerIndexFactory):
    """reference: nearest_neighbors.py:482.  ``mesh`` shards the index
    over a device mesh (ShardedKnnIndex) for multi-chip serving."""

    dimensions: int | None = None
    reserved_space: int = 1024
    metric: str = USearchMetricKind.COS
    embedder: Any = None
    mesh: Any = None
    #: "f32" / "bf16" / "int8"; None = the PATHWAY_INDEX_DTYPE default
    index_dtype: str | None = None
    #: >0 = tiered index with this HBM hot-row budget;
    #: None = the PATHWAY_TIER_HOT_ROWS default (0 keeps it untiered)
    hot_rows: int | None = None

    def build_inner_index(self) -> InnerIndexImpl:
        dim = self._resolve_dim(self.dimensions, self.embedder)
        return BruteForceKnnIndex(
            dim=dim, metric=self.metric, capacity=self.reserved_space,
            mesh=self.mesh, index_dtype=self.index_dtype,
            hot_rows=self.hot_rows,
        )


@dataclass
class UsearchKnnFactory(InnerIndexFactory):
    """reference: nearest_neighbors.py:428 — HNSW there; on TPU the exact
    HBM matmul index answers faster than a host HNSW walk, so this maps to
    the same device index (connectivity/ef params accepted, unused)."""

    dimensions: int | None = None
    reserved_space: int = 1024
    metric: str = USearchMetricKind.COS
    connectivity: int = 0
    expansion_add: int = 0
    expansion_search: int = 0
    embedder: Any = None
    mesh: Any = None
    #: "f32" / "bf16" / "int8"; None = the PATHWAY_INDEX_DTYPE default
    index_dtype: str | None = None
    #: >0 = tiered index with this HBM hot-row budget;
    #: None = the PATHWAY_TIER_HOT_ROWS default (0 keeps it untiered)
    hot_rows: int | None = None

    def build_inner_index(self) -> InnerIndexImpl:
        dim = self._resolve_dim(self.dimensions, self.embedder)
        return BruteForceKnnIndex(
            dim=dim, metric=self.metric, capacity=self.reserved_space,
            mesh=self.mesh, index_dtype=self.index_dtype,
            hot_rows=self.hot_rows,
        )


@dataclass
class LshKnnFactory(InnerIndexFactory):
    """reference: nearest_neighbors.py:528"""

    dimensions: int | None = None
    n_or: int = 8
    n_and: int = 10
    bucket_length: float = 10.0
    distance_type: str = "cosine"
    embedder: Any = None
    #: projection seed — persisted in the snapshot header so a restored
    #: process routes queries to the same buckets
    seed: int = 0

    def build_inner_index(self) -> InnerIndexImpl:
        dim = self._resolve_dim(self.dimensions, self.embedder)
        metric = "cos" if self.distance_type.startswith("cos") else "l2sq"
        return LshKnnIndex(
            dim=dim, metric=metric, n_or=self.n_or, n_and=self.n_and,
            bucket_length=self.bucket_length, seed=self.seed,
        )


@dataclass
class TantivyBM25Factory(InnerIndexFactory):
    """reference: bm25.py:109 (name kept for parity; host-side Okapi BM25)."""

    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_inner_index(self) -> InnerIndexImpl:
        return BM25Index()


BM25Factory = TantivyBM25Factory
