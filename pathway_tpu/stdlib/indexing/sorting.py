"""Sorting: prev/next pointers per instance.

reference: python/pathway/stdlib/indexing/sorting.py:230 ``sort`` backed by
src/engine/dataflow/operators/prev_next.rs ``add_prev_next_pointers``.
"""

from __future__ import annotations

from ...internals import dtype as dt
from ...internals.desugaring import resolve_expression
from ...internals.graph import Operator
from ...internals.schema import ColumnSchema, _schema_from_columns
from ...internals.table import Table

__all__ = ["sort", "retrieve_prev_next_values"]


def sort(table: Table, key=None, instance=None) -> Table:
    """Returns a table (same universe) with ``prev``/``next`` Pointer cols."""
    if key is None:
        key = table[table.column_names()[0]]
    key_e = resolve_expression(key, table)
    instance_e = (
        resolve_expression(instance, table) if instance is not None else None
    )
    schema = _schema_from_columns(
        {
            "prev": ColumnSchema(name="prev", dtype=dt.Optional(dt.POINTER)),
            "next": ColumnSchema(name="next", dtype=dt.Optional(dt.POINTER)),
        }
    )
    op = Operator("sort", [table], params=dict(key=key_e, instance=instance_e))
    return Table._new(op, schema, table._universe)


def _retrieving_prev_next_value(tab: Table) -> Table:
    import pathway_tpu as pw

    return tab.with_columns(
        prev_value=pw.coalesce(
            pw.this.prev_value,
            tab.ix(pw.this.prev, optional=True, context=tab).prev_value,
        ),
        next_value=pw.coalesce(
            pw.this.next_value,
            tab.ix(pw.this.next, optional=True, context=tab).next_value,
        ),
    )


def retrieve_prev_next_values(ordered_table: Table, value=None) -> Table:
    """For each row of a prev/next-linked ordering, the id of the nearest
    row (backward via ``prev_value``, forward via ``next_value``) holding a
    non-None value — a pointer-chasing fixpoint, exactly the reference's
    ``pw.iterate`` formulation (sorting.py:195-230)."""
    import pathway_tpu as pw

    if value is None:
        value = ordered_table.value
    else:
        value = ordered_table[value.name if hasattr(value, "name") else value]

    tab = ordered_table.select(pw.this.prev, pw.this.next, value=value)
    tab = tab.with_columns(
        prev_value=pw.require(pw.this.id, pw.this.value),
        next_value=pw.require(pw.this.id, pw.this.value),
    )
    result = pw.iterate(_retrieving_prev_next_value, tab=tab)
    return result[["prev_value", "next_value"]]
