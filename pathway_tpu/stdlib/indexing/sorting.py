"""Sorting: prev/next pointers per instance.

reference: python/pathway/stdlib/indexing/sorting.py:230 ``sort`` backed by
src/engine/dataflow/operators/prev_next.rs ``add_prev_next_pointers``.
"""

from __future__ import annotations

from ...internals import dtype as dt
from ...internals.desugaring import resolve_expression
from ...internals.graph import Operator
from ...internals.schema import ColumnSchema, _schema_from_columns
from ...internals.table import Table

__all__ = ["sort", "retrieve_prev_next_values"]


def sort(table: Table, key=None, instance=None) -> Table:
    """Returns a table (same universe) with ``prev``/``next`` Pointer cols."""
    if key is None:
        key = table[table.column_names()[0]]
    key_e = resolve_expression(key, table)
    instance_e = (
        resolve_expression(instance, table) if instance is not None else None
    )
    schema = _schema_from_columns(
        {
            "prev": ColumnSchema(name="prev", dtype=dt.Optional(dt.POINTER)),
            "next": ColumnSchema(name="next", dtype=dt.Optional(dt.POINTER)),
        }
    )
    op = Operator("sort", [table], params=dict(key=key_e, instance=instance_e))
    return Table._new(op, schema, table._universe)


def retrieve_prev_next_values(ordered_table: Table, value=None) -> Table:
    """reference: sorting.py retrieve_prev_next_values — for each row, the
    nearest non-None value looking backward/forward along the ordering."""
    raise NotImplementedError(
        "retrieve_prev_next_values lands with the statistical interpolate pass"
    )
