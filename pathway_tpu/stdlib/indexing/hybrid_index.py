"""HybridIndex: reciprocal-rank fusion over multiple retrievers.

reference: python/pathway/stdlib/indexing/hybrid_index.py:14 (RRF with
k=60 at :27).
"""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from .data_index import DataIndex, _IndexJoinResult, _ID, _SCORE

__all__ = ["HybridIndex", "HybridIndexFactory"]


class HybridIndex:
    """Fuse rankings from several DataIndex retrievers with RRF."""

    def __init__(self, retrievers: list[DataIndex], k: float = 60.0):
        self.retrievers = retrievers
        self.k = k

    def _fuse(self, query_table, results: list, number_of_matches):
        # results: list of collapsed right-tables (same universe as queries)
        from ...internals.expression import smart_wrap

        data_cols = self.retrievers[0].data_table.column_names()
        rrf_k = self.k

        def fuse(nm, *packed):
            n = len(packed) // (len(data_cols) + 2)
            # packed groups: per retriever: (*data_cols, ids, scores)
            stride = len(data_cols) + 2
            scores: dict[Any, float] = {}
            payload: dict[Any, tuple] = {}
            for r in range(n):
                group = packed[r * stride : (r + 1) * stride]
                ids = group[len(data_cols)]
                for rank, key in enumerate(ids):
                    scores[key] = scores.get(key, 0.0) + 1.0 / (rrf_k + rank + 1)
                    payload[key] = tuple(group[c][rank] for c in range(len(data_cols)))
            ranked = sorted(scores.items(), key=lambda kv: -kv[1])[: int(nm)]
            return tuple(
                (key, score, payload[key]) for key, score in ranked
            )

        args = [smart_wrap(number_of_matches)]
        for right in results:
            for n in data_cols:
                args.append(right[n])
            args.append(right[_ID])
            args.append(right[_SCORE])
        fused = query_table._select_exprs(
            {"__fused__": ApplyExpression(fuse, dt.List(dt.ANY), *args)},
            universe=query_table._universe,
        )
        out_exprs = {}
        for i, n in enumerate(data_cols):
            out_exprs[n] = ApplyExpression(
                lambda f, _i=i: tuple(m[2][_i] for m in f), dt.List(dt.ANY), fused["__fused__"]
            )
        out_exprs[_ID] = ApplyExpression(
            lambda f: tuple(m[0] for m in f), dt.List(dt.POINTER), fused["__fused__"]
        )
        out_exprs[_SCORE] = ApplyExpression(
            lambda f: tuple(m[1] for m in f), dt.List(dt.FLOAT), fused["__fused__"]
        )
        right = fused._select_exprs(out_exprs, universe=fused._universe)
        return _IndexJoinResult(query_table, right)

    def query_as_of_now(
        self, query_column, *, number_of_matches=3, collapse_rows=True, metadata_filter=None
    ):
        rights = [
            r.query_as_of_now(
                query_column,
                number_of_matches=number_of_matches * 2,
                collapse_rows=True,
                metadata_filter=metadata_filter,
            )._right
            for r in self.retrievers
        ]
        return self._fuse(query_column.table, rights, number_of_matches)

    def query(
        self, query_column, *, number_of_matches=3, collapse_rows=True, metadata_filter=None
    ):
        rights = [
            r.query(
                query_column,
                number_of_matches=number_of_matches * 2,
                collapse_rows=True,
                metadata_filter=metadata_filter,
            )._right
            for r in self.retrievers
        ]
        return self._fuse(query_column.table, rights, number_of_matches)


class HybridIndexFactory:
    """reference: indexing/__init__.py HybridIndexFactory — builds a
    HybridIndex from retriever factories at DocumentStore build time."""

    def __init__(self, retriever_factories: list, k: float = 60.0):
        self.retriever_factories = retriever_factories
        self.k = k
