"""Indexing stdlib: DataIndex facade, retriever factories, sorting.

reference: python/pathway/stdlib/indexing/ (data_index.py, nearest_neighbors.py,
bm25.py, hybrid_index.py, sorting.py).
"""

from .data_index import (
    DataIndex,
    default_vector_document_index,
    default_usearch_knn_document_index,
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_full_text_document_index,
)
from .retrievers import (
    InnerIndexFactory,
    BruteForceKnnFactory,
    UsearchKnnFactory,
    LshKnnFactory,
    TantivyBM25Factory,
    BM25Factory,
    USearchMetricKind,
    BruteForceKnnMetricKind,
)
from .hybrid_index import HybridIndex, HybridIndexFactory
from .sorting import sort

__all__ = [
    "DataIndex",
    "InnerIndexFactory",
    "BruteForceKnnFactory",
    "UsearchKnnFactory",
    "LshKnnFactory",
    "TantivyBM25Factory",
    "BM25Factory",
    "USearchMetricKind",
    "BruteForceKnnMetricKind",
    "HybridIndex",
    "HybridIndexFactory",
    "sort",
    "default_vector_document_index",
    "default_usearch_knn_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_full_text_document_index",
]
