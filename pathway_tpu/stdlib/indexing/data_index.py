"""DataIndex — retrieval facade over an inner index.

reference: python/pathway/stdlib/indexing/data_index.py:278 (``DataIndex``,
``query``:349 / ``query_as_of_now``:412, response repacking
``_extract_data_collapsed_rows``:91) and colnames.py (``_pw_index_reply``,
``_pw_index_reply_score``).
"""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.desugaring import expand_select_args
from ...internals.expression import ColumnExpression, ColumnReference, smart_wrap
from ...internals.graph import Operator
from ...internals.schema import ColumnSchema, _schema_from_columns
from ...internals.table import Table
from .retrievers import InnerIndexFactory

__all__ = [
    "DataIndex",
    "default_vector_document_index",
    "default_usearch_knn_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_full_text_document_index",
    "_external_index_as_of_now",
]

_INDEX_REPLY = "_pw_index_reply"
_SCORE = "_pw_index_reply_score"
_ID = "_pw_index_reply_id"


def _build_index_operator(
    data_table: Table,
    query_table: Table,
    factory: InnerIndexFactory,
    index_data: ColumnExpression,
    query_data: ColumnExpression,
    *,
    index_metadata: ColumnExpression | None = None,
    k: Any = 3,
    query_filter: ColumnExpression | None = None,
    mode: str = "asof_now",
) -> Table:
    """Creates the raw reply table: query columns + ``_pw_index_reply`` of
    ``((doc_id, score, payload), ...)`` tuples."""
    payload_exprs = [data_table[n] for n in data_table.column_names()]
    columns = {
        n: ColumnSchema(name=n, dtype=c.dtype)
        for n, c in query_table.schema.columns().items()
    }
    columns[_INDEX_REPLY] = ColumnSchema(name=_INDEX_REPLY, dtype=dt.List(dt.ANY))
    schema = _schema_from_columns(columns)
    op = Operator(
        "external_index",
        [data_table, query_table],
        params=dict(
            factory=factory,
            index_data=index_data,
            index_metadata=index_metadata,
            query_data=query_data,
            k=k,
            query_filter=query_filter,
            payload_exprs=payload_exprs,
            mode=mode,
        ),
    )
    return Table._new(op, schema, query_table._universe)


def _external_index_as_of_now(
    data_table: Table,
    index_factory,
    query_table: Table,
    *,
    index_column,
    query_column,
    query_responses_limit_column=None,
    index_filter_data_column=None,
    query_filter_column=None,
) -> Table:
    """Low-level parity API (reference: Table._external_index_as_of_now /
    graph.rs:894 ``use_external_index_as_of_now``)."""
    return _build_index_operator(
        data_table,
        query_table,
        index_factory,
        index_column,
        query_column,
        index_metadata=index_filter_data_column,
        k=query_responses_limit_column if query_responses_limit_column is not None else 3,
        query_filter=query_filter_column,
        mode="asof_now",
    )


class _IndexJoinResult:
    """Emulates the reference's JoinResult returned by DataIndex.query*:
    ``pw.left`` = query table, ``pw.right`` = repacked results (same
    universe, so the select lowers to a key-aligned zip)."""

    def __init__(self, left: Table, right: Table):
        self._left = left
        self._right = right

    def select(self, *args: Any, **kwargs: Any) -> Table:
        exprs = expand_select_args(
            args, kwargs, self._left, self._left, self._right
        )
        return self._left._select_exprs(exprs, universe=self._left._universe)

    def filter(self, condition):
        flat = self._flat()
        from ...internals.desugaring import resolve_expression

        return flat.filter(resolve_expression(condition, flat, flat, flat))

    def _flat(self) -> Table:
        exprs: dict[str, Any] = {}
        for n in self._right.column_names():
            exprs[n] = self._right[n]
        for n in self._left.column_names():
            exprs[n] = self._left[n]
        return self.select(**exprs)


class DataIndex:
    """reference: data_index.py:278"""

    def __init__(
        self,
        data_table: Table,
        inner_index: "InnerIndexFactory",
        *,
        data_column: ColumnReference | None = None,
        metadata_column: ColumnReference | None = None,
        embedder=None,
    ):
        self.data_table = data_table
        self.factory = inner_index
        self.data_column = data_column
        self.metadata_column = metadata_column
        self.embedder = embedder

    def _query_impl(
        self,
        query_column: ColumnReference,
        number_of_matches,
        collapse_rows: bool,
        metadata_filter,
        mode: str,
    ):
        query_table = query_column.table
        index_data = self.data_column if self.data_column is not None else None
        if index_data is None:
            raise ValueError("DataIndex requires data_column")
        if self.embedder is not None:
            index_data = self.embedder(index_data)
            query_column = self.embedder(query_column)
        raw = _build_index_operator(
            self.data_table,
            query_table,
            self.factory,
            index_data,
            query_column,
            index_metadata=self.metadata_column,
            k=number_of_matches,
            query_filter=metadata_filter,
            mode=mode,
        )
        right = self._repack(raw, collapse_rows)
        return _IndexJoinResult(query_table, right)

    def _repack(self, raw: Table, collapse_rows: bool) -> Table:
        """reference: data_index.py:46,91 ``_extract_data_*``."""
        from ...internals.expression import ApplyExpression

        data_cols = self.data_table.column_names()
        exprs: dict[str, ColumnExpression] = {}

        def unpack(idx: int, dtype):
            def fn(reply):
                return tuple(m[2][idx] for m in reply)

            return ApplyExpression(fn, dt.List(dtype), raw[_INDEX_REPLY])

        for i, n in enumerate(data_cols):
            exprs[n] = unpack(i, self.data_table.schema[n].dtype)
        exprs[_ID] = ApplyExpression(
            lambda reply: tuple(m[0] for m in reply), dt.List(dt.POINTER), raw[_INDEX_REPLY]
        )
        exprs[_SCORE] = ApplyExpression(
            lambda reply: tuple(m[1] for m in reply), dt.List(dt.FLOAT), raw[_INDEX_REPLY]
        )
        collapsed = raw._select_exprs(exprs, universe=raw._universe)
        if collapse_rows:
            return collapsed
        # flat mode: one row per match
        packed = collapsed._select_exprs(
            {
                "__rows__": ApplyExpression(
                    lambda *cols: tuple(zip(*cols)) if cols and cols[0] else (),
                    dt.List(dt.ANY),
                    *[collapsed[n] for n in (*data_cols, _ID, _SCORE)],
                )
            },
            universe=collapsed._universe,
        )
        flat = packed.flatten(packed["__rows__"])
        out_exprs = {}
        for i, n in enumerate((*data_cols, _ID, _SCORE)):
            out_exprs[n] = flat["__rows__"].get(i)
        return flat._select_exprs(out_exprs, universe=flat._universe)

    def query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches=3,
        collapse_rows: bool = True,
        metadata_filter=None,
    ):
        """Maintained retrieval: answers update when the index changes
        (reference: data_index.py:349)."""
        return self._query_impl(
            query_column, number_of_matches, collapse_rows, metadata_filter, "live"
        )

    def query_as_of_now(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches=3,
        collapse_rows: bool = True,
        metadata_filter=None,
    ):
        """Serve-time retrieval: answer with current state, never revisit
        (reference: data_index.py:412)."""
        return self._query_impl(
            query_column, number_of_matches, collapse_rows, metadata_filter, "asof_now"
        )


# ---------------------------------------------------------------------------
# default document index constructors
# (reference: stdlib/indexing/__init__.py default_* helpers)
# ---------------------------------------------------------------------------


def default_vector_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    from .retrievers import BruteForceKnnFactory

    factory = BruteForceKnnFactory(dimensions=dimensions, embedder=embedder)
    return DataIndex(
        data_table,
        factory,
        data_column=data_column,
        metadata_column=metadata_column,
        embedder=embedder,
    )


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int | None = None,
    embedder=None,
    metadata_column: ColumnReference | None = None,
    **kwargs,
) -> DataIndex:
    from .retrievers import UsearchKnnFactory

    factory = UsearchKnnFactory(dimensions=dimensions, embedder=embedder, **kwargs)
    return DataIndex(
        data_table,
        factory,
        data_column=data_column,
        metadata_column=metadata_column,
        embedder=embedder,
    )


def default_brute_force_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int | None = None,
    embedder=None,
    metadata_column: ColumnReference | None = None,
    **kwargs,
) -> DataIndex:
    from .retrievers import BruteForceKnnFactory

    factory = BruteForceKnnFactory(dimensions=dimensions, embedder=embedder, **kwargs)
    return DataIndex(
        data_table,
        factory,
        data_column=data_column,
        metadata_column=metadata_column,
        embedder=embedder,
    )


def default_lsh_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int | None = None,
    embedder=None,
    metadata_column: ColumnReference | None = None,
    **kwargs,
) -> DataIndex:
    from .retrievers import LshKnnFactory

    factory = LshKnnFactory(dimensions=dimensions, embedder=embedder, **kwargs)
    return DataIndex(
        data_table,
        factory,
        data_column=data_column,
        metadata_column=metadata_column,
        embedder=embedder,
    )


def default_full_text_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    metadata_column: ColumnReference | None = None,
    **kwargs,
) -> DataIndex:
    from .retrievers import TantivyBM25Factory

    factory = TantivyBM25Factory(**kwargs)
    return DataIndex(
        data_table,
        factory,
        data_column=data_column,
        metadata_column=metadata_column,
    )
