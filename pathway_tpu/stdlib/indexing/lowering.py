"""Runtime node + lowering for the external-index operator and sorting.

reference: src/engine/dataflow/operators/external_index.rs
(``use_external_index_as_of_now_core``:81 — updates applied before queries
per time batch :129-160; index stream broadcast :95) and graph.rs:894.

TPU re-design: instead of replicating the index to every worker via
broadcast, the index lives once in device HBM (see ops/knn.py); the node is
marked ``late`` so the engine's per-timestamp barrier guarantees globally
that all index updates for a timestamp land before any query of that
timestamp is answered — the invariant the reference gets from
``batch_by_time`` + local operator ordering.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any

import numpy as np

from ...internals.engine import Entry, Node, consolidate
from ...internals.evaluator import compile_expression
from ...internals.value import ERROR
from ...internals.runtime import GraphRunner, _TableLayout
from ...internals.graph import Operator

__all__ = [
    "ExternalIndexNode",
    "lower_external_index",
    "lower_sort",
    "live_index_node",
]


#: live ExternalIndexNodes keyed by the identity of the factory that built
#: their inner index — the serving scheduler's retrieve plane
#: (xpacks/llm/_scheduler.py) uses this to answer REST queries against the
#: engine-maintained index without riding engine micro-batch cadence.
#: Weak values: a finished engine's nodes drop out with it.
_LIVE_INDEX_NODES: "weakref.WeakValueDictionary[int, Node]" = (
    weakref.WeakValueDictionary()
)


def live_index_node(factory: Any) -> "ExternalIndexNode | None":
    """The running index node lowered from ``factory``, if any."""
    return _LIVE_INDEX_NODES.get(id(factory))


class ExternalIndexNode(Node):
    """Port 0 = index updates (docs), port 1 = queries."""

    late = True

    def __init__(
        self,
        index,
        doc_data_fn,
        doc_meta_fn,
        query_data_fn,
        query_k_fn,
        query_filter_fn,
        doc_payload_fn,
        mode: str = "asof_now",
        name: str = "external_index",
    ):
        super().__init__(n_inputs=2, name=name)
        self.index = index
        self.doc_data_fn = doc_data_fn
        self.doc_meta_fn = doc_meta_fn
        self.query_data_fn = query_data_fn
        self.query_k_fn = query_k_fn
        self.query_filter_fn = query_filter_fn
        self.doc_payload_fn = doc_payload_fn
        self.mode = mode
        # doc payload snapshot for reply enrichment (as-of-answer-time)
        self.doc_payload: dict[Any, tuple] = {}
        # live-mode query state: qkey -> (row, last_emitted_row)
        self.live_queries: dict[Any, list] = {}
        # asof_now: answered replies kept so a query retraction (REST
        # delete_completed_queries) retracts its reply and frees the state —
        # the reference's ForgetImmediately cleanup on asof-now queries.
        # For keep-queries streams this grows with total queries, the same
        # asymptotics as the downstream reply table those queries requested.
        self.answered: dict[Any, tuple] = {}
        #: chunked operator-snapshot plane (streaming driver attaches it
        #: under OPERATOR_PERSISTING).  Deltas carry the ALREADY-COMPUTED
        #: doc vectors — restore streams them back into HBM without one
        #: encoder call (EdgeRAG: persisting embeddings beats online
        #: regeneration).  ``_snap_pending`` holds this step's net doc
        #: changes: key -> (data, meta, payload) for upserts, None for
        #: deletes; cleared only once the delta chunk is durably written.
        self.persistent_id: str | None = None
        self._op_snapshot = None
        self._snap_pending: dict[Any, tuple | None] = {}
        #: warm-restart health gate: "restoring" while the driver streams
        #: snapshot chunks back into the index — the serving plane
        #: (RetrievePlane) answers from the lexical mirror until cleared
        self._restore_state: str | None = None
        self.restored_rows = 0
        #: serving-cache freshness watermark: a monotone per-index commit
        #: sequence advanced EXACTLY when the corpus visible to queries
        #: changes (flush-applied upserts/deletes, snapshot restore).
        #: Tier migrations (pathway_tpu/tiering) deliberately never pass
        #: through here — scores are tier-independent by construction, so
        #: a migration storm must not flush the result cache.
        self.commit_seq = 0
        #: bounded (seq, wall-time) history backing stale-while-revalidate;
        #: the lock covers bump (engine flush thread) vs read (serving
        #: scheduler thread) — iterating a deque mid-append raises
        self._commit_times: deque[tuple[int, float]] = deque(maxlen=256)
        self._commit_times_lock = threading.Lock()

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        index_changed = False
        # 1. apply index updates (updates-before-queries).  Within one
        # timestamp each key's FINAL entry decides its state (add is
        # upsert, remove of an absent key is a no-op), so adds collapse
        # into one batched call — a single staged device scatter per
        # flush instead of one per document
        last: dict[Any, tuple | None] = {}
        payloads: dict[Any, tuple] = {}
        for key, row, diff in self.take(0):
            index_changed = True
            ctx = (key, row)
            data = self.doc_data_fn(ctx)
            meta = self.doc_meta_fn(ctx)
            if data is ERROR or meta is ERROR:
                # a document whose embedding/metadata errored (failed UDF
                # under terminate_on_error=False) must not poison the
                # index: skip it both ways (its retraction computes the
                # same ERROR and is skipped symmetrically) and log once
                if diff > 0:
                    from ...internals.errors import register_error

                    register_error(
                        "document with ERROR embedding/metadata excluded "
                        "from index",
                        kind="index",
                        operator=self.name,
                    )
                continue
            if diff > 0:
                last[key] = (data, meta)
                payloads[key] = self.doc_payload_fn(ctx)
            else:
                last[key] = None
        add_keys = [k for k, v in last.items() if v is not None]
        # the corpus visible to queries changes only when something real
        # applies: an upsert, or a remove of a key actually present.
        # ERROR-skipped docs and removes of absent keys must NOT bump the
        # watermark — a stream of failing UDF docs would otherwise
        # invalidate the whole result cache every flush while serving the
        # exact same corpus (computed BEFORE applying: the apply pops
        # removed keys from doc_payload)
        corpus_changed = bool(add_keys) or any(
            v is None and k in self.doc_payload for k, v in last.items()
        )
        try:
            self._apply_index_updates(last, payloads, add_keys)
        except Exception as exc:  # noqa: BLE001 — classify before routing
            if not self._contain_device_fault(exc):
                raise
            try:
                # one retry against the rebuilt arrays (upserts/removes
                # are idempotent, so a partially-applied first attempt
                # re-applies cleanly)
                self._apply_index_updates(last, payloads, add_keys)
            except Exception as exc2:  # noqa: BLE001
                from ...ops.device_faults import classify_device_error

                if classify_device_error(exc2) is None:
                    raise
                # still failing on the device plane: drop the batch from
                # the DEVICE index but keep the run alive — the snapshot
                # below still records the vectors, so the docs are
                # durable and re-enter on the next rebuild/restart
                from ...internals.errors import register_error

                register_error(
                    f"index update batch dropped after device-fault retry: "
                    f"{type(exc2).__name__}: {exc2}",
                    kind="index",
                    operator=self.name,
                )
        if self._op_snapshot is not None and self.persistent_id:
            snap_vals = self._snap_values(last)
            for key, action in last.items():
                if action is None:
                    self._snap_pending[key] = None
                else:
                    self._snap_pending[key] = (
                        snap_vals[key],
                        action[1],
                        payloads[key],
                    )
        if index_changed:
            # freshness watermark: the updates of engine timestamp `time`
            # are queryable from here on (updates-before-queries), closing
            # the ingest->queryable loop the driver opened when it stamped
            # this timestamp (pathway_index_freshness_seconds{index=...})
            from ...internals.monitoring import get_freshness

            get_freshness().note_indexed(
                self.name, time, scope=getattr(self, "_freshness_scope", 0)
            )
        if corpus_changed:
            # serving result cache: entries cached at an older commit_seq
            # are no longer exact from this point (xpacks/llm/_query_cache)
            self.bump_commit_seq()
        # 2. answer new queries
        new_queries: list[tuple[Any, tuple]] = []
        for key, row, diff in self.take(1):
            if self.mode == "asof_now":
                if diff > 0:
                    new_queries.append((key, row))
                else:
                    answered = self.answered.pop(key, None)
                    if answered is not None:
                        out.append((key, answered, -1))
            else:
                slot = self.live_queries.get(key)
                if diff > 0:
                    self.live_queries[key] = [row, None]
                    new_queries.append((key, row))
                elif slot is not None:
                    if slot[1] is not None:
                        out.append((key, slot[1], -1))
                    del self.live_queries[key]
        if new_queries:
            replies = self._answer([row for _, row in new_queries])
            for (key, row), reply in zip(new_queries, replies):
                out_row = tuple(row) + (reply,)
                out.append((key, out_row, 1))
                if self.mode == "live":
                    self.live_queries[key][1] = out_row
                else:
                    self.answered[key] = out_row
        # 3. live mode: refresh previously-answered queries on index change
        if self.mode == "live" and index_changed and self.live_queries:
            stale = [
                (key, slot)
                for key, slot in self.live_queries.items()
                if slot[1] is not None and not any(key == k for k, _ in new_queries)
            ]
            if stale:
                from ...internals.engine import freeze_row

                replies = self._answer([slot[0] for _, slot in stale])
                for (key, slot), reply in zip(stale, replies):
                    new_row = tuple(slot[0]) + (reply,)
                    if freeze_row(new_row) != freeze_row(slot[1]):
                        out.append((key, slot[1], -1))
                        out.append((key, new_row, 1))
                        slot[1] = new_row
        return consolidate(out)

    # -- serving-cache freshness watermark -------------------------------
    def bump_commit_seq(self) -> None:
        """Advance the per-index commit sequence (see the attribute doc:
        corpus-changing flushes and snapshot restores only — NEVER tier
        migrations)."""
        with self._commit_times_lock:
            self.commit_seq += 1
            self._commit_times.append((self.commit_seq, time.time()))

    def stale_age(self, watermark: int) -> float | None:
        """Seconds since the index FIRST advanced past ``watermark`` —
        i.e. how stale a result cached at that watermark is now.  None
        when unknown (no history, or the advance aged out of the bounded
        ring): callers must treat unknown as too stale."""
        with self._commit_times_lock:
            times = tuple(self._commit_times)
        if not times:
            return None
        if times[0][0] > watermark + 1:
            return None  # the true first-advance time was evicted
        for seq, t in times:
            if seq > watermark:
                return max(0.0, time.time() - t)
        return None

    # -- index-update application + device-fault containment ------------
    def _apply_index_updates(self, last, payloads, add_keys) -> None:
        for key, action in last.items():
            if action is None:
                self.index.remove(key)
                self.doc_payload.pop(key, None)
        if add_keys:
            if hasattr(self.index, "add_batch"):
                self.index.add_batch(
                    add_keys,
                    [last[k][0] for k in add_keys],
                    [last[k][1] for k in add_keys],
                )
            else:  # duck-typed custom index without the batched protocol
                for key in add_keys:
                    self.index.add(key, last[key][0], last[key][1])
            for key in add_keys:
                self.doc_payload[key] = payloads[key]
            from ...internals.flight_recorder import record_ingest_docs

            record_ingest_docs(len(add_keys))

    def _contain_device_fault(self, exc: BaseException) -> bool:
        """Containment for device errors raised by index mutation/search:
        transient ones are logged (the caller retries / degrades), fatal
        ones additionally rebuild the device arrays from the host mirror
        or the snapshot.  Returns False for non-device exceptions — plain
        bugs keep their normal routing."""
        from ...internals.errors import register_error
        from ...ops.device_faults import FATAL, classify_device_error

        kind = classify_device_error(exc)
        if kind is None:
            return False
        register_error(
            f"device fault ({kind}) in index {self.name!r}: "
            f"{type(exc).__name__}: {exc}",
            kind="index",
            operator=self.name,
        )
        if kind == FATAL:
            # a rebuild on a still-dead device can itself raise — that
            # must stay inside the containment boundary (the caller's
            # retry will fail and take the degraded/drop path), never
            # escape to kill the engine thread
            try:
                self.rebuild_device_state()
            except Exception as rexc:  # noqa: BLE001 — contained
                register_error(
                    f"index rebuild after device fault failed: "
                    f"{type(rexc).__name__}: {rexc}",
                    kind="index",
                    operator=self.name,
                )
        return True

    def rebuild_device_state(self) -> bool:
        """Recreate the inner index's device arrays after a fatal fault —
        host mirror first, snapshot vectors as the fallback (the
        ``_place()`` rebuild hook re-pins sharded matrices to the mesh).
        Returns True when a rebuild happened."""
        import time as _time

        from ...internals.flight_recorder import record_span

        inner = getattr(self.index, "index", None)
        if inner is None or not hasattr(inner, "rebuild_device_arrays"):
            return False
        wall = _time.time()
        t0 = _time.monotonic()
        ok = inner.rebuild_device_arrays()
        source = "host_mirror"
        if not ok:
            vectors = self._snapshot_vectors()
            if vectors:
                ok = inner.rebuild_device_arrays(vectors)
                source = "snapshot"
        record_span(
            f"rebuild:{self.name}", "restore", wall,
            (_time.monotonic() - t0) * 1000.0,
            attrs={"ok": ok, "source": source, "index": self.name},
        )
        return ok

    def _snapshot_vectors(self) -> dict | None:
        """Doc vectors replayed from the snapshot plane (fatal-rebuild
        fallback when even a D2H copy of the matrix fails).  Quantized
        indexes snapshot ``(codes, scale)`` records — those replay
        straight back as codes (``DeviceKnnIndex.upsert_coded``)."""
        from ...ops.quantized_scoring import is_quant_record

        if self._op_snapshot is None or not self.persistent_id:
            return None
        state = self._op_snapshot.load(self.persistent_id) or {}
        out = {
            key: rec[0]
            for key, rec in state.items()
            if isinstance(rec[0], np.ndarray) or is_quant_record(rec[0])
        }
        return out or None

    def _inner_device_index(self):
        """The inner ``DeviceKnnIndex`` behind this node's index, if
        any (duck-typed custom indexes return None)."""
        return getattr(self.index, "index", None)

    @staticmethod
    def _snap_value(data):
        """Snapshot representation of one doc's index data: array-likes
        (embeddings) are pinned as float32 numpy — a device array must
        not ride a pickle — while text (BM25) passes through."""
        if isinstance(data, np.ndarray):
            return np.asarray(data, dtype=np.float32)
        if hasattr(data, "__array__") or isinstance(data, (list, tuple)):
            return np.asarray(data, dtype=np.float32)
        return data

    def _snap_values(self, last: dict) -> dict:
        """Snapshot values for one flush's net doc changes.

        Unquantized indexes pin raw f32 vectors (``_snap_value``).  A
        QUANTIZED inner index instead exports the EXACT resident
        codes+scale per key in ONE batched gather
        (``DeviceKnnIndex.export_records``): the snapshot then holds
        precisely the bytes the index serves — restore is bit-identical
        with zero re-embeds and zero re-quantization, and the snapshot
        itself shrinks ~4x with the matrix.  If the export fails (the
        device plane may be faulting — durability must not die with it),
        the host-side quantizer produces an equivalent record from the
        raw vector."""
        inner = self._inner_device_index()
        quantized = inner is not None and getattr(inner, "quantized", False)
        out: dict = {}
        vec_keys: list = []
        for key, action in last.items():
            if action is None:
                continue
            data = action[0]
            if quantized and (
                isinstance(data, np.ndarray)
                or hasattr(data, "__array__")
                or isinstance(data, (list, tuple))
            ):
                vec_keys.append(key)
            else:
                out[key] = self._snap_value(data)
        if vec_keys:
            try:
                records = inner.export_records(vec_keys)
            except Exception:  # noqa: BLE001 — device fault: host fallback
                records = {}
            if len(records) < len(vec_keys):
                from ...ops.quantized_scoring import quantize_record_np

                for key in vec_keys:
                    if key not in records:
                        records[key] = quantize_record_np(
                            np.asarray(last[key][0], dtype=np.float32),
                            normalize=inner.metric == "cos",
                        )
            out.update(records)
        return out

    # -- operator snapshots (reference: operator_snapshot.rs) -----------
    _SNAPSHOT_WRITE_ATTEMPTS = 3

    #: reserved snapshot-state key for the tiered index's placement blob
    #: (== pathway_tpu.tiering.TIER_PLACEMENT_KEY — duplicated literally
    #: so reading a snapshot never imports the jax-backed tiering module)
    _TIER_PLACEMENT_KEY = "__pw_tier_placement__"

    def _maybe_stage_placement(self) -> None:
        """Tiered inner index: when the tier assignment changed since the
        last snapshot (online promotions/demotions, hot fills), stage the
        placement blob as a reserved state row so the NEXT delta carries
        it — a warm restart then rebuilds the exact same placement."""
        fn = getattr(self.index, "placement_blob_if_dirty", None)
        if fn is None:
            return
        blob = fn()
        if blob is not None:
            self._snap_pending[self._TIER_PLACEMENT_KEY] = (blob, None, None)

    def placement_flush_pending(self) -> bool:
        """A tiered inner index changed its placement and the change is
        not yet staged for the snapshot plane.  The streaming driver
        checks this while sources are idle: migrations are driven by
        QUERY traffic, so without an idle step a placement mutated
        during an ingest lull would never be persisted and a kill in
        that window would restore the older placement."""
        if self._op_snapshot is None or not self.persistent_id:
            return False
        return bool(getattr(self.index, "placement_dirty", False))

    def _snap_header(self) -> dict | None:
        """Delta-chunk header: the index's routing spec (LSH projector /
        partition router seeds), persisted so a restored process routes
        queries to the same partitions."""
        fn = getattr(self.index, "snapshot_header", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — a header must never block a delta
            return None

    def apply_snapshot_header(self, header: dict | None) -> None:
        """Re-apply a restored delta-chunk header (routing specs) to the
        inner index — called by the streaming driver BEFORE the restored
        rows stream back in."""
        if not header:
            return
        fn = getattr(self.index, "apply_snapshot_header", None)
        if fn is not None:
            fn(header)

    def end_of_step(self, time: int) -> None:
        if self._op_snapshot is not None and self.persistent_id:
            self._maybe_stage_placement()
        if not (
            self._snap_pending
            and self._op_snapshot is not None
            and self.persistent_id
        ):
            return
        from ...testing import faults

        upserts = {k: v for k, v in self._snap_pending.items() if v is not None}
        deletes = [k for k, v in self._snap_pending.items() if v is None]
        last_exc: BaseException | None = None
        for _attempt in range(self._SNAPSHOT_WRITE_ATTEMPTS):
            try:
                if faults.enabled:
                    faults.perturb("index.snapshot")
                self._op_snapshot.save_delta(
                    self.persistent_id,
                    time,
                    upserts,
                    deletes,
                    live_entries=len(self.doc_payload),
                    header=self._snap_header(),
                )
                self._snap_pending.clear()
                return
            except Exception as exc:  # noqa: BLE001 — bounded retry
                last_exc = exc
        # a snapshot that cannot be written is a durability failure: the
        # commit record would otherwise advance offsets past rows whose
        # state never landed — fail LOUDLY rather than break exactly-once
        raise RuntimeError(
            f"index {self.name!r} could not write its snapshot delta after "
            f"{self._SNAPSHOT_WRITE_ATTEMPTS} attempts"
        ) from last_exc

    def restore_snapshot(self, state: dict) -> None:
        """Warm restart: stream the snapshotted (vector, metadata,
        payload) rows back into the index through ONE bulk ``add_batch``
        (a single staged device scatter) — zero encoder calls.

        A tiered index additionally restores its tier placement: the
        reserved placement row (hot key set + router spec) is popped
        from the state and pinned BEFORE the rows flow in, so every
        restored key lands straight in the tier it held when the
        snapshot was cut — placement is bit-for-bit, not re-derived
        from restore iteration order."""
        placement = state.pop(self._TIER_PLACEMENT_KEY, None)
        if placement is not None and hasattr(self.index, "restore_placement"):
            self.index.restore_placement(placement[0])
        keys, datas, metas = [], [], []
        for key, (data, meta, payload) in state.items():
            keys.append(key)
            datas.append(data)
            metas.append(meta)
            self.doc_payload[key] = payload
        if keys:
            if hasattr(self.index, "add_batch"):
                self.index.add_batch(keys, datas, metas)
            else:
                for key, data, meta in zip(keys, datas, metas):
                    self.index.add(key, data, meta)
        if placement is not None and hasattr(self.index, "finish_restore"):
            self.index.finish_restore()
        self.restored_rows = len(keys)
        # restore invalidates any serving-cache entry from a previous
        # engine life in this process (xpacks/llm/_query_cache)
        self.bump_commit_seq()

    def _answer(self, rows: list[tuple]) -> list[tuple]:
        queries = []
        for row in rows:
            ctx = (None, row)
            q = self.query_data_fn(ctx)
            k = self.query_k_fn(ctx)
            flt = self.query_filter_fn(ctx)
            if q is ERROR or k is ERROR or flt is ERROR:
                # an errored query gets an empty reply instead of
                # crashing the whole batch's device search
                from ...internals.errors import register_error

                register_error(
                    "query with ERROR input answered empty",
                    kind="index",
                    operator=self.name,
                )
                queries.append(None)
            else:
                queries.append((q, int(k), flt))
        live = [q for q in queries if q is not None]
        try:
            raw = self.index.search(live)
        except Exception as exc:  # noqa: BLE001 — classify before routing
            if not self._contain_device_fault(exc):
                raise
            try:
                # one retry against rebuilt/recovered arrays
                raw = self.index.search(live)
            except Exception as exc2:  # noqa: BLE001
                from ...ops.device_faults import classify_device_error

                if classify_device_error(exc2) is None:
                    raise
                from ...internals.errors import register_error

                register_error(
                    "query batch answered empty after device fault: "
                    f"{type(exc2).__name__}: {exc2}",
                    kind="index",
                    operator=self.name,
                )
                raw = [[] for _ in live]
        raw_iter = iter(raw)
        replies = []
        for q in queries:
            matches = () if q is None else next(raw_iter)
            replies.append(
                tuple(
                    (key, float(score), self.doc_payload.get(key))
                    for key, score in matches
                )
            )
        return replies


def lower_external_index(runner: GraphRunner, op: Operator) -> None:
    docs_t, query_t = op.inputs
    dlayout = _TableLayout([docs_t])
    qlayout = _TableLayout([query_t])
    dresolve = dlayout.resolver()
    qresolve = qlayout.resolver()

    p = op.params
    index = p["factory"].build_inner_index()
    doc_data_fn = compile_expression(p["index_data"], dresolve)
    meta = p.get("index_metadata")
    doc_meta_fn = (
        compile_expression(meta, dresolve) if meta is not None else (lambda ctx: None)
    )
    payload_fns = [
        compile_expression(e, dresolve) for e in p.get("payload_exprs", [])
    ]

    def doc_payload_fn(ctx):
        return tuple(f(ctx) for f in payload_fns)

    query_data_fn = compile_expression(p["query_data"], qresolve)
    k = p.get("k", 3)
    if hasattr(k, "_dtype"):
        query_k_fn = compile_expression(k, qresolve)
    else:
        query_k_fn = lambda ctx, _k=k: _k
    flt = p.get("query_filter")
    query_filter_fn = (
        compile_expression(flt, qresolve) if flt is not None else (lambda ctx: None)
    )

    node = ExternalIndexNode(
        index,
        doc_data_fn,
        doc_meta_fn,
        query_data_fn,
        query_k_fn,
        query_filter_fn,
        doc_payload_fn,
        mode=p.get("mode", "asof_now"),
        name=f"index#{op.id}",
    )
    runner.engine.add(node)
    runner._connect_inputs(op, node)
    runner._register(op, node)
    # freshness watermarks are matched per engine (timestamps restart at 1
    # in every run — see FreshnessTracker's scope note)
    node._freshness_scope = id(runner.engine)
    # snapshot keyspace: op ids are deterministic for a given program
    # (graph build order), the same stability contract as the default
    # connector persistent ids — the streaming driver attaches the
    # snapshot plane under OPERATOR_PERSISTING
    node.persistent_id = f"index#{op.id}"
    # pin the factory on the node: the registry key is id(factory), so the
    # factory must stay alive exactly as long as the entry does — otherwise
    # a recycled id could alias a NEW factory to this stale node
    node._factory = p["factory"]
    _LIVE_INDEX_NODES[id(p["factory"])] = node


# ---------------------------------------------------------------------------
# sorting (reference: src/engine/dataflow/operators/prev_next.rs:770
# add_prev_next_pointers; stdlib/indexing/sorting.py)
# ---------------------------------------------------------------------------


class SortNode(Node):
    """Maintains per-instance ordering, emits (prev, next) pointer columns."""

    def __init__(self, key_fn, instance_fn, name: str = "sort"):
        super().__init__(n_inputs=1, name=name)
        self.key_fn = key_fn
        self.instance_fn = instance_fn
        from collections import defaultdict

        self.rows: dict = {}
        self.instances: dict = defaultdict(dict)  # inst -> {key: sort_val}
        self.last_out: dict = {}

    def flush(self, time: int) -> list[Entry]:
        from ...internals.engine import freeze_value

        dirty = set()
        for key, row, diff in self.take(0):
            ctx = (key, row)
            inst = freeze_value(self.instance_fn(ctx))
            dirty.add(inst)
            if diff > 0:
                self.instances[inst][key] = self.key_fn(ctx)
                self.rows[key] = inst
            else:
                self.instances[inst].pop(key, None)
                self.rows.pop(key, None)
        out: list[Entry] = []
        for inst in dirty:
            ordered = sorted(self.instances[inst].items(), key=lambda kv: (kv[1], kv[0]))
            n = len(ordered)
            for i, (key, _val) in enumerate(ordered):
                prev_key = ordered[i - 1][0] if i > 0 else None
                next_key = ordered[i + 1][0] if i < n - 1 else None
                new_row = (prev_key, next_key)
                old = self.last_out.get(key)
                if old != new_row:
                    if old is not None:
                        out.append((key, old, -1))
                    out.append((key, new_row, 1))
                    self.last_out[key] = new_row
        # rows fully removed
        gone = [k for k in self.last_out if k not in self.rows]
        for key in gone:
            out.append((key, self.last_out.pop(key), -1))
        return consolidate(out)


def lower_sort(runner: GraphRunner, op: Operator) -> None:
    table = op.inputs[0]
    layout = _TableLayout([table])
    resolve = layout.resolver()
    key_fn = compile_expression(op.params["key"], resolve)
    instance = op.params.get("instance")
    inst_fn = (
        compile_expression(instance, resolve)
        if instance is not None
        else (lambda ctx: 0)
    )
    node = SortNode(key_fn, inst_fn, name=f"sort#{op.id}")
    runner.engine.add(node)
    runner._connect_inputs(op, node)
    runner._register(op, node)
