"""Ordered ops (reference: python/pathway/stdlib/ordered/ ``diff``)."""

from __future__ import annotations

from ...internals.table import Table

__all__ = ["diff"]


def diff(table: Table, timestamp, *values, instance=None) -> Table:
    """Per-row difference vs the previous row in ``timestamp`` order
    (reference: stdlib/ordered/diff.py)."""
    import pathway_tpu as pw

    from ..indexing.sorting import sort as _sort

    order = _sort(table, key=timestamp, instance=instance)
    with_prev = table.with_columns(__prev__=order.prev)
    exprs = {}
    for v in values:
        name = v.name
        prev_val = table.ix(with_prev["__prev__"], optional=True, context=with_prev)[name]
        exprs[f"diff_{name}"] = pw.if_else(
            with_prev["__prev__"].is_none(),
            None,
            table[name] - prev_val,
        )
    return with_prev._select_exprs(exprs, universe=table._universe)
