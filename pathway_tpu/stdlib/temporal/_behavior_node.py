"""Runtime node applying window behaviors to assigned window rows.

reference: src/engine/dataflow/operators/time_column.rs — ``buffer``
(delay: hold rows until the event-time watermark passes
window_start + delay), ``forget`` (cutoff: drop late rows and, with
``keep_results=False``, retract whole windows once the watermark passes
window_end + cutoff) and ``freeze`` — parameterized by
``common_behavior`` / ``exactly_once_behavior``
(stdlib/temporal/temporal_behavior.py).

The event-time watermark is the max time value observed across the
stream, advanced at micro-batch boundaries — the same "watermark follows
the data" model the reference's time_column operator uses on the totally
ordered outer scope.
"""

from __future__ import annotations

from typing import Any

from ...internals.engine import Entry, Node, consolidate, freeze_row
from ...internals.graph import Operator
from ...internals.runtime import GraphRunner

__all__ = ["WindowBehaviorNode", "lower_window_behavior"]


def _num(v):
    from ...internals.value import DateTimeNaive, DateTimeUtc, Duration

    if isinstance(v, (Duration, DateTimeNaive, DateTimeUtc)):
        return v.ns
    return v


class WindowBehaviorNode(Node):
    """Port 0: assigned window rows carrying (time, window_start,
    window_end) at known positions."""

    def __init__(
        self,
        time_idx: int,
        start_idx: int,
        end_idx: int,
        delay: Any = None,
        cutoff: Any = None,
        keep_results: bool = True,
        delay_from_end: bool = False,
        name: str = "window_behavior",
    ):
        super().__init__(n_inputs=1, name=name)
        self.time_idx = time_idx
        self.start_idx = start_idx
        self.end_idx = end_idx
        self.delay_from_end = delay_from_end  # exactly-once: ready at end+shift
        self.delay = _num(delay) if delay is not None else None
        self.cutoff = _num(cutoff) if cutoff is not None else None
        self.keep_results = keep_results
        self.watermark: Any = None
        self.held: list[Entry] = []
        # window_end -> released entries (for keep_results=False retraction)
        self.released: dict[Any, list[Entry]] = {}
        self.closed: set = set()

    def _window_closed(self, end) -> bool:
        return (
            self.cutoff is not None
            and self.watermark is not None
            and _num(end) + self.cutoff <= self.watermark
        )

    def _ready(self, row) -> bool:
        if self.delay is None:
            return True
        ref = row[self.end_idx if self.delay_from_end else self.start_idx]
        return (
            self.watermark is not None
            and _num(ref) + self.delay <= self.watermark
        )

    def _release(self, entry: Entry, out: list[Entry]) -> None:
        end_key = _num(entry[1][self.end_idx])
        if not self.keep_results:
            self.released.setdefault(end_key, []).append(entry)
        out.append(entry)

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        incoming = self.take(0)
        # watermark advances at the batch boundary: rows of this batch are
        # admitted against the watermark of the *previous* batch, then the
        # clock moves (time_column.rs applies the same batch-edge semantics)
        for key, row, diff in incoming:
            end = row[self.end_idx]
            if diff > 0 and self._window_closed(end):
                continue  # late data for a closed window: forgotten
            if diff < 0:
                # retraction: cancel a matching held entry first
                target = (key, freeze_row(row))
                for i, (hk, hr, hd) in enumerate(self.held):
                    if hd > 0 and (hk, freeze_row(hr)) == target:
                        del self.held[i]
                        break
                else:
                    if self.keep_results and self._window_closed(end):
                        # closed windows are frozen: a late upstream
                        # recompute (e.g. a session re-merge triggered by a
                        # forgotten row) may not retract their emitted rows
                        continue
                    self._release((key, row, diff), out)
                continue
            if self._ready(row):
                self._release((key, row, diff), out)
            else:
                self.held.append((key, row, diff))
        # advance the watermark (probe-only intervals_over rows carry a
        # None event time and do not move the clock)
        for _, row, _ in incoming:
            tv = row[self.time_idx]
            if tv is None:
                continue
            tv = _num(tv)
            if self.watermark is None or tv > self.watermark:
                self.watermark = tv
        # release newly-ready held rows; cutoff is admission control for
        # *incoming* rows — anything already held was on time, so a window
        # closing while its rows sat in the buffer still emits them
        still: list[Entry] = []
        for entry in self.held:
            if self._ready(entry[1]):
                self._release(entry, out)
            else:
                still.append(entry)
        self.held = still
        # keep_results=False: retract every row of windows that just closed
        if not self.keep_results:
            for end_key in list(self.released):
                if (
                    self.cutoff is not None
                    and self.watermark is not None
                    and end_key + self.cutoff <= self.watermark
                ):
                    for key, row, diff in self.released.pop(end_key):
                        out.append((key, row, -diff))
        return consolidate(out)

    def on_end(self) -> list[Entry]:
        # stream close: flush everything still buffered (batch-mode windows
        # must still appear even if the watermark never passed their delay)
        out: list[Entry] = []
        held, self.held = self.held, []
        for entry in held:
            out.append(entry)
        return consolidate(out)


def apply_temporal_behavior(table, time_expr, behavior):
    """Buffer/forget a plain stream by its event-time column (reference:
    interval-join behaviors compiled onto time_column.rs forget/buffer).

    delay holds rows until the watermark passes t+delay; cutoff drops rows
    arriving after the watermark passed t+cutoff; keep_results=False
    retracts rows once their time falls behind the cutoff — which is what
    bounds join state for interval joins.  Returns a table with the same
    columns.
    """
    from ...internals.desugaring import resolve_expression
    from ...internals.table import Table
    from ...internals.universe import Universe
    from .temporal_behavior import CommonBehavior, ExactlyOnceBehavior

    time_e = resolve_expression(time_expr, table)
    with_t = table.with_columns(__behavior_t__=time_e)
    names = with_t.column_names()
    idx = names.index("__behavior_t__")
    if isinstance(behavior, ExactlyOnceBehavior):
        params = dict(
            delay=behavior.shift or 0, cutoff=behavior.shift or 0,
            keep_results=True, delay_from_end=True,
        )
    elif isinstance(behavior, CommonBehavior):
        params = dict(
            delay=behavior.delay, cutoff=behavior.cutoff,
            keep_results=behavior.keep_results, delay_from_end=False,
        )
    else:
        raise TypeError(f"unknown behavior {behavior!r}")
    op = Operator(
        "window_behavior",
        [with_t],
        params=dict(time_idx=idx, start_idx=idx, end_idx=idx, **params),
    )
    out = Table._new(op, with_t.schema, Universe())
    return out._select_exprs(
        {n: out[n] for n in table.column_names()}, universe=out._universe
    )


def lower_window_behavior(runner: GraphRunner, op: Operator) -> None:
    node = WindowBehaviorNode(
        time_idx=op.params["time_idx"],
        start_idx=op.params["start_idx"],
        end_idx=op.params["end_idx"],
        delay=op.params.get("delay"),
        cutoff=op.params.get("cutoff"),
        keep_results=op.params.get("keep_results", True),
        delay_from_end=op.params.get("delay_from_end", False),
        name=f"window_behavior#{op.id}",
    )
    runner.engine.add(node)
    runner._connect_inputs(op, node)
    runner._register(op, node)
