"""Temporal joins: interval_join, window_join, asof_join.

reference: python/pathway/stdlib/temporal/_interval_join.py (1619 LoC),
_window_join.py (1217), _asof_join.py (1107) — all return JoinResult-style
objects finalized by ``.select(...)``.

Design: all three desugar onto the core incremental engine —

* interval_join: the time axis is bucketed at band width; left rows flatten
  into candidate buckets, equi-join on (bucket, keys), exact band condition
  filters (bucketing bounds the candidate set, playing the role of the
  reference's gradual_broadcast band maintenance);
* window_join: both sides get window assignments, equi-join on the window;
* asof_join: per key, both sides merge into one sorted multiset and the
  match assignment is recomputed per dirty key by the incremental groupby.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any

import pathway_tpu as pw

from ...internals import dtype as dt
from ...internals.desugaring import expand_select_args, resolve_expression
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.joins import JoinMode
from ...internals.table import Table
from ._window import Window, _num

__all__ = ["interval", "interval_join", "window_join", "asof_join", "AsofDirection"]


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    """reference: _interval_join.py interval()"""
    return Interval(lower_bound, upper_bound)


class _PackedJoinResult:
    """Join result over a base table carrying packed payload tuples
    ``__lpay__``/``__rpay__``; ``select`` rewrites references to the original
    left/right tables into tuple projections."""

    def __init__(
        self,
        base: Table,
        left: Table,
        right: Table,
        right_optional: bool,
        left_optional: bool = False,
    ):
        self._base = base
        self._left = left
        self._right = right
        self._right_optional = right_optional
        self._left_optional = left_optional

    def select(self, *args: Any, **kwargs: Any) -> Table:
        exprs = expand_select_args(args, kwargs, self._left, self._left, self._right)
        lnames = self._left.column_names()
        rnames = self._right.column_names()
        base = self._base
        right_optional = self._right_optional
        left_optional = self._left_optional

        def mapping(node):
            if isinstance(node, ColumnReference) and node.table is self._left:
                i = lnames.index(node.name)
                dtype = dt.Optional(node._dtype) if left_optional else node._dtype
                return ApplyExpression(
                    lambda lp, _i=i: (lp[_i] if lp is not None else None),
                    dtype,
                    base["__lpay__"],
                )
            if isinstance(node, ColumnReference) and node.table is self._right:
                i = rnames.index(node.name)
                dtype = (
                    dt.Optional(node._dtype) if right_optional else node._dtype
                )
                return ApplyExpression(
                    lambda rp, _i=i: (rp[_i] if rp is not None else None),
                    dtype,
                    base["__rpay__"],
                )
            return None

        out = {n: e._substitute(mapping) for n, e in exprs.items()}
        return base._select_exprs(out, universe=base._universe)


def _pack(table: Table) -> Any:
    return pw.make_tuple(*[table[n] for n in table.column_names()])


def interval_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    interval: Interval,
    *on: Any,
    how: JoinMode = JoinMode.INNER,
    behavior=None,
) -> _PackedJoinResult:
    """reference: _interval_join.py interval_join — match when
    ``other_time - self_time ∈ [lb, ub]``.  ``behavior`` buffers/forgets
    both input streams by their time columns before joining (late rows
    dropped, old state retracted with keep_results=False)."""
    lb, ub = _num(interval.lower_bound), _num(interval.upper_bound)
    if ub < lb:
        raise ValueError("interval upper bound below lower bound")
    width = max(ub - lb, 1)

    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)

    def left_buckets(t):
        tv = _num(t)
        return tuple(range(int((tv + lb) // width), int((tv + ub) // width) + 1))

    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    lhs = self.select(
        __t__=lt,
        __buckets__=ApplyExpression(left_buckets, dt.List(dt.INT), lt),
        __k__=pw.make_tuple(*key_l),
        __lpay__=_pack(self),
        __lorig__=pw.this.id,  # original row id survives the flatten
    )
    lhs = lhs.flatten(lhs["__buckets__"])
    rhs = other.select(
        __t__=rt,
        __bucket__=ApplyExpression(lambda t: int(_num(t) // width), dt.INT, rt),
        __k__=pw.make_tuple(*key_r),
        __rpay__=_pack(other),
    )
    if behavior is not None:
        # buffer/forget both sides by event time before the join: late rows
        # beyond the cutoff are dropped, keep_results=False retracts old
        # rows and bounds the join state (time_column.rs forget semantics)
        from ._behavior_node import apply_temporal_behavior

        lhs = apply_temporal_behavior(lhs, lhs["__t__"], behavior)
        rhs = apply_temporal_behavior(rhs, rhs["__t__"], behavior)
    joined = lhs.join(
        rhs,
        lhs["__buckets__"] == rhs["__bucket__"],
        lhs["__k__"] == rhs["__k__"],
        how=JoinMode.INNER,
    ).select(
        __lt__=lhs["__t__"],
        __rt__=rhs["__t__"],
        __lpay__=lhs["__lpay__"],
        __rpay__=rhs["__rpay__"],
        __lid__=lhs["__lorig__"],
        __rid__=pw.right.id,
    )
    in_band = joined.filter(
        (joined["__rt__"] - joined["__lt__"] >= interval.lower_bound)
        & (joined["__rt__"] - joined["__lt__"] <= interval.upper_bound)
    )
    if how in (JoinMode.LEFT, JoinMode.OUTER):
        # left rows with no band match get a None right payload
        # (reference: _interval_join.py interval_join_left :40-120)
        in_band = in_band.concat_reindex(
            _antijoin_side(self, in_band, "__lid__").select(
                __lt__=None, __rt__=None,
                __lpay__=pw.this["__pay__"], __rpay__=None,
                __lid__=pw.this["__sid__"], __rid__=None,
            )
        )
    if how in (JoinMode.RIGHT, JoinMode.OUTER):
        in_band = in_band.concat_reindex(
            _antijoin_side(other, in_band, "__rid__").select(
                __lt__=None, __rt__=None,
                __lpay__=None, __rpay__=pw.this["__pay__"],
                __lid__=None, __rid__=pw.this["__sid__"],
            )
        )
    return _PackedJoinResult(
        in_band,
        self,
        other,
        right_optional=how in (JoinMode.LEFT, JoinMode.OUTER),
        left_optional=how in (JoinMode.RIGHT, JoinMode.OUTER),
    )


def _antijoin_side(side: Table, matched: Table, id_col: str) -> Table:
    """Rows of ``side`` whose id never appears in ``matched[id_col]``,
    packed as (__sid__, __pay__)."""
    present = matched.filter(matched[id_col].is_not_none())
    keys = present.groupby(present[id_col]).reduce(
        __sid__=present[id_col], __n__=pw.reducers.count()
    )
    all_rows = side.select(__pay__=_pack(side), __sid__=pw.this.id)
    return all_rows.with_id(all_rows["__sid__"]).difference(
        keys.with_id(keys["__sid__"])
    )


def window_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    window: Window,
    *on: Any,
    how: JoinMode = JoinMode.INNER,
) -> _PackedJoinResult:
    """reference: _window_join.py — join rows landing in the same window;
    left/right/outer modes emit unmatched (row, window) instances with a
    None payload for the absent side (window_join_left/right/outer)."""
    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)
    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    def wins(t):
        return window.assign(t)

    lhs = self.select(
        __wins__=ApplyExpression(wins, dt.List(dt.ANY), lt),
        __k__=pw.make_tuple(*key_l),
        __lpay__=_pack(self),
    )
    lhs = lhs.flatten(lhs["__wins__"])
    rhs = other.select(
        __wins__=ApplyExpression(wins, dt.List(dt.ANY), rt),
        __k__=pw.make_tuple(*key_r),
        __rpay__=_pack(other),
    )
    rhs = rhs.flatten(rhs["__wins__"])
    joined = lhs.join(
        rhs,
        lhs["__wins__"] == rhs["__wins__"],
        lhs["__k__"] == rhs["__k__"],
        how=JoinMode.INNER,
    ).select(
        __lpay__=lhs["__lpay__"],
        __rpay__=rhs["__rpay__"],
        __window__=lhs["__wins__"],
        __lid__=pw.left.id,
        __rid__=pw.right.id,
    )
    if how in (JoinMode.LEFT, JoinMode.OUTER):
        # unmatched (left row, window) instances keep their window
        joined = joined.concat_reindex(
            _antijoin_window_side(lhs, joined, "__lid__", "__lpay__").select(
                __lpay__=pw.this["__pay__"], __rpay__=None,
                __window__=pw.this["__win__"],
                __lid__=pw.this["__sid__"], __rid__=None,
            )
        )
    if how in (JoinMode.RIGHT, JoinMode.OUTER):
        joined = joined.concat_reindex(
            _antijoin_window_side(rhs, joined, "__rid__", "__rpay__").select(
                __lpay__=None, __rpay__=pw.this["__pay__"],
                __window__=pw.this["__win__"],
                __lid__=None, __rid__=pw.this["__sid__"],
            )
        )
    return _PackedJoinResult(
        joined,
        self,
        other,
        right_optional=how in (JoinMode.LEFT, JoinMode.OUTER),
        left_optional=how in (JoinMode.RIGHT, JoinMode.OUTER),
    )


def _antijoin_window_side(
    flat_side: Table, matched: Table, id_col: str, pay_col: str
) -> Table:
    """Flattened (row, window) instances of one side that matched nothing,
    packed as (__sid__, __pay__, __win__)."""
    present = matched.filter(matched[id_col].is_not_none())
    keys = present.groupby(present[id_col]).reduce(
        __sid__=present[id_col], __n__=pw.reducers.count()
    )
    all_rows = flat_side.select(
        __pay__=flat_side[pay_col], __win__=flat_side["__wins__"], __sid__=pw.this.id
    )
    return all_rows.with_id(all_rows["__sid__"]).difference(
        keys.with_id(keys["__sid__"])
    )


class AsofDirection(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


def asof_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    *on: Any,
    how: JoinMode = JoinMode.LEFT,
    defaults: dict | None = None,
    direction: AsofDirection = AsofDirection.BACKWARD,
) -> _PackedJoinResult:
    """reference: _asof_join.py — for each row, the temporally closest
    counterpart row (per key) in the given direction.  LEFT matches every
    left row, RIGHT every right row, OUTER both perspectives."""
    if how not in (JoinMode.LEFT, JoinMode.RIGHT, JoinMode.OUTER):
        raise ValueError("asof_join supports left, right, and outer modes")
    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)
    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    l_packed = self.select(
        __k__=pw.make_tuple(*key_l),
        __t__=lt,
        __side__=0,
        __pay__=_pack(self),
        __rid__=pw.this.id,
    )
    r_packed = other.select(
        __k__=pw.make_tuple(*key_r),
        __t__=rt,
        __side__=1,
        __pay__=_pack(other),
        __rid__=pw.this.id,
    )
    merged = l_packed.concat_reindex(r_packed)
    dir_value = direction.value
    mode = how

    def best_match(t, cands):
        """Closest (time, pay) among time-sorted ``cands`` per direction."""
        best = None
        if dir_value in ("backward", "nearest"):
            for ct, cpay in cands:
                if ct <= t:
                    best = (ct, cpay)
                else:
                    break
        if dir_value in ("forward", "nearest"):
            fwd = next(((ct, cpay) for ct, cpay in cands if ct >= t), None)
            if fwd is not None and (
                best is None
                or (
                    dir_value == "nearest"
                    and abs(_num(fwd[0]) - _num(t)) < abs(_num(best[0]) - _num(t))
                )
                or dir_value == "forward"
            ):
                best = fwd
        return best

    def assign(rows):
        lefts = [(t, rid, pay) for t, side, rid, pay in rows if side == 0]
        rights = [(t, rid, pay) for t, side, rid, pay in rows if side == 1]
        out = []
        if mode in (JoinMode.LEFT, JoinMode.OUTER):
            r_cands = [(t, pay) for t, _rid, pay in rights]
            for t, rid, pay in lefts:
                best = best_match(t, r_cands)
                out.append((0, rid, pay, best[1] if best else None))
        if mode in (JoinMode.RIGHT, JoinMode.OUTER):
            l_cands = [(t, pay) for t, _rid, pay in lefts]
            for t, rid, pay in rights:
                best = best_match(t, l_cands)
                out.append((1, rid, best[1] if best else None, pay))
        return tuple(out)

    grouped = merged.groupby(merged["__k__"]).reduce(
        __matches__=pw.apply_with_type(
            lambda rows: assign(list(rows)),
            tuple,
            pw.reducers.sorted_tuple(
                pw.make_tuple(
                    merged["__t__"], merged["__side__"], merged["__rid__"], merged["__pay__"]
                )
            ),
        ),
    )
    flat = grouped.flatten(grouped["__matches__"])
    from ...internals.keys import ref_scalar

    base = flat._select_exprs(
        {
            "__side__": flat["__matches__"].get(0),
            "__rid__": flat["__matches__"].get(1),
            "__lpay__": flat["__matches__"].get(2),
            "__rpay__": flat["__matches__"].get(3),
        },
        universe=flat._universe,
    )
    if how == JoinMode.OUTER:
        # OUTER emits both perspectives: row ids from the two source tables
        # share one key space, so salt keys by side to keep a left id that
        # collides with a right id from overwriting its row
        base = base.with_id(
            ApplyExpression(
                lambda side, rid: ref_scalar("__asof__", side, rid),
                dt.ANY,
                base["__side__"],
                base["__rid__"],
            )
        )
    else:
        base = base.with_id(base["__rid__"])
    result = _PackedJoinResult(
        base,
        self,
        other,
        right_optional=how in (JoinMode.LEFT, JoinMode.OUTER),
        left_optional=how in (JoinMode.RIGHT, JoinMode.OUTER),
    )
    if defaults:
        result._defaults = defaults  # applied by callers via coalesce
    return result


# -- named mode wrappers (reference surface: _interval_join.py
# interval_join_{inner,left,right,outer} etc.) --


def _mode_wrapper(fn, mode: JoinMode):
    import functools

    @functools.wraps(fn)
    def wrapped(self, other, *args, **kwargs):
        kwargs["how"] = mode
        return fn(self, other, *args, **kwargs)

    return wrapped


interval_join_inner = _mode_wrapper(interval_join, JoinMode.INNER)
interval_join_left = _mode_wrapper(interval_join, JoinMode.LEFT)
interval_join_right = _mode_wrapper(interval_join, JoinMode.RIGHT)
interval_join_outer = _mode_wrapper(interval_join, JoinMode.OUTER)
window_join_inner = _mode_wrapper(window_join, JoinMode.INNER)
window_join_left = _mode_wrapper(window_join, JoinMode.LEFT)
window_join_right = _mode_wrapper(window_join, JoinMode.RIGHT)
window_join_outer = _mode_wrapper(window_join, JoinMode.OUTER)
asof_join_left = _mode_wrapper(asof_join, JoinMode.LEFT)
asof_join_right = _mode_wrapper(asof_join, JoinMode.RIGHT)
asof_join_outer = _mode_wrapper(asof_join, JoinMode.OUTER)

#: reference result-class names (surface parity; one packed implementation)
IntervalJoinResult = _PackedJoinResult
WindowJoinResult = _PackedJoinResult
AsofJoinResult = _PackedJoinResult
