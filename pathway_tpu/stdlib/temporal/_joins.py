"""Temporal joins: interval_join, window_join, asof_join.

reference: python/pathway/stdlib/temporal/_interval_join.py (1619 LoC),
_window_join.py (1217), _asof_join.py (1107) — all return JoinResult-style
objects finalized by ``.select(...)``.

Design: all three desugar onto the core incremental engine —

* interval_join: the time axis is bucketed at band width; left rows flatten
  into candidate buckets, equi-join on (bucket, keys), exact band condition
  filters (bucketing bounds the candidate set, playing the role of the
  reference's gradual_broadcast band maintenance);
* window_join: both sides get window assignments, equi-join on the window;
* asof_join: per key, both sides merge into one sorted multiset and the
  match assignment is recomputed per dirty key by the incremental groupby.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any

import pathway_tpu as pw

from ...internals import dtype as dt
from ...internals.desugaring import expand_select_args, resolve_expression
from ...internals.expression import ApplyExpression, ColumnReference
from ...internals.joins import JoinMode
from ...internals.table import Table
from ._window import Window, _num

__all__ = ["interval", "interval_join", "window_join", "asof_join", "AsofDirection"]


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    """reference: _interval_join.py interval()"""
    return Interval(lower_bound, upper_bound)


class _PackedJoinResult:
    """Join result over a base table carrying packed payload tuples
    ``__lpay__``/``__rpay__``; ``select`` rewrites references to the original
    left/right tables into tuple projections."""

    def __init__(self, base: Table, left: Table, right: Table, right_optional: bool):
        self._base = base
        self._left = left
        self._right = right
        self._right_optional = right_optional

    def select(self, *args: Any, **kwargs: Any) -> Table:
        exprs = expand_select_args(args, kwargs, self._left, self._left, self._right)
        lnames = self._left.column_names()
        rnames = self._right.column_names()
        base = self._base
        right_optional = self._right_optional

        def mapping(node):
            if isinstance(node, ColumnReference) and node.table is self._left:
                i = lnames.index(node.name)
                return ApplyExpression(
                    lambda lp, _i=i: lp[_i], node._dtype, base["__lpay__"]
                )
            if isinstance(node, ColumnReference) and node.table is self._right:
                i = rnames.index(node.name)
                dtype = (
                    dt.Optional(node._dtype) if right_optional else node._dtype
                )
                return ApplyExpression(
                    lambda rp, _i=i: (rp[_i] if rp is not None else None),
                    dtype,
                    base["__rpay__"],
                )
            return None

        out = {n: e._substitute(mapping) for n, e in exprs.items()}
        return base._select_exprs(out, universe=base._universe)


def _pack(table: Table) -> Any:
    return pw.make_tuple(*[table[n] for n in table.column_names()])


def interval_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    interval: Interval,
    *on: Any,
    how: JoinMode = JoinMode.INNER,
    behavior=None,
) -> _PackedJoinResult:
    """reference: _interval_join.py interval_join — match when
    ``other_time - self_time ∈ [lb, ub]``.  ``behavior`` buffers/forgets
    both input streams by their time columns before joining (late rows
    dropped, old state retracted with keep_results=False)."""
    lb, ub = _num(interval.lower_bound), _num(interval.upper_bound)
    if ub < lb:
        raise ValueError("interval upper bound below lower bound")
    if how not in (JoinMode.INNER, JoinMode.LEFT):
        raise ValueError("interval_join supports inner and left modes")
    width = max(ub - lb, 1)

    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)

    def left_buckets(t):
        tv = _num(t)
        return tuple(range(int((tv + lb) // width), int((tv + ub) // width) + 1))

    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    lhs = self.select(
        __t__=lt,
        __buckets__=ApplyExpression(left_buckets, dt.List(dt.INT), lt),
        __k__=pw.make_tuple(*key_l),
        __lpay__=_pack(self),
    )
    lhs = lhs.flatten(lhs["__buckets__"])
    rhs = other.select(
        __t__=rt,
        __bucket__=ApplyExpression(lambda t: int(_num(t) // width), dt.INT, rt),
        __k__=pw.make_tuple(*key_r),
        __rpay__=_pack(other),
    )
    if behavior is not None:
        # buffer/forget both sides by event time before the join: late rows
        # beyond the cutoff are dropped, keep_results=False retracts old
        # rows and bounds the join state (time_column.rs forget semantics)
        from ._behavior_node import apply_temporal_behavior

        lhs = apply_temporal_behavior(lhs, lhs["__t__"], behavior)
        rhs = apply_temporal_behavior(rhs, rhs["__t__"], behavior)
    joined = lhs.join(
        rhs,
        lhs["__buckets__"] == rhs["__bucket__"],
        lhs["__k__"] == rhs["__k__"],
        how=JoinMode.INNER,
    ).select(
        __lt__=lhs["__t__"],
        __rt__=rhs["__t__"],
        __lpay__=lhs["__lpay__"],
        __rpay__=rhs["__rpay__"],
        __lid__=pw.left.id,
    )
    in_band = joined.filter(
        (joined["__rt__"] - joined["__lt__"] >= interval.lower_bound)
        & (joined["__rt__"] - joined["__lt__"] <= interval.upper_bound)
    )
    if how == JoinMode.LEFT:
        # left rows with no band match get a None right payload
        matched_left = in_band.groupby(in_band["__lid__"]).reduce(
            __lid__=in_band["__lid__"], n=pw.reducers.count()
        )
        all_left = self.select(__lpay__=_pack(self), __lid__=pw.this.id)
        matched_keys = matched_left.with_id(matched_left["__lid__"])
        unmatched = all_left.with_id(all_left["__lid__"]).difference(matched_keys)
        unmatched_rows = unmatched.select(
            __lt__=None, __rt__=None,
            __lpay__=unmatched["__lpay__"], __rpay__=None, __lid__=unmatched["__lid__"],
        )
        in_band = in_band.concat_reindex(unmatched_rows)
    return _PackedJoinResult(in_band, self, other, right_optional=how == JoinMode.LEFT)


def window_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    window: Window,
    *on: Any,
    how: JoinMode = JoinMode.INNER,
) -> _PackedJoinResult:
    """reference: _window_join.py — join rows landing in the same window."""
    if how not in (JoinMode.INNER,):
        raise ValueError("window_join currently supports inner mode")
    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)
    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    def wins(t):
        return window.assign(t)

    lhs = self.select(
        __wins__=ApplyExpression(wins, dt.List(dt.ANY), lt),
        __k__=pw.make_tuple(*key_l),
        __lpay__=_pack(self),
    )
    lhs = lhs.flatten(lhs["__wins__"])
    rhs = other.select(
        __wins__=ApplyExpression(wins, dt.List(dt.ANY), rt),
        __k__=pw.make_tuple(*key_r),
        __rpay__=_pack(other),
    )
    rhs = rhs.flatten(rhs["__wins__"])
    joined = lhs.join(
        rhs,
        lhs["__wins__"] == rhs["__wins__"],
        lhs["__k__"] == rhs["__k__"],
        how=JoinMode.INNER,
    ).select(
        __lpay__=lhs["__lpay__"],
        __rpay__=rhs["__rpay__"],
        __window__=lhs["__wins__"],
    )
    return _PackedJoinResult(joined, self, other, right_optional=False)


class AsofDirection(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


def asof_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    *on: Any,
    how: JoinMode = JoinMode.LEFT,
    defaults: dict | None = None,
    direction: AsofDirection = AsofDirection.BACKWARD,
) -> _PackedJoinResult:
    """reference: _asof_join.py — for each left row, the temporally closest
    right row (per key) in the given direction."""
    lt = resolve_expression(self_time, self)
    rt = resolve_expression(other_time, other)
    key_l = [resolve_expression(c.left, self, self, other) for c in on]
    key_r = [resolve_expression(c.right, self, self, other) for c in on]

    l_packed = self.select(
        __k__=pw.make_tuple(*key_l),
        __t__=lt,
        __side__=0,
        __pay__=_pack(self),
        __rid__=pw.this.id,
    )
    r_packed = other.select(
        __k__=pw.make_tuple(*key_r),
        __t__=rt,
        __side__=1,
        __pay__=_pack(other),
        __rid__=pw.this.id,
    )
    merged = l_packed.concat_reindex(r_packed)
    dir_value = direction.value

    def assign(rows):
        rights = [(t, pay) for t, side, rid, pay in rows if side == 1]
        out = []
        for t, side, rid, pay in rows:
            if side != 0:
                continue
            best = None
            if dir_value in ("backward", "nearest"):
                for rt_, rpay in rights:
                    if rt_ <= t:
                        best = (rt_, rpay)
                    else:
                        break
            if dir_value in ("forward", "nearest"):
                fwd = next(((rt_, rpay) for rt_, rpay in rights if rt_ >= t), None)
                if fwd is not None and (
                    best is None
                    or (
                        dir_value == "nearest"
                        and abs(_num(fwd[0]) - _num(t)) < abs(_num(best[0]) - _num(t))
                    )
                    or dir_value == "forward"
                ):
                    best = fwd
            out.append((rid, pay, best[1] if best else None))
        return tuple(out)

    grouped = merged.groupby(merged["__k__"]).reduce(
        __matches__=pw.apply_with_type(
            lambda rows: assign(list(rows)),
            tuple,
            pw.reducers.sorted_tuple(
                pw.make_tuple(
                    merged["__t__"], merged["__side__"], merged["__rid__"], merged["__pay__"]
                )
            ),
        ),
    )
    flat = grouped.flatten(grouped["__matches__"])
    base = flat._select_exprs(
        {
            "__rid__": flat["__matches__"].get(0),
            "__lpay__": flat["__matches__"].get(1),
            "__rpay__": flat["__matches__"].get(2),
        },
        universe=flat._universe,
    )
    base = base.with_id(base["__rid__"])
    result = _PackedJoinResult(base, self, other, right_optional=True)
    if defaults:
        result._defaults = defaults  # applied by callers via coalesce
    return result
