"""asof-now join — "join against current state only".

reference: python/pathway/stdlib/temporal/_asof_now_join.py:403 — the
serving primitive: each left (query) row is joined against the right side's
state as of the row's arrival time; the result is never revisited when the
right side later changes.  The engine's ``late`` barrier provides the
global updates-before-queries ordering per timestamp.
"""

from __future__ import annotations

from typing import Any

from ...internals.engine import Entry, JoinNode, consolidate, freeze_value
from ...internals.joins import JoinMode, JoinResult
from ...internals.table import Table

__all__ = ["asof_now_join", "asof_now_join_inner", "asof_now_join_left", "AsofNowJoinNode"]


class AsofNowJoinNode(JoinNode):
    """Port 0 = right (state), port 1 = left (queries, append-only)."""

    late = True

    def flush(self, time: int) -> list[Entry]:
        out: list[Entry] = []
        # state updates first
        for key, row, diff in self.take(0):
            jk = freeze_value(self.right_key_fn(key, row))
            self._apply(self.right_state, jk, key, row, diff)
            self.right_count[jk] += diff
        # then queries: answered once against current state
        for key, row, diff in self.take(1):
            if diff <= 0:
                raise ValueError(
                    "asof_now_join received a retraction on its left (query) "
                    "side; the left stream must be append-only"
                )
            jk = freeze_value(self.left_key_fn(key, row))
            matches = list(self.right_state.get(jk, {}).values()) if jk is not None else []
            if matches:
                for cnt, rkey, rrow in matches:
                    self._emit(key, row, rkey, rrow, diff * cnt, out)
            elif self.left_outer:
                self._emit(key, row, None, None, diff, out)
        return consolidate(out)


class AsofNowJoinResult(JoinResult):
    """Same select surface as JoinResult but lowered to AsofNowJoinNode."""

    def select(self, *args: Any, **kwargs: Any) -> Table:
        from ...internals.graph import Operator
        from ...internals.desugaring import expand_select_args
        from ...internals.schema import ColumnSchema, _schema_from_columns
        from ...internals import dtype as dt
        from ...internals.universe import Universe

        exprs = expand_select_args(args, kwargs, self._left, self._left, self._right)
        columns = {}
        for name, e in exprs.items():
            dtype = e._dtype
            if self._mode in (JoinMode.LEFT,):
                from ...internals.joins import _refers_to

                if _refers_to(e, self._right):
                    dtype = dt.Optional(dtype)
            columns[name] = ColumnSchema(name=name, dtype=dtype)
        op = Operator(
            "asof_now_join",
            [self._left, self._right],
            params=dict(
                on=self._on,
                mode=self._mode,
                out_exprs=exprs,
                id_expr=self._id_expr,
            ),
        )
        return Table._new(op, _schema_from_columns(columns), Universe())


def asof_now_join(
    self: Table,
    other: Table,
    *on: Any,
    how: JoinMode = JoinMode.INNER,
    id: Any = None,
    left_instance=None,
    right_instance=None,
) -> AsofNowJoinResult:
    """reference: _asof_now_join.py asof_now_join"""
    if how not in (JoinMode.INNER, JoinMode.LEFT):
        raise ValueError("asof_now_join supports only INNER and LEFT modes")
    on = list(on)
    if left_instance is not None and right_instance is not None:
        from ...internals.desugaring import resolve_expression
        from ...internals.expression import smart_wrap

        on.append(
            smart_wrap(resolve_expression(left_instance, self))
            == resolve_expression(right_instance, other)
        )
    id_expr = None
    if id is not None:
        from ...internals.desugaring import resolve_expression

        id_expr = resolve_expression(id, self, self, other)
    return AsofNowJoinResult(self, other, tuple(on), how, id_expr)


def asof_now_join_inner(self: Table, other: Table, *on, **kwargs) -> AsofNowJoinResult:
    return asof_now_join(self, other, *on, how=JoinMode.INNER, **kwargs)


def asof_now_join_left(self: Table, other: Table, *on, **kwargs) -> AsofNowJoinResult:
    return asof_now_join(self, other, *on, how=JoinMode.LEFT, **kwargs)


def lower_asof_now_join(runner, op) -> None:
    """Lowering mirrors _lower_join but with ports swapped (right=state is
    port 0 so updates land first) and no revisiting."""
    from ...internals.evaluator import compile_expression
    from ...internals.expression import ColumnReference, IdExpression
    from ...internals.keys import ref_pair
    from ...internals.runtime import _TableLayout

    left, right = op.inputs
    mode: JoinMode = op.params["mode"]
    on = op.params["on"]
    out_exprs = op.params["out_exprs"]
    id_expr = op.params.get("id_expr")

    llayout = _TableLayout([left])
    rlayout = _TableLayout([right])
    lfns = [compile_expression(le, llayout.resolver()) for le, _ in on]
    rfns = [compile_expression(re, rlayout.resolver()) for _, re in on]
    lcols = {n: i for i, n in enumerate(left.column_names())}
    rcols = {n: i for i, n in enumerate(right.column_names())}

    def join_resolve(ref: ColumnReference):
        if ref.name == "id":
            if ref.table is left:
                return lambda ctx: ctx[0]
            if ref.table is right:
                return lambda ctx: ctx[2]
            raise ValueError("id reference outside join")
        if ref.table is left:
            idx = lcols[ref.name]
            return lambda ctx: (ctx[1][idx] if ctx[1] is not None else None)
        if ref.table is right:
            idx = rcols[ref.name]
            return lambda ctx: (ctx[3][idx] if ctx[3] is not None else None)
        raise ValueError(f"asof_now_join select references foreign table: {ref!r}")

    out_fns = [compile_expression(e, join_resolve) for e in out_exprs.values()]

    def out_fn(lkey, lrow, rkey, rrow):
        return tuple(f((lkey, lrow, rkey, rrow)) for f in out_fns)

    if id_expr is not None and isinstance(id_expr, IdExpression) and id_expr.table is left:
        out_key_fn = lambda lkey, lrow, rkey, rrow: lkey
    else:
        out_key_fn = lambda lkey, lrow, rkey, rrow: ref_pair(lkey, rkey)

    node = AsofNowJoinNode(
        left_key_fn=lambda key, row: tuple(f((key, row)) for f in lfns),
        right_key_fn=lambda key, row: tuple(f((key, row)) for f in rfns),
        out_fn=out_fn,
        out_key_fn=out_key_fn,
        left_outer=mode == JoinMode.LEFT,
        name=f"asof_now_join#{op.id}",
    )
    runner.engine.add(node)
    # port 0 = right (state), port 1 = left (queries)
    runner._node_of(right).downstream.append((node, 0))
    runner._node_of(left).downstream.append((node, 1))
    runner._register(op, node)
