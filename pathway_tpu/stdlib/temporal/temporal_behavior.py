"""Temporal behaviors: delay / cutoff / keep_results.

reference: python/pathway/stdlib/temporal/temporal_behavior.py — compiled in
the reference to engine forget/buffer/freeze (operators/time_column.rs).
In this build behaviors parameterize the window operator's host-side
buffering/cutoff (applied in ``_window.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

__all__ = ["Behavior", "CommonBehavior", "common_behavior", "ExactlyOnceBehavior", "exactly_once_behavior"]


@dataclass
class Behavior:
    pass


@dataclass
class CommonBehavior(Behavior):
    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(delay=None, cutoff=None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay=delay, cutoff=cutoff, keep_results=keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any = None


def exactly_once_behavior(shift=None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift=shift)
