"""Windows: tumbling / sliding / session + ``windowby``.

reference: python/pathway/stdlib/temporal/_window.py:593-910 (windowby at
:863; window metadata columns ``_pw_window_start``/``_pw_window_end``).

Design: window assignment is a row-wise computation (tumbling/sliding) or a
per-instance recompute (session — merged from the sorted event multiset,
differential-style), after which the reduction is the ordinary incremental
groupby of the core engine keyed on (instance, window_start, window_end).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import pathway_tpu as pw

from ...internals import dtype as dt
from ...internals.desugaring import expand_select_args, resolve_expression
from ...internals.expression import ApplyExpression, ColumnExpression
from ...internals.table import Table
from .temporal_behavior import Behavior, CommonBehavior, ExactlyOnceBehavior

__all__ = ["Window", "tumbling", "sliding", "session", "windowby", "WindowGroupedTable"]


def _num(v):
    from ...internals.value import Duration, DateTimeNaive, DateTimeUtc

    if isinstance(v, Duration):
        return v.ns
    if isinstance(v, (DateTimeNaive, DateTimeUtc)):
        return v.ns
    return v


@dataclass
class Window:
    def assign(self, t: Any) -> tuple[tuple[Any, Any], ...]:
        raise NotImplementedError


@dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None

    def assign(self, t):
        d = _num(self.duration)
        o = _num(self.origin) if self.origin is not None else 0
        tv = _num(t)
        start = ((tv - o) // d) * d + o
        return ((self._wrap(start, t), self._wrap(start + d, t)),)

    def _wrap(self, value, sample):
        from ...internals.value import DateTimeNaive, DateTimeUtc, Duration

        if isinstance(sample, (DateTimeNaive, DateTimeUtc)):
            return type(sample)(ns=value)
        if isinstance(sample, Duration):
            return Duration(value)
        if isinstance(sample, float):
            return float(value)
        return value


@dataclass
class SlidingWindow(TumblingWindow):
    hop: Any = None
    ratio: int = 1

    def assign(self, t):
        d = _num(self.duration)
        h = _num(self.hop)
        o = _num(self.origin) if self.origin is not None else 0
        tv = _num(t)
        wins = []
        # all windows [s, s+d) with s ≡ o mod h containing tv
        first = ((tv - o - d) // h + 1) * h + o
        s = first
        while s <= tv:
            if tv < s + d:
                wins.append((self._wrap(s, t), self._wrap(s + d, t)))
            s += h
        return tuple(wins)


@dataclass
class SessionWindow(Window):
    predicate: Callable | None = None
    max_gap: Any = None

    def merge(self, times: list) -> list[tuple[Any, Any, Any]]:
        """Given sorted (time, id) pairs, return (start, end, id) per row."""
        out = []
        cur: list = []

        def flush():
            if not cur:
                return
            start = cur[0][0]
            end = cur[-1][0]
            for t, rid in cur:
                out.append((start, end, rid))

        for t, rid in times:
            if cur:
                prev_t = cur[-1][0]
                if self.predicate is not None:
                    joined = self.predicate(prev_t, t)
                else:
                    joined = _num(t) - _num(prev_t) <= _num(self.max_gap)
                if not joined:
                    flush()
                    cur = []
            cur.append((t, rid))
        flush()
        return out


@dataclass
class IntervalsOverWindow(Window):
    """Windows anchored at probe times from another table
    (reference: _window.py:793 ``intervals_over``)."""

    at: Any
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def intervals_over(*, at, lower_bound, upper_bound, is_outer: bool = True) -> Window:
    """For each probe time t in ``at``, group rows whose time lies in
    ``[t+lower_bound, t+upper_bound]``; ``_pw_window_location`` carries t
    (reference: _window.py:793)."""
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


def tumbling(duration=None, origin=None, length=None) -> Window:
    """reference: _window.py tumbling()"""
    return TumblingWindow(duration=duration if duration is not None else length, origin=origin)


def sliding(hop=None, duration=None, origin=None, ratio=None) -> Window:
    """reference: _window.py sliding()"""
    if duration is None and ratio is not None:
        duration = hop * ratio
    w = SlidingWindow(duration=duration, origin=origin)
    w.hop = hop
    return w


def session(predicate: Callable | None = None, max_gap=None) -> Window:
    """reference: _window.py session()"""
    if predicate is None and max_gap is None:
        raise ValueError("session() needs predicate or max_gap")
    return SessionWindow(predicate=predicate, max_gap=max_gap)


class WindowGroupedTable:
    """Result of windowby; ``reduce`` closes the aggregation
    (reference: _window.py WindowGroupedTable)."""

    def __init__(self, assigned: Table, instance_given: bool):
        self._assigned = assigned
        self._instance_given = instance_given

    _sort_by_name: str | None = None

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        t = self._assigned
        grouping = [t["_pw_window"], t["_pw_window_start"], t["_pw_window_end"]]
        if "_pw_window_location" in t.column_names():
            grouping.append(t["_pw_window_location"])
        if self._instance_given:
            grouping.append(t["_pw_instance"])
        sort_by = t[self._sort_by_name] if self._sort_by_name else None
        gt = t.groupby(*grouping, sort_by=sort_by)
        # rebind pw.this refs against the assigned table
        return gt.reduce(*args, **kwargs)


def windowby(
    table: Table,
    time_expr: Any,
    *,
    window: Window,
    instance: Any = None,
    behavior: Behavior | None = None,
    origin=None,
) -> WindowGroupedTable:
    """reference: _window.py:863"""
    time_e = resolve_expression(time_expr, table)
    instance_e = resolve_expression(instance, table) if instance is not None else None

    if isinstance(window, IntervalsOverWindow):
        assigned = _assign_intervals_over(table, time_e, instance_e, window)
        if behavior is not None:
            # probe-anchored windows buffer/forget by the data-row time
            # already materialized as __iv_time__ (probe-only rows have a
            # None time and ride the window bounds instead)
            assigned = _apply_behavior(
                assigned, table, time_e, behavior, time_col="__iv_time__"
            )
        wgt = WindowGroupedTable(assigned, instance_e is not None)
        wgt._sort_by_name = "__iv_time__"
        return wgt
    if isinstance(window, SessionWindow):
        # Sessions merge retroactively, so behaviors compile onto the INPUT
        # stream (the reference applies time_column forget/buffer before the
        # session operator): late rows are forgotten before they can merge
        # into already-emitted sessions, buffered rows enter the merge only
        # once the watermark passes t+delay, and keep_results=False retracts
        # input rows (hence their sessions) once behind the cutoff.
        if behavior is not None:
            from ...internals.expression import ColumnReference
            from ._behavior_node import apply_temporal_behavior
            from .temporal_behavior import ExactlyOnceBehavior

            if isinstance(behavior, ExactlyOnceBehavior):
                # emit-once: forget rows later than shift, and hold inputs
                # until the watermark passes t + shift + max_gap so no
                # further merge can touch the session once it appears
                gap = _num(window.max_gap) if window.max_gap is not None else 0
                shift = _num(behavior.shift or 0)
                input_behavior = CommonBehavior(
                    delay=shift + gap, cutoff=shift, keep_results=True
                )
            else:
                input_behavior = behavior
            gated = apply_temporal_behavior(table, time_e, input_behavior)

            def onto_gated(node):
                if isinstance(node, ColumnReference) and node.table is table:
                    return gated[node.name]
                return None

            time_e = time_e._substitute(onto_gated)
            if instance_e is not None:
                instance_e = instance_e._substitute(onto_gated)
            table = gated
            behavior = None  # fully compiled onto the input stream
        assigned = _assign_session(table, time_e, instance_e, window)
    else:
        win_dtype = time_e._dtype

        def windows_of(t):
            return window.assign(t)

        with_wins = table.with_columns(
            __wins__=ApplyExpression(windows_of, dt.List(dt.ANY), time_e),
            __inst__=(instance_e if instance_e is not None else 0),
        )
        flat = with_wins.flatten(with_wins["__wins__"])
        assigned = flat._select_exprs(
            {
                **{n: flat[n] for n in table.column_names()},
                "_pw_window_start": ApplyExpression(
                    lambda w: w[0], dt.unoptionalize(win_dtype), flat["__wins__"]
                ),
                "_pw_window_end": ApplyExpression(
                    lambda w: w[1], dt.unoptionalize(win_dtype), flat["__wins__"]
                ),
                "_pw_window": flat["__wins__"],
                "_pw_instance": flat["__inst__"],
            },
            universe=flat._universe,
        )
    if behavior is not None:
        assigned = _apply_behavior(assigned, table, time_e, behavior)
    return WindowGroupedTable(assigned, instance_e is not None)


def _apply_behavior(
    assigned: Table,
    source: Table,
    time_e,
    behavior: Behavior,
    time_col: str | None = None,
) -> Table:
    """Insert the buffering/cutoff node between window assignment and the
    grouped reduction (reference: behaviors compiled onto time_column.rs
    forget/buffer in the window operator).  ``time_col`` names an existing
    time column on ``assigned``; otherwise ``time_e`` is rebound onto it."""
    from ...internals.expression import ColumnReference
    from ...internals.graph import Operator
    from ...internals.universe import Universe

    if time_col is not None:
        with_t = assigned.with_columns(__behavior_t__=assigned[time_col])
    else:
        # rebind the time expression onto the assigned table (same column
        # names survive assignment)
        def rebind(node):
            if isinstance(node, ColumnReference) and node.table is source:
                return assigned[node.name]
            return None

        time_on_assigned = time_e._substitute(rebind)
        with_t = assigned.with_columns(__behavior_t__=time_on_assigned)
    names = with_t.column_names()
    if isinstance(behavior, ExactlyOnceBehavior):
        params = dict(
            delay=behavior.shift or 0,
            cutoff=behavior.shift or 0,
            keep_results=True,
            delay_from_end=True,
        )
    elif isinstance(behavior, CommonBehavior):
        params = dict(
            delay=behavior.delay,
            cutoff=behavior.cutoff,
            keep_results=behavior.keep_results,
            delay_from_end=False,
        )
    else:
        raise TypeError(f"unknown behavior {behavior!r}")
    op = Operator(
        "window_behavior",
        [with_t],
        params=dict(
            time_idx=names.index("__behavior_t__"),
            start_idx=names.index("_pw_window_start"),
            end_idx=names.index("_pw_window_end"),
            **params,
        ),
    )
    return Table._new(op, with_t.schema, Universe())


def _assign_intervals_over(
    table: Table, time_e, instance_e, window: IntervalsOverWindow
) -> Table:
    """One assigned row per (probe, matching data row); probes without
    matches survive as empty windows when ``is_outer`` (the reference's
    outer interval join, _window.py:793)."""
    from ...internals.joins import JoinMode
    from ._joins import interval, interval_join

    probes = window.at.table
    how = JoinMode.LEFT if window.is_outer else JoinMode.INNER
    res = interval_join(
        probes, table, window.at, time_e,
        interval(window.lower_bound, window.upper_bound), how=how,
    )
    at_ref = window.at
    lb, ub = window.lower_bound, window.upper_bound
    exprs: dict[str, Any] = {n: table[n] for n in table.column_names()}
    exprs["__iv_time__"] = time_e
    exprs["_pw_window_location"] = at_ref
    exprs["_pw_window"] = at_ref
    exprs["_pw_window_start"] = at_ref + lb
    exprs["_pw_window_end"] = at_ref + ub
    exprs["_pw_instance"] = (
        instance_e if instance_e is not None else ApplyExpression(lambda v: 0, dt.INT, at_ref)
    )
    return res.select(**exprs)


def _assign_session(table: Table, time_e, instance_e, window: SessionWindow) -> Table:
    """Sessions are merged per instance from the full sorted multiset —
    the differential recompute the reference performs in its session window
    operator."""
    inst = instance_e if instance_e is not None else 0
    base = table.with_columns(__t__=time_e, __inst__=inst)
    merged = base.groupby(base["__inst__"]).reduce(
        base["__inst__"],
        __spans__=pw.apply_with_type(
            lambda pairs: tuple(window.merge(list(pairs))),
            tuple,
            pw.reducers.sorted_tuple(pw.make_tuple(base["__t__"], base.id)),
        ),
    )
    flat = merged.flatten(merged["__spans__"])
    spans = flat._select_exprs(
        {
            "__start__": flat["__spans__"].get(0),
            "__end__": flat["__spans__"].get(1),
            "__rid__": flat["__spans__"].get(2),
            "__inst2__": flat["__inst__"],
        },
        universe=flat._universe,
    )
    spans = spans.with_id(spans["__rid__"])
    spans = spans.promise_universes_are_equal(table)
    joined = table.with_universe_of(spans)
    assigned = joined._select_exprs(
        {
            **{n: joined[n] for n in table.column_names()},
            "_pw_window_start": spans["__start__"],
            "_pw_window_end": spans["__end__"],
            "_pw_window": pw.make_tuple(spans["__start__"], spans["__end__"]),
            "_pw_instance": spans["__inst2__"],
        },
        universe=joined._universe,
    )
    return assigned
