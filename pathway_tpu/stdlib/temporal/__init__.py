"""Temporal stdlib: windows, temporal behaviors, asof-now joins.

reference: python/pathway/stdlib/temporal/ (~5600 LoC: _window.py:863
``windowby``, _asof_now_join.py:403, _interval_join.py, _asof_join.py,
_window_join.py, temporal_behavior.py).

Example — tumbling-window aggregation:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... t  | v
    ... 1  | 10
    ... 3  | 20
    ... 11 | 5
    ... ''')
    >>> r = t.windowby(t.t, window=pw.temporal.tumbling(duration=10)).reduce(
    ...     start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v))
    >>> pw.debug.compute_and_print(r, include_id=False)
    start | s
    0 | 30
    10 | 5
"""

from ._window import (
    Window,
    intervals_over,
    tumbling,
    sliding,
    session,
    windowby,
)
from .temporal_behavior import common_behavior, exactly_once_behavior, Behavior
from ._asof_now_join import asof_now_join, asof_now_join_inner, asof_now_join_left
from ._joins import (
    AsofDirection,
    AsofJoinResult,
    IntervalJoinResult,
    WindowJoinResult,
    asof_join,
    asof_join_left,
    asof_join_outer,
    asof_join_right,
    interval,
    interval_join,
    interval_join_inner,
    interval_join_left,
    interval_join_outer,
    interval_join_right,
    window_join,
    window_join_inner,
    window_join_left,
    window_join_outer,
    window_join_right,
)

__all__ = [
    "Window",
    "intervals_over",
    "tumbling",
    "sliding",
    "session",
    "windowby",
    "common_behavior",
    "exactly_once_behavior",
    "Behavior",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "AsofJoinResult",
    "IntervalJoinResult",
    "WindowJoinResult",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
    "interval",
    "AsofDirection",
]
