"""Live visualization (reference: python/pathway/stdlib/viz/ — bokeh/panel
plots over streaming tables, ``table.plot`` / ``table.show``).

The bokeh/panel stack is optional; without it the helpers degrade to a
textual snapshot so notebooks in this image still get output.
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals.table import Table

__all__ = ["plot", "show", "table_viz"]


def _try_panel():
    try:
        import bokeh  # noqa: F401
        import panel  # noqa: F401

        return True
    except ImportError:
        return False


def plot(table: Table, plotting_function: Callable, sorting_col=None) -> Any:
    """Live bokeh plot of a streaming table
    (reference: stdlib/viz plot — updates as diffs arrive)."""
    if not _try_panel():
        raise ImportError(
            "table.plot requires bokeh + panel; neither is installed in "
            "this image — use pw.debug.compute_and_print or pw.io.subscribe"
        )
    import bokeh.models
    import panel as pn

    source = bokeh.models.ColumnDataSource(data={n: [] for n in table.column_names()})
    fig = plotting_function(source)
    import pathway_tpu as pw

    def on_change(key, row, time, is_addition):
        if is_addition:
            source.stream({n: [row[n]] for n in table.column_names()})

    pw.io.subscribe(table, on_change=on_change)
    return pn.pane.Bokeh(fig)


def show(table: Table, *, include_id: bool = True, short_pointers: bool = True) -> Any:
    """Notebook widget of the table's current state; plain print fallback
    (reference: stdlib/viz show / table_viz)."""
    if _try_panel():
        import panel as pn

        import pathway_tpu.debug as dbg

        df = dbg.table_to_pandas(table)
        return pn.widgets.DataFrame(df)
    import pathway_tpu.debug as dbg

    dbg.compute_and_print(
        table, include_id=include_id, short_pointers=short_pointers
    )
    return None


table_viz = show
