from . import indexing, ml, temporal, stateful, graphs, utils, statistical, ordered, viz

__all__ = [
    "indexing",
    "ml",
    "temporal",
    "stateful",
    "graphs",
    "utils",
    "statistical",
    "ordered",
    "viz",
]
