from . import indexing, ml, temporal, stateful, graphs, utils, statistical, ordered

__all__ = [
    "indexing",
    "ml",
    "temporal",
    "stateful",
    "graphs",
    "utils",
    "statistical",
    "ordered",
]
