"""Stateful ops (reference: python/pathway/stdlib/stateful/).

``deduplicate`` is exposed as a Table method (internals/table.py) and as a
free function here for parity."""

from ...internals.table import Table

__all__ = ["deduplicate"]


def deduplicate(table: Table, *, value, instance=None, acceptor=None, persistent_id=None) -> Table:
    return table.deduplicate(
        value=value, instance=instance, acceptor=acceptor, persistent_id=persistent_id
    )
