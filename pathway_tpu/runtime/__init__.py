"""Unified device-tick runtime with QoS classes — one token-budget
executor for serving (``INTERACTIVE``), engine-plane embed/rerank/LLM
micro-batches (``LLM_RERANK``) and bulk ingest (``BULK_INGEST``).

See :mod:`pathway_tpu.runtime.executor` for the policy (strict priority
with budget, starvation-bounded minimum shares, WindVE-style per-class
admission control) and README "Operations: unified runtime & QoS
classes" for the operator view.
"""

from .executor import (
    AdmissionRefused,
    DeadlineExceeded,
    DeviceTickRuntime,
    QoS,
    WorkGroup,
    WorkItem,
    budget_chunks,
    configure,
    estimate_tokens,
    get_runtime,
    reset_runtime,
    runtime_enabled,
    runtime_settings,
    runtime_stats_if_active,
)

__all__ = [
    "AdmissionRefused",
    "DeadlineExceeded",
    "DeviceTickRuntime",
    "QoS",
    "WorkGroup",
    "WorkItem",
    "budget_chunks",
    "configure",
    "estimate_tokens",
    "get_runtime",
    "reset_runtime",
    "runtime_enabled",
    "runtime_settings",
    "runtime_stats_if_active",
]
