"""Unified device-tick runtime with QoS classes.

PRs 2/5 grew three independently-built producer/consumer loops that all
compete for the same device: the serving scheduler
(``xpacks/llm/_scheduler.py``), the engine-plane micro-batcher
(``xpacks/llm/_utils.AsyncMicroBatcher``) and the ingest pipeline's
device worker (``xpacks/llm/_ingest.py``).  Each had its own queue, its
own drain policy and its own token budget — so a bulk ingest burst could
stall interactive ``/v1/retrieve`` ticks, and there was no single place
to make ticks mesh-aware or route tiered-index work (ROADMAP item 4).

This module is the ONE executor those planes now submit to.  Every
submission is a :class:`WorkItem` carrying a QoS class, a token
estimate, an optional deadline and an optional request trace; the
executor composes each device tick from the class queues under a
**strict-priority-with-budget** policy:

* classes drain in priority order ``INTERACTIVE > LLM_RERANK >
  GENERATE > BULK_INGEST`` — an interactive query arriving while an
  ingest (or decode) backlog
  is queued rides the very next tick, ahead of every queued ingest
  chunk (preemption at tick granularity; ingest submits tick-sized
  chunks precisely so a tick is never longer than one bounded dispatch);
* each tick has a token budget (``tick_tokens``): higher classes fill
  it first, but every lower class with pending work is guaranteed a
  **starvation-bounded minimum share** (``min_share``, ≥ 1 item per
  tick) so sustained interactive load cannot starve ingest to zero;
* per-class **admission control** follows WindVE's (arXiv:2504.14941)
  CPU↔device queue-depth decoupling: each class has a queue-depth
  target and sheddable submissions beyond it are refused immediately
  with :class:`AdmissionRefused` (HTTP planes map it to
  503 + ``Retry-After``) — backpressure, not collapse.  Engine-plane
  work (no deadline) is exempt: refusing it would error the engine.

Existing guarantees ride along unchanged because they live in the batch
handlers, not the loop: breaker/degraded serving (PR 3) and the
restore gate (PR 6) sit inside ``RetrievePlane._batch``, deadline
shedding keeps the 503+Retry-After contract, traces are stamped with
``queue_wait`` and batch-scoped stage spans exactly as the legacy
scheduler did, and every tick lands in the flight recorder.

Re-entrancy: a submit *from the executor thread itself* (e.g. a rerank
triggered inside a retrieve tick) executes inline and **inherits the
running tick's class and budget** instead of jumping the queue — an
inline ``LLM_RERANK`` submit inside an ``INTERACTIVE`` tick is
accounted to the interactive tick, never enqueued ahead of it
(class-inversion fix, PR 7).

``PATHWAY_RUNTIME=0`` restores the three legacy per-plane loops for
A/B; see README "Operations: unified runtime & QoS classes".

Import discipline: this package sits below ``xpacks`` (the planes import
it, never the reverse) and only pulls the ``internals`` observability
leaves (``metrics_names``, ``flight_recorder``, ``monitoring``'s
provider hook) lazily.
"""

from __future__ import annotations

import asyncio
import enum
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Sequence

__all__ = [
    "QoS",
    "WorkItem",
    "WorkGroup",
    "DeviceTickRuntime",
    "DeadlineExceeded",
    "AdmissionRefused",
    "estimate_tokens",
    "budget_chunks",
    "get_runtime",
    "runtime_enabled",
    "runtime_settings",
    "runtime_stats_if_active",
    "configure",
    "reset_runtime",
]


class QoS(enum.IntEnum):
    """Strict-priority QoS classes (lower value = higher priority)."""

    INTERACTIVE = 0  # latency-critical serving (/v1/retrieve ticks)
    LLM_RERANK = 1   # engine-plane embed/rerank/LLM-guard micro-batches
    GENERATE = 2     # paged-KV decode ticks (token streams tolerate a
                     # bounded inter-token gap; retrieval p99 does not)
    BULK_INGEST = 3  # backlog-tolerant bulk embed→upsert chunks

    @property
    def label(self) -> str:
        return self.name.lower()


#: every class an INTERACTIVE tick may preempt (strict-priority order)
_LOWER_CLASSES = (QoS.LLM_RERANK, QoS.GENERATE, QoS.BULK_INGEST)
#: classes whose "highest nonempty" tick is share-capped so the
#: preemption horizon an arriving query faces stays one short tick —
#: decode steps and ingest chunks are independent dispatches with no
#: cross-item fusion benefit, so a budget-full train only adds latency
_SHARE_CAPPED_CLASSES = (QoS.GENERATE, QoS.BULK_INGEST)


class DeadlineExceeded(Exception):
    """The request was shed: its deadline passed before dispatch.

    ``retry_after_s`` is the server's backoff hint (HTTP ``Retry-After``).
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionRefused(DeadlineExceeded):
    """Admission refused: the class queue is at its depth target."""


def _device_count() -> int | None:
    """How many accelerator devices the executor's ticks dispatch over
    (mesh-sharded ticks fan each dispatch across all of them).  Reported
    only when jax is already imported — a bare stats/health probe must
    not pull in (or initialize) a backend."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return int(jax.device_count())
    except Exception:  # noqa: BLE001 — stats must never raise
        return None


def _active_attention_impl() -> str | None:
    """The process's serving attention impl (most recently built
    encoder), for the runtime stats/health block."""
    try:
        from ..internals.flight_recorder import active_attention_impl

        return active_attention_impl()
    except Exception:  # noqa: BLE001 — stats must never raise
        return None


def estimate_tokens(item: Any) -> int:
    """Cheap token-mass estimate for budget batching: whitespace words
    + CLS/SEP for text (wordpiece splits only lengthen it, which errs on
    the safe — smaller — batch side), 1 for opaque payloads (images)."""
    if isinstance(item, bytes):
        item = item.decode("utf-8", errors="replace")
    if isinstance(item, str):
        return len(item.split()) + 2
    return 1


class WorkGroup:
    """One batchable kind of device work.

    ``batch_fn(list_of_payloads) -> list_of_results`` runs on the
    executor thread; items of the same group drained in one tick execute
    as one call (chunked at ``max_batch`` and, when ``max_tokens`` /
    ``token_estimate`` are set, at that token budget too).

    CONTRACT: a handler must SYNCHRONIZE the device work it dispatches
    (a host read, ``np.asarray``, ``jax.block_until_ready``) before
    returning.  The executor's preemption guarantee is "at most one
    tick in flight on the device" — a handler that returns unfinished
    async dispatches rebuilds the unprioritized device queue this
    runtime exists to replace, and higher-class work submitted next
    tick will silently wait behind the backlog anyway.
    """

    def __init__(
        self,
        label: str,
        batch_fn: Callable[[list], Sequence],
        max_batch: int = 1024,
        max_tokens: int | None = None,
        token_estimate: Callable[[Any], int] | None = None,
    ):
        self.label = label
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_tokens = max_tokens
        self.token_estimate = token_estimate


def budget_chunks(group: Any, items: list["WorkItem"]) -> list[list["WorkItem"]]:
    """Split a tick's items into execute chunks: ``max_batch`` count cap
    plus, when the group declares one (``AsyncMicroBatcher.max_tokens``),
    a token-mass cap so a run of long documents dispatches in
    length-adapted batches.  Every chunk carries at least one item.

    THE budget-chunking implementation — the legacy serving scheduler's
    ``_budget_chunks`` is an alias of this."""
    max_tokens = getattr(group, "max_tokens", None)
    estimate = getattr(group, "token_estimate", None)
    if max_tokens is None or estimate is None:
        return [
            items[start : start + group.max_batch]
            for start in range(0, len(items), group.max_batch)
        ]
    chunks: list[list[WorkItem]] = []
    cur: list[WorkItem] = []
    cur_tokens = 0
    for it in items:
        t = estimate(it.payload)
        if cur and (len(cur) >= group.max_batch or cur_tokens + t > max_tokens):
            chunks.append(cur)
            cur, cur_tokens = [], 0
        cur.append(it)
        cur_tokens += t
    if cur:
        chunks.append(cur)
    return chunks


class WorkItem:
    """One scheduled submission: ``(class, tokens_est, deadline, trace)``
    plus the bookkeeping the executor needs (group, payload, future)."""

    __slots__ = (
        "group", "payload", "qos", "tokens", "future",
        "enqueued_at", "deadline_at", "coalesce_s", "trace", "observer",
        "retry_after_s", "trace_link",
    )

    def __init__(
        self,
        group,
        payload,
        qos: QoS,
        tokens: int,
        future: Future,
        enqueued_at: float,
        deadline_at: float | None,
        coalesce_s: float,
        trace=None,
        observer=None,
        retry_after_s: float | None = None,
        trace_link: tuple[str, str] | None = None,
    ):
        self.group = group
        self.payload = payload
        self.qos = qos
        self.tokens = max(int(tokens), 1)
        self.future = future
        self.enqueued_at = enqueued_at
        self.deadline_at = deadline_at
        #: how long this item is willing to wait for tick-mates (the
        #: legacy per-scheduler ``max_wait_ms``, carried per item now
        #: that the tick cadence is shared; ingest chunks pass 0)
        self.coalesce_s = coalesce_s
        #: sampled RequestTrace riding this item (internals/flight_recorder)
        self.trace = trace
        #: legacy-facade stats observer (``ServingScheduler``) — receives
        #: ``_obs_*`` callbacks so per-facade counters keep working
        self.observer = observer
        #: per-item Retry-After override (the submitting plane's hint);
        #: None uses the runtime default
        self.retry_after_s = retry_after_s
        #: ``(trace_id, parent_span_id)`` of the request that CAUSED this
        #: item — deferred work executes after the request's batch scope
        #: is gone, so the link captured at submit time is the only way
        #: its tick spans stay attributable to the trigger
        self.trace_link = trace_link


#: wait-time histogram bucket upper bounds (milliseconds)
_WAIT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0)
#: items-per-tick histogram buckets
_OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
#: tokens-per-tick histogram buckets
_TICK_TOKEN_BUCKETS = (64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0)
#: lower-class share-of-tick buckets (fractions)
_SHARE_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0)


# one shared warn-and-default parser for the whole repo (also used by
# the serving query-cache knobs)
from ..internals.config import env_float as _env_float  # noqa: E402
from ..internals.config import env_int as _env_int  # noqa: E402


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "off", "no", "")


class DeviceTickRuntime:
    """Token-budget device-tick executor with QoS classes (module doc)."""

    def __init__(
        self,
        *,
        tick_tokens: int = 16384,
        max_batch: int = 256,
        max_wait_ms: float = 5.0,
        retry_after_s: float = 1.0,
        depth: dict[QoS, int] | None = None,
        min_share: dict[QoS, float] | None = None,
        name: str = "runtime",
    ):
        self.tick_tokens = int(tick_tokens)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        #: per-class queue-depth targets (WindVE-style admission control)
        self.depth = {
            QoS.INTERACTIVE: 1024,
            QoS.LLM_RERANK: 4096,
            QoS.GENERATE: 256,
            QoS.BULK_INGEST: 512,
            **(depth or {}),
        }
        #: starvation bound: fraction of the tick budget reserved for a
        #: lower class whenever it has pending work (always ≥ 1 item)
        self.min_share = {
            QoS.INTERACTIVE: 1.0,
            QoS.LLM_RERANK: 0.2,
            QoS.GENERATE: 0.15,
            QoS.BULK_INGEST: 0.1,
            **(min_share or {}),
        }
        self._cv = threading.Condition()
        self._queues: dict[QoS, deque[WorkItem]] = {c: deque() for c in QoS}
        self._pending_tokens: dict[QoS, int] = {c: 0 for c in QoS}
        self._thread: threading.Thread | None = None
        #: class of the tick currently executing (executor thread only) —
        #: inline re-entrant submits inherit it instead of queue-jumping
        self._tick_qos: QoS | None = None
        # metrics — guarded by _mx, not _cv: ticks update them while
        # submitters hold _cv
        from ..internals.metrics_names import Histogram

        self._mx = threading.Lock()
        self._class_counters: dict[QoS, dict[str, int]] = {
            c: {
                "submitted_total": 0,
                "completed_total": 0,
                "failed_total": 0,
                "shed_deadline_total": 0,
                "admission_rejected_total": 0,
                "inline_total": 0,
                "queue_depth_max": 0,
            }
            for c in QoS
        }
        self._wait_hist: dict[QoS, Any] = {
            c: Histogram(_WAIT_BUCKETS_MS) for c in QoS
        }
        self._ticks_total = 0
        self._preemptions_total = 0
        self._occupancy_hist = Histogram(_OCCUPANCY_BUCKETS)
        self._tick_tokens_hist = Histogram(_TICK_TOKEN_BUCKETS)
        self._share_hist = Histogram(_SHARE_BUCKETS)
        from ..internals.monitoring import register_metrics_provider

        # replace=False: an ad-hoc instance must not steal (and, being
        # weakly held, later delete) an established registration under
        # the same name — the process-global runtime re-registers
        # authoritatively in get_runtime()
        register_metrics_provider(name, self, replace=False)

    # -- submission ------------------------------------------------------
    def queue_depth(self, qos: QoS) -> int:
        """Current queued (not yet drained) items of one class — the
        WindVE-style pressure signal the serving cache stack's
        collaborative CPU embed path keys on.  A GIL-atomic ``len`` read:
        no lock, never spawns the executor thread."""
        return len(self._queues[QoS(qos)])

    def on_runtime_thread(self) -> bool:
        return (
            self._thread is not None
            and threading.current_thread() is self._thread
        )

    def submit(
        self,
        group: Any,
        payload: Any,
        *,
        qos: QoS = QoS.INTERACTIVE,
        deadline_s: float | None = None,
        sheddable: bool | None = None,
        trace: Any = None,
        tokens: int | None = None,
        coalesce_s: float | None = None,
        observer: Any = None,
        retry_after_s: float | None = None,
        defer: bool = False,
        trace_link: tuple[str, str] | None = None,
    ) -> Future:
        """Enqueue one payload under a QoS class; the future resolves
        when its batch ran.

        ``deadline_s`` is a relative budget: if the item is still queued
        that long after submission it is shed with
        :class:`DeadlineExceeded` and its work never executes.  ``None``
        (engine-plane work) is never shed.

        ``sheddable`` work (default: anything with a deadline) is
        additionally subject to the class's queue-depth target.  Engine
        and ingest planes are exempt: refusing their work would error
        the engine, and their volume is bounded upstream (engine batch
        sizes, the ingest pipeline's hand-off depth).

        ``tokens`` overrides the estimate used for tick-budget
        composition (``group.token_estimate`` / :func:`estimate_tokens`
        otherwise).  ``coalesce_s`` is how long the item will wait for
        tick-mates (default: the runtime's ``max_wait_ms``).

        ``defer=True`` marks FIRE-AND-FORGET work: a submit from the
        executor thread itself ENQUEUES for a later tick instead of
        running inline.  The inline shortcut exists for handlers that
        block on the returned future (a queued item could never drain
        while the loop is inside the current tick); background work
        nobody waits on inside the tick — e.g. a tier-migration batch
        triggered by a serving search — must NOT ride the triggering
        tick's class/budget, or an INTERACTIVE query pays for
        BULK_INGEST work in its own latency.  Never block on a
        defer=True future from a batch handler.
        """
        qos = QoS(qos)
        if sheddable is None:
            sheddable = deadline_s is not None
        if trace is not None and not trace.sampled:
            trace = None
        if defer and trace_link is None:
            # deferred work submitted from inside a request's batch scope
            # (query-cache refresh, tier migration) would otherwise start
            # trace-orphaned — capture the triggering request's span now,
            # while the scope still exists
            from ..internals.flight_recorder import current_trace_link

            trace_link = current_trace_link()
        if tokens is None:
            estimate = getattr(group, "token_estimate", None)
            tokens = (estimate or estimate_tokens)(payload)
        fut: Future = Future()
        if self.on_runtime_thread() and not defer:
            # re-entrant submit from inside a batch handler (e.g. a
            # rerank fired by a retrieve handler): run inline — a queued
            # item could never drain while the loop is inside this very
            # tick.  The work inherits the RUNNING tick's class and
            # budget instead of jumping the queue: an inline LLM_RERANK
            # inside an INTERACTIVE tick is interactive-tick work, and
            # an inline INTERACTIVE inside a BULK_INGEST tick must not
            # let ingest impersonate the interactive class.
            tick_qos = self._tick_qos if self._tick_qos is not None else qos
            with self._mx:
                self._class_counters[qos]["inline_total"] += 1
            item = WorkItem(
                group, payload, tick_qos, tokens, fut,
                time.monotonic(), None, 0.0, trace, observer, retry_after_s,
                trace_link,
            )
            self._execute(group, [item], tick_qos, inline=True)
            return fut
        now = time.monotonic()
        item = WorkItem(
            group,
            payload,
            qos,
            tokens,
            fut,
            now,
            None if deadline_s is None else now + deadline_s,
            self.max_wait_ms / 1000.0 if coalesce_s is None else coalesce_s,
            trace,
            observer,
            retry_after_s,
            trace_link,
        )
        refused = False
        with self._cv:
            if sheddable and len(self._queues[qos]) >= self.depth[qos]:
                refused = True
            else:
                self._ensure_thread()
                if observer is not None:
                    # BEFORE the item becomes visible to the tick thread:
                    # with a 0-coalesce window the drain (and its
                    # _obs_drained) can otherwise run before the
                    # enqueue hook, driving the facade's pending count
                    # negative and weakening its admission cap.  Safe
                    # under _cv: no caller holds the observer's lock
                    # across a submit.
                    observer._obs_enqueued()
                self._queues[qos].append(item)
                self._pending_tokens[qos] += item.tokens
                depth = len(self._queues[qos])
                self._cv.notify_all()
        if refused:
            with self._mx:
                self._class_counters[qos]["admission_rejected_total"] += 1
            fut.set_exception(
                AdmissionRefused(
                    f"runtime {qos.label} queue full "
                    f"({self.depth[qos]} pending)",
                    retry_after_s=(
                        self.retry_after_s
                        if retry_after_s is None
                        else retry_after_s
                    ),
                )
            )
            if observer is not None:
                observer._obs_refused()
            return fut
        with self._mx:
            c = self._class_counters[qos]
            c["submitted_total"] += 1
            if depth > c["queue_depth_max"]:
                c["queue_depth_max"] = depth
        return fut

    async def submit_async(self, group: Any, payload: Any, **kwargs: Any) -> Any:
        return await asyncio.wrap_future(self.submit(group, payload, **kwargs))

    # -- device-tick loop ------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=f"pw-{self.name}-tick"
            )
            self._thread.start()

    def _pending_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _should_flush_locked(self) -> bool:
        if any(len(q) >= self.max_batch for q in self._queues.values()):
            return True
        return sum(self._pending_tokens.values()) >= self.tick_tokens

    def _window_s_locked(self) -> float:
        """Admission window for the next tick: the largest coalesce wish
        among the class-queue HEADS (a lone 0-coalesce ingest chunk
        flushes immediately; a facade configured with max_wait_ms=80
        keeps its legacy window).  Heads only — scanning every queued
        item would hold ``_cv`` for O(backlog) per tick, and a plane
        submits one coalesce value for all its items anyway (the head
        is its oldest)."""
        window = 0.0
        for q in self._queues.values():
            if q and q[0].coalesce_s > window:
                window = q[0].coalesce_s
        return window

    def _loop(self) -> None:
        while True:
            with self._cv:
                while self._pending_locked() == 0:
                    self._cv.wait()
                # admission window: from the first pending item, wait for
                # concurrent requests to join the tick, flushing early on
                # max_batch / a full token budget
                flush_at = time.monotonic() + self._window_s_locked()
                while not self._should_flush_locked():
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                items, tick_stats = self._compose_tick_locked()
            if not items:
                continue
            try:
                self._run_tick(items, tick_stats)
            except BaseException as exc:  # noqa: BLE001 — the loop must
                # survive; per-item errors are already routed to futures in
                # _execute, so anything landing here is a harness bug: fail
                # the unresolved items with the ACTUAL exception (a generic
                # wrapper would make the defect undiagnosable)
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(exc)

    def _compose_tick_locked(self) -> tuple[list[WorkItem], dict]:
        """Strict priority with budget + starvation-bounded reservations
        (see module docstring).  Returns (items, accounting).

        Only the HIGHEST nonempty class fills the tick (that is where
        coalescing pays — concurrent queries fuse into one dispatch);
        every lower class gets exactly its reserved minimum share
        (≥ 1 item).  Backfilling lower-class work into a tick's leftover
        budget would only lengthen the tick — bulk chunks are
        independent dispatches with no cross-item fusion benefit, and
        every extra one pushes the next interactive arrival's wait out
        by a full dispatch (measured: leftover-backfill inflated
        contended p99 ~2× over the legacy loops; share-capped
        composition is what makes preemption at tick granularity real).
        A BULK_INGEST-only tick is likewise capped at the class's share
        so the preemption horizon an arriving query faces is one short
        tick, never a budget-full train of chunks — back-to-back ticks
        keep idle-device ingest throughput identical."""
        reserved: dict[QoS, int] = {}
        for c in _LOWER_CLASSES:
            if self._queues[c] and self.min_share.get(c, 0.0) > 0.0:
                reserved[c] = max(1, int(self.min_share[c] * self.tick_tokens))
        lower_pending_at_start = {
            c: bool(self._queues[c]) for c in _LOWER_CLASSES
        }
        highest = next((c for c in QoS if self._queues[c]), None)
        take: list[WorkItem] = []
        per_class = {c: [0, 0] for c in QoS}  # class -> [count, tokens]
        remaining = self.tick_tokens
        for c in QoS:
            q = self._queues[c]
            guaranteed = reserved.pop(c, 0)
            if not q:
                continue
            if c == highest and c not in _SHARE_CAPPED_CLASSES:
                allowed = remaining - sum(reserved.values())
            elif c == highest:
                # decode/bulk-only tick: one share's worth, then recompose
                # — the horizon for a preempting query stays one short tick
                allowed = max(
                    guaranteed,
                    max(1, int(self.min_share.get(c, 0.0) * self.tick_tokens)),
                )
            else:
                allowed = guaranteed
            used = count = 0
            while q and count < self.max_batch:
                tok = q[0].tokens
                if count and used + tok > allowed:
                    break
                if not count and allowed <= 0:
                    break
                item = q.popleft()
                self._pending_tokens[c] -= item.tokens
                take.append(item)
                used += tok
                count += 1
            remaining -= used
            per_class[c] = [count, used]
        leftover = {c: len(self._queues[c]) for c in QoS}
        return take, {
            "per_class": per_class,
            "leftover": leftover,
            "lower_pending_at_start": lower_pending_at_start,
        }

    def _run_tick(self, items: list[WorkItem], tick_stats: dict) -> None:
        now = time.monotonic()
        tick_wall = time.time()
        tick_t0 = time.monotonic()
        live_groups: dict[int, tuple[Any, list[WorkItem]]] = {}
        live_tokens = 0
        for it in items:  # already in priority+submission order
            wait_ms = (now - it.enqueued_at) * 1000.0
            with self._mx:
                self._wait_hist[it.qos].observe(wait_ms)
            obs = it.observer
            if obs is not None:
                obs._obs_wait(wait_ms)
                obs._obs_drained()
            if it.trace is not None:
                it.trace.add_stage_mono("queue_wait", it.enqueued_at, now)
            if it.deadline_at is not None and now > it.deadline_at:
                with self._mx:
                    self._class_counters[it.qos]["shed_deadline_total"] += 1
                if obs is not None:
                    obs._obs_shed_deadline()
                if not it.future.done():  # client may have cancelled
                    it.future.set_exception(
                        DeadlineExceeded(
                            "deadline exceeded before dispatch "
                            f"(queued {wait_ms:.1f} ms)",
                            retry_after_s=(
                                self.retry_after_s
                                if it.retry_after_s is None
                                else it.retry_after_s
                            ),
                        )
                    )
            else:
                live_groups.setdefault(id(it.group), (it.group, []))[1].append(it)
                live_tokens += it.tokens
        per_class = tick_stats["per_class"]
        # a tick that carries interactive work while lower-class work
        # stays queued behind it preempted that work at tick granularity
        preempted = per_class[QoS.INTERACTIVE][0] > 0 and any(
            tick_stats["leftover"][c] > 0 for c in _LOWER_CLASSES
        )
        with self._mx:
            self._ticks_total += 1
            if preempted:
                self._preemptions_total += 1
            self._occupancy_hist.observe(float(len(items)))
            self._tick_tokens_hist.observe(float(live_tokens))
            if per_class[QoS.INTERACTIVE][0] > 0 and (
                tick_stats["lower_pending_at_start"][QoS.BULK_INGEST]
                or per_class[QoS.BULK_INGEST][0] > 0
            ):
                # observed share of a contended tick granted to bulk
                # ingest — the starvation bound made measurable
                total = sum(t for _n, t in per_class.values()) or 1
                self._share_hist.observe(
                    per_class[QoS.BULK_INGEST][1] / total
                )
        for group, gitems in live_groups.values():
            for chunk in budget_chunks(group, gitems):
                self._execute(group, chunk, chunk[0].qos)
        from ..internals.flight_recorder import record_span

        record_span(
            "tick:runtime",
            "runtime",
            tick_wall,
            (time.monotonic() - tick_t0) * 1000.0,
            attrs={
                "occupancy": len(items),
                "tokens": live_tokens,
                "preempted": preempted,
                **{
                    c.label: per_class[c][0]
                    for c in QoS
                    if per_class[c][0]
                },
            },
        )

    def _execute(
        self,
        group: Any,
        chunk: list[WorkItem],
        qos: QoS,
        inline: bool = False,
    ) -> None:
        if not chunk:
            return
        from ..internals.flight_recorder import batch_traces, record_span

        obs = chunk[0].observer
        if obs is not None:
            obs._obs_batch(len(chunk))
        # honor the plane's dispatch lock: build-time probes may call the
        # model off-thread while the loop runs
        lock = getattr(group, "_dispatch_lock", None)
        traces = [it.trace for it in chunk if it.trace is not None]
        tick_wall = time.time()
        tick_t0 = time.monotonic()
        prev_qos = self._tick_qos
        self._tick_qos = qos
        ok = True
        try:
            from ..testing import faults

            if faults.enabled:
                # chaos site "scheduler.step": a failed device step fans
                # out to the batch's waiters like any handler error
                faults.perturb("scheduler.step")
            # batch-scope the riding traces: the handler's stage timers
            # (embed, search) stamp onto every request in the tick
            with batch_traces(traces):
                if lock is not None:
                    with lock:
                        results = group.batch_fn([it.payload for it in chunk])
                else:
                    results = group.batch_fn([it.payload for it in chunk])
            if len(results) != len(chunk):
                raise RuntimeError(
                    f"batch handler {group.label!r} returned {len(results)} "
                    f"results for {len(chunk)} items"
                )
        except BaseException as exc:  # noqa: BLE001 — propagate to every waiter
            ok = False
            with self._mx:
                self._class_counters[qos]["failed_total"] += len(chunk)
            if obs is not None:
                obs._obs_done(len(chunk), ok=False)
            for it in chunk:
                if not it.future.done():
                    it.future.set_exception(exc)
            return
        finally:
            self._tick_qos = prev_qos
            attrs = {
                "runtime": self.name,
                "qos": qos.label,
                "occupancy": len(chunk),
                "ok": ok,
            }
            if inline:
                attrs["inline"] = True
            dur_ms = (time.monotonic() - tick_t0) * 1000.0
            # deferred items carry the (trace_id, span_id) of the request
            # that caused them: record the tick span once per distinct
            # triggering trace so the stitched tree shows the background
            # work under its requester, and once unlinked otherwise
            links: list[tuple[str, str]] = []
            for it in chunk:
                if it.trace_link is not None and it.trace_link not in links:
                    links.append(it.trace_link)
            if links:
                from ..internals.flight_recorder import new_span_id

                for tid, parent in links:
                    record_span(
                        f"tick:{group.label}",
                        "scheduler",
                        tick_wall,
                        dur_ms,
                        trace_id=tid,
                        span_id=new_span_id(),
                        parent_id=parent,
                        attrs={**attrs, "deferred": True},
                    )
            else:
                record_span(
                    f"tick:{group.label}",
                    "scheduler",
                    tick_wall,
                    dur_ms,
                    attrs=attrs,
                )
        with self._mx:
            self._class_counters[qos]["completed_total"] += len(chunk)
        if obs is not None:
            obs._obs_done(len(chunk), ok=True)
        for it, res in zip(chunk, results):
            if not it.future.done():
                it.future.set_result(res)

    # -- observability ---------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._cv:
            depths = {c.label: len(self._queues[c]) for c in QoS}
        with self._mx:
            classes = {
                c.label: {
                    **self._class_counters[c],
                    "queue_depth": depths[c.label],
                    "wait_ms_sum": self._wait_hist[c].sum,
                    "wait_ms_count": self._wait_hist[c].count,
                }
                for c in QoS
            }
            return {
                "classes": classes,
                "ticks_total": self._ticks_total,
                "preemptions_total": self._preemptions_total,
                "tick_occupancy_mean": (
                    self._occupancy_hist.sum / self._occupancy_hist.count
                    if self._occupancy_hist.count
                    else 0.0
                ),
                "tick_tokens_mean": (
                    self._tick_tokens_hist.sum / self._tick_tokens_hist.count
                    if self._tick_tokens_hist.count
                    else 0.0
                ),
                "bulk_share_mean": (
                    self._share_hist.sum / self._share_hist.count
                    if self._share_hist.count
                    else None
                ),
                "tick_tokens_budget": self.tick_tokens,
                "min_share": {c.label: self.min_share[c] for c in QoS},
                "depth_targets": {c.label: self.depth[c] for c in QoS},
                "devices": _device_count(),
                # which attention kernel the tick's embed work runs on
                # (PATHWAY_ATTENTION_IMPL observable; None = no encoder
                # built in this process yet)
                "attention_impl": _active_attention_impl(),
            }

    def openmetrics_lines(self) -> list[str]:
        """``pathway_runtime_*`` series for the /status endpoint."""
        from ..internals.metrics_names import escape_label_value
        # (mesh-sharded tick series live with the sharded index itself —
        # parallel/index.py's provider — since a tick is mesh-wide work
        # regardless of which QoS class submitted it)

        with self._cv:
            depths = {c: len(self._queues[c]) for c in QoS}
        lines: list[str] = []
        with self._mx:
            per_class_metrics = (
                ("submitted_total", "counter"),
                ("completed_total", "counter"),
                ("failed_total", "counter"),
                ("shed_deadline_total", "counter"),
                ("admission_rejected_total", "counter"),
                ("inline_total", "counter"),
                ("queue_depth_max", "gauge"),
            )
            for metric, kind in per_class_metrics:
                lines.append(f"# TYPE pathway_runtime_{metric} {kind}")
                for c in QoS:
                    lbl = f'qos="{escape_label_value(c.label)}"'
                    lines.append(
                        f"pathway_runtime_{metric}{{{lbl}}} "
                        f"{self._class_counters[c][metric]}"
                    )
            lines.append("# TYPE pathway_runtime_queue_depth gauge")
            for c in QoS:
                lbl = f'qos="{escape_label_value(c.label)}"'
                lines.append(
                    f"pathway_runtime_queue_depth{{{lbl}}} {depths[c]}"
                )
            lines.append("# TYPE pathway_runtime_ticks_total counter")
            lines.append(f"pathway_runtime_ticks_total {self._ticks_total}")
            lines.append("# TYPE pathway_runtime_preemptions_total counter")
            lines.append(
                f"pathway_runtime_preemptions_total {self._preemptions_total}"
            )
            lines.append("# TYPE pathway_runtime_wait_ms histogram")
            for c in QoS:
                lbl = f'qos="{escape_label_value(c.label)}"'
                lines.extend(
                    self._wait_hist[c].openmetrics_lines(
                        "pathway_runtime_wait_ms", lbl
                    )
                )
            lines.append("# TYPE pathway_runtime_tick_occupancy histogram")
            lines.extend(
                self._occupancy_hist.openmetrics_lines(
                    "pathway_runtime_tick_occupancy"
                )
            )
            lines.append("# TYPE pathway_runtime_tick_tokens histogram")
            lines.extend(
                self._tick_tokens_hist.openmetrics_lines(
                    "pathway_runtime_tick_tokens"
                )
            )
            lines.append("# TYPE pathway_runtime_starvation_share histogram")
            lines.extend(
                self._share_hist.openmetrics_lines(
                    "pathway_runtime_starvation_share"
                )
            )
        return lines


# ---------------------------------------------------------------------------
# process-global runtime + settings (compat shims read the legacy
# PATHWAY_SERVING_* knobs when the PATHWAY_RUNTIME_* ones are unset)
# ---------------------------------------------------------------------------

_SETTINGS: dict[str, Any] = {
    "enabled": _env_flag("PATHWAY_RUNTIME", True),
    "tick_tokens": _env_int("PATHWAY_RUNTIME_TICK_TOKENS", 16384),
    "max_batch": _env_int(
        "PATHWAY_RUNTIME_MAX_BATCH",
        _env_int("PATHWAY_SERVING_MAX_BATCH", 256),
    ),
    "max_wait_ms": _env_float(
        "PATHWAY_RUNTIME_MAX_WAIT_MS",
        _env_float("PATHWAY_SERVING_MAX_WAIT_MS", 5.0),
    ),
    "retry_after_s": _env_float(
        "PATHWAY_RUNTIME_RETRY_AFTER_S",
        _env_float("PATHWAY_SERVING_RETRY_AFTER_S", 1.0),
    ),
    "depth": {
        QoS.INTERACTIVE: _env_int(
            "PATHWAY_RUNTIME_DEPTH_INTERACTIVE",
            _env_int("PATHWAY_SERVING_MAX_QUEUE", 1024),
        ),
        QoS.LLM_RERANK: _env_int("PATHWAY_RUNTIME_DEPTH_LLM_RERANK", 4096),
        QoS.GENERATE: _env_int("PATHWAY_RUNTIME_DEPTH_GENERATE", 256),
        QoS.BULK_INGEST: _env_int("PATHWAY_RUNTIME_DEPTH_BULK_INGEST", 512),
    },
    "min_share": {
        QoS.INTERACTIVE: 1.0,
        QoS.LLM_RERANK: _env_float("PATHWAY_RUNTIME_MIN_SHARE_LLM_RERANK", 0.2),
        QoS.GENERATE: _env_float("PATHWAY_RUNTIME_MIN_SHARE_GENERATE", 0.15),
        QoS.BULK_INGEST: _env_float(
            "PATHWAY_RUNTIME_MIN_SHARE_BULK_INGEST", 0.1
        ),
    },
}
_GLOBAL_LOCK = threading.Lock()
_GLOBAL: DeviceTickRuntime | None = None


def runtime_enabled() -> bool:
    return bool(_SETTINGS["enabled"])


def runtime_settings() -> dict[str, Any]:
    out = dict(_SETTINGS)
    out["depth"] = dict(_SETTINGS["depth"])
    out["min_share"] = dict(_SETTINGS["min_share"])
    return out


def configure(**kwargs: Any) -> None:
    """Adjust the global runtime policy (``enabled``, ``tick_tokens``,
    ``max_batch``, ``max_wait_ms``, ``retry_after_s``, ``depth``,
    ``min_share``).  ``depth``/``min_share`` take partial ``{QoS: value}``
    dicts and merge.  Live knobs apply to the already-running global
    runtime too."""
    unknown = set(kwargs) - set(_SETTINGS)
    if unknown:
        raise TypeError(f"unknown runtime settings: {sorted(unknown)}")
    for key, value in kwargs.items():
        if key in ("depth", "min_share"):
            _SETTINGS[key] = {
                **_SETTINGS[key],
                **{QoS(k): v for k, v in value.items()},
            }
        else:
            _SETTINGS[key] = value
    with _GLOBAL_LOCK:
        rt = _GLOBAL
    if rt is None:
        return
    for knob in ("tick_tokens", "max_batch", "max_wait_ms", "retry_after_s"):
        if knob in kwargs:
            setattr(rt, knob, kwargs[knob])
    if "depth" in kwargs:
        rt.depth = {**rt.depth, **{QoS(k): v for k, v in kwargs["depth"].items()}}
    if "min_share" in kwargs:
        rt.min_share = {
            **rt.min_share,
            **{QoS(k): v for k, v in kwargs["min_share"].items()},
        }


def get_runtime() -> DeviceTickRuntime:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DeviceTickRuntime(
                tick_tokens=_SETTINGS["tick_tokens"],
                max_batch=_SETTINGS["max_batch"],
                max_wait_ms=_SETTINGS["max_wait_ms"],
                retry_after_s=_SETTINGS["retry_after_s"],
                depth=dict(_SETTINGS["depth"]),
                min_share=dict(_SETTINGS["min_share"]),
            )
            # the global runtime is the authoritative "runtime" metrics
            # provider — claim the name even if an ad-hoc instance
            # registered first
            from ..internals.monitoring import register_metrics_provider

            register_metrics_provider(_GLOBAL.name, _GLOBAL)
        return _GLOBAL


def runtime_stats_if_active() -> dict[str, Any] | None:
    """The global runtime's stats WITHOUT creating it — health/status
    surfaces call this so a process that never used the runtime does not
    spawn its thread just by being probed."""
    with _GLOBAL_LOCK:
        rt = _GLOBAL
    return None if rt is None else rt.stats()


def runtime_capacity_if_active() -> dict[str, Any] | None:
    """Lean occupancy view for the ``/v1/health`` ``"capacity"`` block
    (observability/hbm_ledger.capacity_status): per-class queue depth +
    depth targets + the tick token budget — the admission headroom a
    fleet router compares across replicas.  Lock-light (GIL-atomic len
    reads) and never spawns the executor thread."""
    with _GLOBAL_LOCK:
        rt = _GLOBAL
    if rt is None:
        return None
    return {
        "queue_depth": {c.label: len(rt._queues[c]) for c in QoS},
        "depth_targets": {c.label: rt.depth[c] for c in QoS},
        "tick_tokens_budget": rt.tick_tokens,
        "ticks_total": rt._ticks_total,
    }


def reset_runtime() -> None:
    """Test-isolation hook: forget the process-global runtime (its
    daemon thread parks forever on an abandoned condition variable)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None
