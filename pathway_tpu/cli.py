"""``pathway spawn`` — multi-process launcher.

reference: python/pathway/cli.py (320 LoC) — ``spawn --threads --processes``
(:60-110 setting PATHWAY_* envs + one subprocess.Popen per process) and
``spawn-from-env``.

Usage::

    python -m pathway_tpu spawn --threads 2 --processes 2 python app.py
    python -m pathway_tpu spawn-from-env python app.py   # reads PATHWAY_SPAWN_ARGS

Each spawned process gets PATHWAY_PROCESS_ID/PATHWAY_PROCESSES/
PATHWAY_THREADS/PATHWAY_FIRST_PORT; process 0 inherits stdio.  The host
plane shards sources by these (internals/config.py); the device plane
sizes its mesh from jax.device_count, not from the env.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["main", "spawn_program"]


def spawn_program(
    threads: int,
    processes: int,
    first_port: int,
    program: str,
    arguments: list[str],
    env: dict | None = None,
) -> int:
    """reference: cli.py:92-109 — N processes, shared env, wait for all."""
    base_env = dict(env or os.environ)
    base_env.update(
        {
            "PATHWAY_THREADS": str(threads),
            "PATHWAY_PROCESSES": str(processes),
            "PATHWAY_FIRST_PORT": str(first_port),
        }
    )
    procs: list[subprocess.Popen] = []
    try:
        for pid in range(processes):
            penv = dict(base_env)
            penv["PATHWAY_PROCESS_ID"] = str(pid)
            procs.append(subprocess.Popen([program, *arguments], env=penv))
        exit_code = 0
        for p in procs:
            code = p.wait()
            if code:
                exit_code = code
        return exit_code
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait()
        return 130


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("spawn", help="run a program over N processes x M threads")
    sp.add_argument("--threads", "-t", type=int, default=1)
    sp.add_argument("--processes", "-n", type=int, default=1)
    sp.add_argument("--first-port", type=int, default=10000)
    sp.add_argument("--record", action="store_true", help="persist inputs while running")
    sp.add_argument("--record-path", default="record")
    sp.add_argument("program")
    sp.add_argument("arguments", nargs=argparse.REMAINDER)

    se = sub.add_parser(
        "spawn-from-env",
        help="like spawn, with arguments taken from PATHWAY_SPAWN_ARGS",
    )
    se.add_argument("program")
    se.add_argument("arguments", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)

    if args.command == "spawn":
        env = dict(os.environ)
        if args.record:
            env["PATHWAY_PERSISTENT_STORAGE"] = args.record_path
        return spawn_program(
            args.threads, args.processes, args.first_port,
            args.program, args.arguments, env,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "").split()
        ns = parser.parse_args(["spawn", *spawn_args, args.program, *args.arguments])
        return spawn_program(
            ns.threads, ns.processes, ns.first_port, ns.program, ns.arguments
        )
    return 2


if __name__ == "__main__":
    sys.exit(main())
