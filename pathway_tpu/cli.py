"""``pathway spawn`` — multi-process launcher.

reference: python/pathway/cli.py (320 LoC) — ``spawn --threads --processes``
(:60-110 setting PATHWAY_* envs + one subprocess.Popen per process) and
``spawn-from-env``.

Usage::

    python -m pathway_tpu spawn --threads 2 --processes 2 python app.py
    python -m pathway_tpu spawn-from-env python app.py   # reads PATHWAY_SPAWN_ARGS

Each spawned process gets PATHWAY_PROCESS_ID/PATHWAY_PROCESSES/
PATHWAY_THREADS/PATHWAY_FIRST_PORT; process 0 inherits stdio.  The host
plane shards sources by these (internals/config.py); the device plane
sizes its mesh from jax.device_count, not from the env.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["main", "spawn_program"]


def checkout_repository(
    repository_url: str, branch: str | None
) -> str:
    """Clone ``repository_url`` (any git URL, incl. ``file://`` and local
    paths) into a temp dir and return its path
    (reference: cli.py:34-50 ``checkout_repository``).  If the repo
    carries a ``requirements.txt``, a private venv is built for it and
    the spawned program runs on that interpreter."""
    import tempfile

    root = tempfile.mkdtemp(prefix="pathway-spawn-")
    repo_path = os.path.join(root, "repository")
    clone = subprocess.run(
        ["git", "clone", "--quiet", repository_url, repo_path],
        capture_output=True,
        text=True,
    )
    if clone.returncode != 0:
        raise RuntimeError(f"git clone failed: {clone.stderr.strip()}")
    if branch:
        co = subprocess.run(
            ["git", "-C", repo_path, "checkout", "--quiet", branch],
            capture_output=True,
            text=True,
        )
        if co.returncode != 0:
            raise RuntimeError(f"git checkout failed: {co.stderr.strip()}")
    return repo_path


def _venv_python(repo_path: str) -> str | None:
    """Build a venv + install the repo's requirements, when present
    (reference: cli.py venv flow).  Returns the venv's python or None."""
    req = os.path.join(repo_path, "requirements.txt")
    if not os.path.exists(req):
        return None
    import venv

    venv_path = os.path.join(os.path.dirname(repo_path), "venv")
    venv.create(venv_path, with_pip=True)
    python = os.path.join(venv_path, "bin", "python")
    pip = subprocess.run(
        [python, "-m", "pip", "install", "--quiet", "-r", req],
        capture_output=True,
        text=True,
    )
    if pip.returncode != 0:
        raise RuntimeError(f"pip install failed: {pip.stderr[-500:]}")
    return python


def spawn_program(
    threads: int,
    processes: int,
    first_port: int,
    program: str,
    arguments: list[str],
    env: dict | None = None,
    repository_url: str | None = None,
    branch: str | None = None,
) -> int:
    """reference: cli.py:92-109 — N processes, shared env, wait for all;
    with ``repository_url`` the program runs from a fresh clone."""
    cwd = None
    if repository_url is not None:
        cwd = checkout_repository(repository_url, branch)
        python = _venv_python(cwd)
        if python is not None and program in ("python", sys.executable):
            program = python
    base_env = dict(env or os.environ)
    base_env.update(
        {
            "PATHWAY_THREADS": str(threads),
            "PATHWAY_PROCESSES": str(processes),
            "PATHWAY_FIRST_PORT": str(first_port),
        }
    )
    procs: list[subprocess.Popen] = []
    try:
        for pid in range(processes):
            penv = dict(base_env)
            penv["PATHWAY_PROCESS_ID"] = str(pid)
            procs.append(
                subprocess.Popen([program, *arguments], env=penv, cwd=cwd)
            )
        exit_code = 0
        for p in procs:
            code = p.wait()
            if code:
                exit_code = code
        return exit_code
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait()
        return 130


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("spawn", help="run a program over N processes x M threads")
    sp.add_argument("--threads", "-t", type=int, default=1)
    sp.add_argument("--processes", "-n", type=int, default=1)
    sp.add_argument("--first-port", type=int, default=10000)
    sp.add_argument("--record", action="store_true", help="persist inputs while running")
    sp.add_argument("--record-path", default="record")
    sp.add_argument(
        "--repository-url", default=None,
        help="git URL to clone and run the program from (reference: "
        "spawn's git-repo flow; a repo requirements.txt gets a venv)",
    )
    sp.add_argument("--branch", default=None)
    sp.add_argument("program")
    sp.add_argument("arguments", nargs=argparse.REMAINDER)

    se = sub.add_parser(
        "spawn-from-env",
        help="like spawn, with arguments taken from PATHWAY_SPAWN_ARGS",
    )
    se.add_argument("program")
    se.add_argument("arguments", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)

    if args.command == "spawn":
        env = dict(os.environ)
        if args.record:
            env["PATHWAY_PERSISTENT_STORAGE"] = args.record_path
        return spawn_program(
            args.threads, args.processes, args.first_port,
            args.program, args.arguments, env,
            repository_url=args.repository_url, branch=args.branch,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "").split()
        ns = parser.parse_args(["spawn", *spawn_args, args.program, *args.arguments])
        return spawn_program(
            ns.threads, ns.processes, ns.first_port, ns.program, ns.arguments,
            repository_url=ns.repository_url, branch=ns.branch,
        )
    return 2


if __name__ == "__main__":
    sys.exit(main())
