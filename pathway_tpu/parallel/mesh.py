"""Device-mesh construction.

The reference scales by ``PATHWAY_THREADS × PATHWAY_PROCESSES`` timely
workers over TCP (src/engine/dataflow/config.rs:88-120).  Here the unit of
scale-out is a TPU mesh: axis ``data`` shards rows/batches (the analogue of
the reference's key-hash worker sharding), axis ``model`` shards model
weights (tensor parallelism — no reference analogue; the reference has no
on-device model at all).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "serving_mesh", "data_axis", "model_axis"]

data_axis = "data"
model_axis = "model"


def make_mesh(
    n_devices: int | None = None,
    *,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the first ``n_devices`` devices.

    ``model_parallel`` splits off a tensor-parallel axis; the rest is data
    parallel.  ``PATHWAY_MODEL_PARALLEL`` env overrides (mirroring the
    reference's env-driven worker config, dataflow/config.rs:88).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    mp = int(os.environ.get("PATHWAY_MODEL_PARALLEL", model_parallel))
    if n_devices % mp != 0:
        raise ValueError(f"n_devices={n_devices} not divisible by model_parallel={mp}")
    grid = np.array(devices).reshape(n_devices // mp, mp)
    return Mesh(grid, (data_axis, model_axis))


#: cached default serving mesh, keyed by the env value that built it —
#: Mesh identity matters: the sharded search is lru-cached per mesh, so
#: every server constructed under one setting must share one object
_serving_mesh_cache: dict[str, Mesh] = {}


def serving_mesh() -> Mesh | None:
    """Process-default serving mesh from ``PATHWAY_SERVING_MESH``.

    ``N`` (an int > 1) builds a data-parallel mesh over the first N
    devices; ``all`` uses every visible device; unset/``0``/``1`` means
    single-device serving (returns ``None``).  ``VectorStoreServer`` and
    ``DocumentStore`` consult this when no explicit ``mesh=`` is passed —
    the env knob that turns a one-chip deployment into a sharded one
    without touching code.  ``PATHWAY_MODEL_PARALLEL`` composes: it
    splits the tensor-parallel axis off the same device set."""
    raw = os.environ.get("PATHWAY_SERVING_MESH", "").strip().lower()
    if not raw or raw in ("0", "1", "none", "off"):
        return None
    cached = _serving_mesh_cache.get(raw)
    if cached is not None:
        return cached
    if raw == "all":
        n: int | None = None
    else:
        try:
            n = int(raw)
        except ValueError:
            import warnings

            warnings.warn(
                f"PATHWAY_SERVING_MESH={raw!r} is not an int or 'all' — "
                "serving single-device",
                stacklevel=2,
            )
            return None
        if n <= 1:
            return None
    avail = len(jax.devices())
    if n is not None and n > avail:
        import warnings

        warnings.warn(
            f"PATHWAY_SERVING_MESH={n} > {avail} visible devices — "
            f"serving over all {avail}",
            stacklevel=2,
        )
        n = avail
    mesh = make_mesh(n)
    _serving_mesh_cache[raw] = mesh
    return mesh
