"""Device-mesh construction.

The reference scales by ``PATHWAY_THREADS × PATHWAY_PROCESSES`` timely
workers over TCP (src/engine/dataflow/config.rs:88-120).  Here the unit of
scale-out is a TPU mesh: axis ``data`` shards rows/batches (the analogue of
the reference's key-hash worker sharding), axis ``model`` shards model
weights (tensor parallelism — no reference analogue; the reference has no
on-device model at all).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "data_axis", "model_axis"]

data_axis = "data"
model_axis = "model"


def make_mesh(
    n_devices: int | None = None,
    *,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the first ``n_devices`` devices.

    ``model_parallel`` splits off a tensor-parallel axis; the rest is data
    parallel.  ``PATHWAY_MODEL_PARALLEL`` env overrides (mirroring the
    reference's env-driven worker config, dataflow/config.rs:88).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    mp = int(os.environ.get("PATHWAY_MODEL_PARALLEL", model_parallel))
    if n_devices % mp != 0:
        raise ValueError(f"n_devices={n_devices} not divisible by model_parallel={mp}")
    grid = np.array(devices).reshape(n_devices // mp, mp)
    return Mesh(grid, (data_axis, model_axis))
