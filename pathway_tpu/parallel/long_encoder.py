"""Sequence-parallel encoder forward: long documents over the mesh.

The reference can only chunk long inputs (splitters.py:34) because its
embedder is a single-device torch module.  Here the SAME checkpoint
params that drive :class:`pathway_tpu.models.encoder.TransformerEncoder`
run a sequence-parallel forward: token positions are sharded over the
mesh's sequence axis, attention is :func:`ring_attention` (kv blocks
rotate over ICI), every other sublayer is position-local, and the final
masked-mean pool is a ``psum`` — so one document's context can span
``n_devices × T_local`` tokens without any chip materializing the full
sequence.

This is a functional re-expression of the flax module (same param
pytree, same math: query-scaled attention, erf-GELU, post-LN residuals),
asserted equivalent to the single-device forward in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map
from .ring_attention import ring_attention

__all__ = ["ring_encode", "ring_forward"]


def _layer_norm(x, p, eps):
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * p["scale"] + p["bias"]


def _block(x, valid, p, axis_name, eps):
    """One encoder layer with ring attention (flax Block parity:
    models/encoder.py Block — attention → ln1 → mlp(erf gelu) → ln2)."""
    att = p["attention"]
    q = jnp.einsum("bth,hnd->btnd", x, att["query"]["kernel"]) + att["query"]["bias"]
    k = jnp.einsum("bth,hnd->btnd", x, att["key"]["kernel"]) + att["key"]["bias"]
    v = jnp.einsum("bth,hnd->btnd", x, att["value"]["kernel"]) + att["value"]["bias"]
    ctx = ring_attention(q, k, v, valid, axis_name)
    h = jnp.einsum("btnd,ndh->bth", ctx, att["out"]["kernel"]) + att["out"]["bias"]
    x = _layer_norm(x + h, p["ln1"], eps)
    h = jnp.einsum("bth,hm->btm", x, p["mlp_in"]["kernel"]) + p["mlp_in"]["bias"]
    h = jax.nn.gelu(h, approximate=False)
    h = jnp.einsum("btm,mh->bth", h, p["mlp_out"]["kernel"]) + p["mlp_out"]["bias"]
    return _layer_norm(x + h, p["ln2"], eps)


def ring_forward(params, ids, mask, *, num_layers: int, ln_eps: float,
                 axis_name: str, pool: bool = True):
    """Per-shard forward (call inside shard_map; seq axis sharded).

    ids/mask: ``[B, T_local]``; params: the TransformerEncoder pytree.
    """
    t_local = ids.shape[1]
    shard = lax.axis_index(axis_name)
    positions = shard * t_local + jnp.arange(t_local)[None, :]
    x = params["tok_emb"]["embedding"][ids]
    x = x + params["pos_emb"]["embedding"][positions]
    if "type_emb" in params:
        x = x + params["type_emb"]["embedding"][jnp.zeros_like(ids)]
    x = _layer_norm(x, params["ln_emb"], ln_eps)
    valid = mask.astype(bool)
    for i in range(num_layers):
        x = _block(x, valid, params[f"layer_{i}"], axis_name, ln_eps)
    if not pool:
        return x
    m = mask[:, :, None].astype(jnp.float32)
    num = lax.psum(jnp.sum(x * m, axis=1), axis_name)
    den = lax.psum(jnp.sum(m, axis=1), axis_name)
    pooled = num / jnp.maximum(den, 1e-9)
    if "proj" in params:
        pooled = (
            jnp.einsum("bh,he->be", pooled, params["proj"]["kernel"])
            + params["proj"]["bias"]
        )
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-12)


@functools.lru_cache(maxsize=None)
def _compiled(mesh: Mesh, axis: str, num_layers: int, ln_eps: float,
              pool: bool):
    fwd = functools.partial(
        ring_forward, num_layers=num_layers, ln_eps=ln_eps,
        axis_name=axis, pool=pool,
    )

    @jax.jit
    def run(params, ids, mask):
        out_spec = P() if pool else P(None, axis)
        f = shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis)),
            out_specs=out_spec,
            check_replication=False,  # pooled output is replicated via psum
        )
        return f(params, ids, mask)

    return run


def ring_encode(params, ids, mask, mesh: Mesh, axis: str, *,
                num_layers: int, ln_eps: float = 1e-12,
                pool: bool = True):
    """Sequence-parallel encode of ``[B, T_global]`` token ids; T_global
    must divide evenly by the mesh's ``axis`` size."""
    n = mesh.shape[axis]
    if ids.shape[1] % n:
        raise ValueError(
            f"global sequence {ids.shape[1]} not divisible by mesh axis "
            f"{axis} size {n}"
        )
    max_len = params["pos_emb"]["embedding"].shape[0]
    if ids.shape[1] > max_len:
        # jit would silently clamp the position gather — wrong embeddings
        raise ValueError(
            f"global sequence {ids.shape[1]} exceeds the checkpoint's "
            f"position table ({max_len}); extend pos_emb before encoding"
        )
    seq_spec = NamedSharding(mesh, P(None, axis))
    ids = jax.device_put(jnp.asarray(ids, jnp.int32), seq_spec)
    mask = jax.device_put(jnp.asarray(mask, jnp.int32), seq_spec)
    params = jax.device_put(params, NamedSharding(mesh, P()))
    return _compiled(mesh, axis, num_layers, ln_eps, pool)(params, ids, mask)
