"""Mesh-sharded KNN index: per-device shards + ICI top-k merge.

reference: src/engine/dataflow/operators/external_index.rs:95-98 keeps a
FULL index replica on every timely worker (index stream ``.broadcast()``)
and shards only the queries.  That replication cannot fit TPU HBM at scale,
so the TPU design inverts it: the vector matrix is sharded row-wise over
the mesh's ``data`` axis (NamedSharding ``P("data", None)``), queries are
replicated, and one ``shard_map``-compiled program computes each shard's
local scores on its MXU, takes a local top-k, then merges across chips
with ``lax.all_gather`` over ICI followed by a final top-k — the classic
distributed-top-k recipe.  Per query the wire cost is ``S·k`` floats+ints
instead of shipping any index rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.knn import DeviceKnnIndex
from ._compat import shard_map
from .mesh import data_axis

__all__ = ["ShardedKnnIndex"]

NEG_INF = -jnp.inf


@functools.lru_cache(maxsize=None)
def _sharded_search_fn(mesh: Mesh, k: int, metric: str, n_local: int):
    """Compile the per-shard search + ICI merge for one (mesh, k, metric)."""

    def local_search(q, vecs, valid):
        # q: [Q, D] replicated; vecs: [n_local, D]; valid: [n_local]
        if metric in ("cos", "dot"):
            s = jnp.dot(q, vecs.T, preferred_element_type=jnp.float32)
        else:  # l2sq, negated so higher = better
            dots = jnp.dot(q, vecs.T, preferred_element_type=jnp.float32)
            qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
            vn = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)
            s = 2.0 * dots - qn - vn[None, :]
        s = jnp.where(valid[None, :], s, NEG_INF)
        k_local = min(k, n_local)
        scores, idx = lax.top_k(s, k_local)
        # local slot -> global slot
        shard = lax.axis_index(data_axis)
        gidx = idx + shard * n_local
        # merge over ICI: all-gather per-shard candidates, final top-k
        all_s = lax.all_gather(scores, data_axis)  # [S, Q, k_local]
        all_i = lax.all_gather(gidx, data_axis)
        n_shards = all_s.shape[0]
        all_s = jnp.transpose(all_s, (1, 0, 2)).reshape(q.shape[0], n_shards * k_local)
        all_i = jnp.transpose(all_i, (1, 0, 2)).reshape(q.shape[0], n_shards * k_local)
        k_out = min(k, n_shards * k_local)
        ms, pos = lax.top_k(all_s, k_out)
        mi = jnp.take_along_axis(all_i, pos, axis=1)
        return ms, mi

    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(data_axis, None), P(data_axis)),
        out_specs=(P(), P()),
    )
    mapped = shard_map(local_search, check_replication=False, **specs)
    return jax.jit(mapped)


class ShardedKnnIndex(DeviceKnnIndex):
    """KNN index whose vector matrix is sharded over a device mesh.

    Drop-in for :class:`DeviceKnnIndex` — host-side bookkeeping (slots,
    tombstones, staging) is inherited; only array placement and the search
    path change.  Works on any mesh with a ``data`` axis; arrays are
    replicated over other mesh axes.
    """

    #: device-batch staging would scatter through an unsharded jit and
    #: drop the mesh placement — sharded indexes stage host-side
    _device_stage_ok = False

    def __init__(
        self,
        dim: int,
        mesh: Mesh,
        metric: str = "cos",
        capacity: int = 1024,
        dtype=jnp.float32,
    ):
        self.mesh = mesh
        self.n_shards = mesh.shape[data_axis]
        super().__init__(dim, metric=metric, capacity=int(capacity), dtype=dtype)
        self._vec_sharding = NamedSharding(mesh, P(data_axis, None))
        self._mask_sharding = NamedSharding(mesh, P(data_axis))
        self._place()
        self._scatter_rows_fn = jax.jit(
            lambda m, i, v: m.at[i].set(v), out_shardings=self._vec_sharding
        )
        self._scatter_mask_fn = jax.jit(
            lambda m, i, v: m.at[i].set(v), out_shardings=self._mask_sharding
        )

    def _round_capacity(self, capacity: int) -> int:
        """Also keep capacity divisible by the shard count through every
        doubling/compaction so row-sharding stays balanced."""
        capacity = super()._round_capacity(max(capacity, 8 * self.n_shards))
        rem = capacity % self.n_shards
        if rem:
            capacity += self.n_shards - rem
        return capacity

    def _place(self) -> None:
        # __init__ ordering: the base constructor builds the arrays before
        # the shardings exist; the explicit _place() call after they do
        # pins both arrays to the mesh
        if hasattr(self, "_vec_sharding"):
            self.vectors = jax.device_put(self.vectors, self._vec_sharding)
            self.valid = jax.device_put(self.valid, self._mask_sharding)

    def _device_search(self, q: np.ndarray, k: int):
        n_local = self.capacity // self.n_shards
        fn = _sharded_search_fn(self.mesh, int(k), self.metric, n_local)
        return fn(jnp.asarray(q, dtype=self.dtype), self.vectors, self.valid)
