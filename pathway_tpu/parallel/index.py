"""Mesh-sharded KNN index: per-device shards + ICI top-k merge.

reference: src/engine/dataflow/operators/external_index.rs:95-98 keeps a
FULL index replica on every timely worker (index stream ``.broadcast()``)
and shards only the queries.  That replication cannot fit TPU HBM at scale,
so the TPU design inverts it: the vector matrix is sharded row-wise over
the mesh's ``data`` axis (NamedSharding ``P("data", None)``), queries are
replicated, and one ``shard_map``-compiled program computes each shard's
local scores on its MXU, takes a local top-k, then merges across chips
with ``lax.all_gather`` over ICI followed by a final top-k — the classic
distributed-top-k recipe.  Per query the wire cost is ``S·k`` floats+ints
instead of shipping any index rows.
"""

from __future__ import annotations

import functools
import itertools
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.knn import (
    DeviceKnnIndex,
    _coded_scatter_body,
    _quant_scatter_body,
    _scatter_rows_dropping_body,
)
from ._compat import shard_map
from .mesh import data_axis

__all__ = ["ShardedKnnIndex", "mesh_status"]

NEG_INF = -jnp.inf


@functools.lru_cache(maxsize=None)
def _sharded_search_fn(mesh: Mesh, k: int, metric: str, n_local: int):
    """Compile the per-shard search + ICI merge for one (mesh, k, metric)."""

    def local_search(q, vecs, valid):
        # q: [Q, D] replicated; vecs: [n_local, D]; valid: [n_local]
        if metric in ("cos", "dot"):
            s = jnp.dot(q, vecs.T, preferred_element_type=jnp.float32)
        else:  # l2sq, negated so higher = better
            dots = jnp.dot(q, vecs.T, preferred_element_type=jnp.float32)
            qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
            vn = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)
            s = 2.0 * dots - qn - vn[None, :]
        s = jnp.where(valid[None, :], s, NEG_INF)
        k_local = min(k, n_local)
        scores, idx = lax.top_k(s, k_local)
        # local slot -> global slot
        shard = lax.axis_index(data_axis)
        gidx = idx + shard * n_local
        # merge over ICI: all-gather per-shard candidates, final top-k
        all_s = lax.all_gather(scores, data_axis)  # [S, Q, k_local]
        all_i = lax.all_gather(gidx, data_axis)
        n_shards = all_s.shape[0]
        all_s = jnp.transpose(all_s, (1, 0, 2)).reshape(q.shape[0], n_shards * k_local)
        all_i = jnp.transpose(all_i, (1, 0, 2)).reshape(q.shape[0], n_shards * k_local)
        k_out = min(k, n_shards * k_local)
        ms, pos = lax.top_k(all_s, k_out)
        mi = jnp.take_along_axis(all_i, pos, axis=1)
        return ms, mi

    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(data_axis, None), P(data_axis)),
        out_specs=(P(), P()),
    )
    mapped = shard_map(local_search, check_replication=False, **specs)
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _sharded_quant_search_fn(
    mesh: Mesh, c: int, metric: str, n_local: int, mode: str
):
    """Per-shard asymmetric int8 scoring + ICI top-c merge for one
    (mesh, c, metric, kernel mode).  Each shard scores its slice through
    the SAME dispatcher the single-device path uses
    (``quantized_scoring.quantized_scores``) — on a real TPU the Pallas
    kernel streams the shard's int8 code tiles from HBM, off-TPU the XLA
    reference runs (interpret mode never executes inside shard_map), so
    single-vs-sharded scores come from one scoring body per platform and
    the merged candidate list is bit-identical to the single-device
    stage 1 — the property the quantized parity tests pin.  The rescore
    stage runs OUTSIDE the shard_map against the replicated f32 ring
    (``ops/quantized_scoring.rescore_topk``), exactly as on one
    device."""
    from ..ops.quantized_scoring import _reference_scores, quantized_scores

    on_tpu = jax.default_backend() == "tpu"

    def local_search(q, codes, scales, valid):
        # q: [Q, D] replicated; codes: [n_local, D]; scales/valid:
        # [n_local] — the shard slice through the shared dispatcher
        if on_tpu:
            s = quantized_scores(q, codes, scales, valid, metric, mode)
        else:
            s = _reference_scores(q, codes, scales, valid, metric)
        c_local = min(c, n_local)
        cand, idx = lax.top_k(s, c_local)
        shard = lax.axis_index(data_axis)
        gidx = idx + shard * n_local
        all_s = lax.all_gather(cand, data_axis)
        all_i = lax.all_gather(gidx, data_axis)
        n_shards = all_s.shape[0]
        all_s = jnp.transpose(all_s, (1, 0, 2)).reshape(
            q.shape[0], n_shards * c_local
        )
        all_i = jnp.transpose(all_i, (1, 0, 2)).reshape(
            q.shape[0], n_shards * c_local
        )
        c_out = min(c, n_shards * c_local)
        ms, pos = lax.top_k(all_s, c_out)
        mi = jnp.take_along_axis(all_i, pos, axis=1)
        return ms, mi

    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(data_axis, None), P(data_axis), P(data_axis)),
        out_specs=(P(), P()),
    )
    mapped = shard_map(local_search, check_replication=False, **specs)
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _sharded_fused_search_fn(
    mesh: Mesh, k: int, metric: str, n_local: int, normalize: bool,
    q_b: int, qdt: str,
):
    """Fused sharded serving: query widen/L2-normalize/pad folded into
    the SAME dispatch as the per-shard search + ICI merge — one launch
    per tick instead of prep + search.  The body reuses the staged
    ``_sharded_search_fn`` computation verbatim (traced inline), so the
    sharded fused-vs-reference parity is by construction."""
    from ..ops.fused_serving import _DTYPES, _prep_body

    base = _sharded_search_fn(mesh, k, metric, n_local)

    def fused(q, vecs, valid):
        qn = _prep_body(q, q_b, normalize)
        return base(qn.astype(_DTYPES[qdt]), vecs, valid)

    return jax.jit(fused)


@functools.lru_cache(maxsize=None)
def _sharded_fused_quant_fn(
    mesh: Mesh, c: int, metric: str, n_local: int, mode: str,
    normalize: bool, q_b: int,
):
    """Quantized twin: prep + per-shard int8 scoring + ICI top-c merge
    in one dispatch, returning the normalized queries alongside the
    candidates so the rescore-ring pass (the only second launch) never
    re-normalizes."""
    from ..ops.fused_serving import _prep_body

    base = _sharded_quant_search_fn(mesh, c, metric, n_local, mode)

    def fused(q, codes, scales, valid):
        qn = _prep_body(q, q_b, normalize)
        cand_s, cand_i = base(qn, codes, scales, valid)
        return cand_s, cand_i, qn

    return jax.jit(fused)


#: live sharded indexes, for /status + /v1/health mesh surfacing (weak:
#: a finished run's indexes drop out with it)
_LIVE_SHARDED: "weakref.WeakSet[ShardedKnnIndex]" = weakref.WeakSet()
_label_seq = itertools.count()


class ShardedKnnIndex(DeviceKnnIndex):
    """KNN index whose vector matrix is sharded over a device mesh.

    Drop-in for :class:`DeviceKnnIndex` — host-side bookkeeping (slots,
    tombstones, staging) is inherited; only array placement and the search
    path change.  Works on any mesh with a ``data`` axis; arrays are
    replicated over other mesh axes.

    Device-batch staging (the ingest plane's embed→upsert fast path) is
    supported since PR 8: the dropping scatter is jitted with
    ``out_shardings`` pinned to the mesh, so staged rows land in their
    owning shard — the PR 5 ``_device_stage_ok=False`` restriction is
    lifted (see MIGRATION).
    """

    def __init__(
        self,
        dim: int,
        mesh: Mesh,
        metric: str = "cos",
        capacity: int = 1024,
        dtype=None,
        index_dtype: str | None = None,
        rescore_depth: int | None = None,
        rescore_cache_rows: int | None = None,
    ):
        self.mesh = mesh
        self.n_shards = mesh.shape[data_axis]
        super().__init__(
            dim,
            metric=metric,
            capacity=int(capacity),
            dtype=dtype,
            index_dtype=index_dtype,
            rescore_depth=rescore_depth,
            rescore_cache_rows=rescore_cache_rows,
        )
        self._vec_sharding = NamedSharding(mesh, P(data_axis, None))
        self._mask_sharding = NamedSharding(mesh, P(data_axis))
        #: the f32 rescore ring and the slot→ring table replicate (they
        #: are small by construction, and the post-merge rescore gathers
        #: arbitrary global slots — a replicated read beats an
        #: all-to-all per search)
        self._repl_sharding = NamedSharding(mesh, P())
        self._place()
        self._scatter_rows_fn = jax.jit(
            lambda m, i, v: m.at[i].set(v), out_shardings=self._vec_sharding
        )
        self._scatter_mask_fn = jax.jit(
            lambda m, i, v: m.at[i].set(v), out_shardings=self._mask_sharding
        )
        # device-staged rows scatter through the SAME body as the
        # single-device path (no numeric divergence) but with the output
        # pinned to the mesh — GSPMD routes each row to its owning shard
        self._scatter_dropping_fn = functools.partial(
            jax.jit,
            static_argnames=("normalize",),
            out_shardings=self._vec_sharding,
        )(_scatter_rows_dropping_body)
        # quantized twins: codes shard row-wise like the f32 matrix,
        # scales like the tombstone mask, ring + map replicated
        self._quant_scatter_fn = functools.partial(
            jax.jit,
            static_argnames=("normalize",),
            out_shardings=(
                self._vec_sharding,
                self._mask_sharding,
                self._repl_sharding,
                self._repl_sharding,
            ),
        )(_quant_scatter_body)
        self._coded_scatter_fn = jax.jit(
            _coded_scatter_body,
            out_shardings=(self._vec_sharding, self._mask_sharding),
        )
        #: fused embed→search ticks answered by this sharded index
        self.sharded_ticks = 0
        self.mesh_label = f"sharded{next(_label_seq)}"
        _LIVE_SHARDED.add(self)
        _ensure_mesh_provider()

    def _round_capacity(self, capacity: int) -> int:
        """Also keep capacity divisible by the shard count through every
        doubling/compaction so row-sharding stays balanced."""
        capacity = super()._round_capacity(max(capacity, 8 * self.n_shards))
        rem = capacity % self.n_shards
        if rem:
            capacity += self.n_shards - rem
        return capacity

    def _place(self) -> None:
        # __init__ ordering: the base constructor builds the arrays before
        # the shardings exist; the explicit _place() call after they do
        # pins both arrays to the mesh
        if hasattr(self, "_vec_sharding"):
            if self.quantized:
                self.codes = jax.device_put(self.codes, self._vec_sharding)
                self.scales = jax.device_put(self.scales, self._mask_sharding)
                self.rescore_vecs = jax.device_put(
                    self.rescore_vecs, self._repl_sharding
                )
                self.cache_map = jax.device_put(
                    self.cache_map, self._repl_sharding
                )
            else:
                self.vectors = jax.device_put(self.vectors, self._vec_sharding)
            self.valid = jax.device_put(self.valid, self._mask_sharding)

    def _device_search(self, q, k: int):
        from ..ops.fused_serving import record_launch

        n_local = self.capacity // self.n_shards
        self.sharded_ticks += 1
        if self.quantized:
            from ..ops.quantized_scoring import kernel_mode, rescore_topk

            self.quant_searches += 1
            k_eff = min(int(k), self.capacity)
            c = self.quant_depth(k_eff)
            fn = _sharded_quant_search_fn(
                self.mesh, c, self.metric, n_local, kernel_mode()
            )
            record_launch("score")
            cand_scores, cand_idx = fn(
                self._quant_device_search(q), self.codes, self.scales, self.valid
            )
            if self.rescore_cache_rows > 0:
                record_launch("rescore")
                return rescore_topk(
                    jnp.asarray(q, dtype=jnp.float32),
                    cand_scores,
                    cand_idx,
                    self.rescore_vecs,
                    self.cache_map,
                    k=k_eff,
                    metric=self.metric,
                )
            return cand_scores[:, :k_eff], cand_idx[:, :k_eff]
        fn = _sharded_search_fn(self.mesh, int(k), self.metric, n_local)
        record_launch("score")
        return fn(jnp.asarray(q, dtype=self.dtype), self.vectors, self.valid)

    def _fused_device_search(self, q, k: int, q_b: int, normalize: bool, mode: str):
        """Fused sharded serving tick: ≤2 launches (1 dense, 2 with the
        int8 rescore-ring pass) — prep rides inside the shard_map jit.
        The ``mode`` knob's pallas/auto distinction is a per-shard
        concern handled by the quantized scoring dispatcher; the merge
        topology is the same either way."""
        from ..ops.fused_serving import record_launch

        n_local = self.capacity // self.n_shards
        self.sharded_ticks += 1
        if self.quantized:
            from ..ops.quantized_scoring import kernel_mode, rescore_topk

            self.quant_searches += 1
            k_eff = min(int(k), self.capacity)
            c = self.quant_depth(k_eff)
            fn = _sharded_fused_quant_fn(
                self.mesh, c, self.metric, n_local, kernel_mode(),
                normalize, q_b,
            )
            record_launch("fused")
            cand_scores, cand_idx, qn = fn(
                q if isinstance(q, jax.Array)
                else jnp.asarray(q, dtype=jnp.float32),
                self.codes,
                self.scales,
                self.valid,
            )
            if self.rescore_cache_rows > 0:
                record_launch("rescore")
                return rescore_topk(
                    qn,
                    cand_scores,
                    cand_idx,
                    self.rescore_vecs,
                    self.cache_map,
                    k=k_eff,
                    metric=self.metric,
                )
            return cand_scores[:, :k_eff], cand_idx[:, :k_eff]
        fn = _sharded_fused_search_fn(
            self.mesh, int(k), self.metric, n_local, normalize, q_b,
            "bf16" if self.dtype == jnp.bfloat16 else "f32",
        )
        record_launch("fused")
        return fn(
            q if isinstance(q, jax.Array) else jnp.asarray(q),
            self.vectors,
            self.valid,
        )

    # -- mesh observability ---------------------------------------------
    def hbm_ledger_entries(self) -> dict[str, int]:
        """Per-shard breakdown for the unified HBM ledger
        (``pathway_hbm_bytes{component="knn:<label>",shard=}``).  The
        shard rows sum to EXACTLY :meth:`hbm_bytes` — the replicated
        rescore ring/cache-map copies are already counted per shard
        there, so an even split (remainder on shard 0) attributes every
        byte exactly once."""
        total = int(self.hbm_bytes())
        n = max(int(self.n_shards), 1)
        base = total // n
        out = {str(i): base for i in range(n)}
        out["0"] = base + (total - base * n)
        return out

    def shard_row_counts(self) -> list[int]:
        """Live rows per shard (row-sharding balance observable — slots
        are allocated LIFO off one free list, so a heavily skewed profile
        here means deletes concentrated in one shard's slot range).

        LOCK-FREE on purpose: health probes and metric scrapes call this,
        and taking ``self._lock`` would block them behind an in-flight
        search or a long staged apply — exactly the "probe stalls during
        heavy ingest" failure /v1/health must not have.  ``list(dict
        .values())`` is one C-level snapshot under the GIL; a concurrent
        resize raises RuntimeError, so retry a few times and report the
        last good approximation (it is a gauge, not an invariant)."""
        n_local = max(self.capacity // self.n_shards, 1)
        slots: list = []
        for _attempt in range(4):
            try:
                slots = list(self.slot_of_key.values())
                break
            except RuntimeError:  # dict resized mid-snapshot
                continue
        counts = [0] * self.n_shards
        for slot in slots:
            counts[min(slot // n_local, self.n_shards - 1)] += 1
        return counts


# ---------------------------------------------------------------------------
# mesh observability: pathway_mesh_* series on /status, mesh block on
# /v1/health (internals/health.py reads mesh_status() only when this
# module is already imported — a health probe never imports jax state)
# ---------------------------------------------------------------------------


class _MeshMetricsProvider:
    """``pathway_mesh_*`` OpenMetrics series over every live sharded
    index: mesh width, per-shard live rows, fused sharded-tick count."""

    def stats(self) -> dict:
        return mesh_status() or {}

    def openmetrics_lines(self) -> list[str]:
        from ..internals.metrics_names import escape_label_value

        indexes = sorted(_LIVE_SHARDED, key=lambda i: i.mesh_label)
        if not indexes:
            return []
        lines = [
            "# TYPE pathway_mesh_devices gauge",
        ]
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.mesh_label)}"'
            lines.append(f"pathway_mesh_devices{{{lbl}}} {idx.n_shards}")
        lines.append("# TYPE pathway_mesh_shard_rows gauge")
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.mesh_label)}"'
            for shard, rows in enumerate(idx.shard_row_counts()):
                lines.append(
                    f'pathway_mesh_shard_rows{{{lbl},shard="{shard}"}} {rows}'
                )
        lines.append("# TYPE pathway_mesh_sharded_ticks_total counter")
        for idx in indexes:
            lbl = f'index="{escape_label_value(idx.mesh_label)}"'
            lines.append(
                f"pathway_mesh_sharded_ticks_total{{{lbl}}} {idx.sharded_ticks}"
            )
        return lines


def _ensure_mesh_provider() -> None:
    # once-registration with a strong ref held by monitoring (the
    # provider table itself is weak-valued)
    from ..internals.monitoring import register_metrics_provider_once

    register_metrics_provider_once("mesh", _MeshMetricsProvider)


def mesh_status() -> dict | None:
    """Mesh shape + per-shard row counts for ``/v1/health`` (None when no
    sharded index is live)."""
    indexes = sorted(_LIVE_SHARDED, key=lambda i: i.mesh_label)
    if not indexes:
        return None
    return {
        idx.mesh_label: {
            "devices": int(idx.n_shards),
            "capacity_rows": int(idx.capacity),
            "rows_per_shard": idx.shard_row_counts(),
            "sharded_ticks": int(idx.sharded_ticks),
            "metric": idx.metric,
            "dim": int(idx.dim),
            "index_dtype": idx.index_dtype,
            # "hot" when this mesh-sharded index is a tiered index's
            # per-shard HBM hot tier (pathway_tpu/tiering)
            "role": getattr(idx, "tier_role", "primary"),
        }
        for idx in indexes
    }
