"""Ring attention: sequence-parallel attention over a mesh axis.

The reference has no sequence parallelism at all (SURVEY §5 long-context:
its only long-input tool is document chunking, splitters.py:34).  The TPU
build makes long context first-class: documents longer than one chip's
comfortable sequence length are sharded over the mesh's sequence axis and
attended with the ring algorithm — each device holds one query block and
rotates key/value blocks around the ring with ``lax.ppermute`` (one ICI
hop per step), accumulating softmax online in the numerically-stable
flash style.  Peak memory per chip stays O(T_local²-ish) while the
effective context is T_local × ring_size; the collectives ride ICI.

Layout convention: ``[batch, seq_local, heads, head_dim]`` inside
``shard_map`` with the sequence axis sharded over ``axis_name``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]

NEG_INF = -1e30


def ring_attention(q, k, v, kv_valid, axis_name: str):
    """Bidirectional (encoder) attention with the kv blocks ring-rotated.

    q, k, v: ``[B, T_local, H, Dh]`` — the sequence axis is sharded over
    ``axis_name``; kv_valid: ``[B, T_local]`` bool — padding mask for the
    local kv block.  Returns ``[B, T_local, H, Dh]`` in fp32.

    Online-softmax accumulation: running max ``m``, normalizer ``l`` and
    unnormalized output ``o`` are updated per ring step, so no step ever
    materializes the full [T, T_global] score matrix.
    """
    n = lax.psum(1, axis_name)
    b, t, h, dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))
    qf = q.astype(jnp.float32)

    m = jnp.full((b, h, t), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    o = jnp.zeros((b, t, h, dh), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    for _step in range(n):
        s = jnp.einsum(
            "bthd,bshd->bhts", qf, k.astype(jnp.float32)
        ) * scale
        s = jnp.where(kv_valid[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows: exp(NEG_INF - NEG_INF) must not be 1
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(kv_valid[:, None, None, :], p, 0.0)
        corr = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - safe_m)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, v.astype(jnp.float32)
        )
        m = m_new
        if _step < n - 1:  # the last step's rotation would never be read
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            kv_valid = lax.ppermute(kv_valid, axis_name, perm)
    l_t = l.transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    return o / jnp.maximum(l_t, 1e-30)


@functools.lru_cache(maxsize=None)
def _compiled_ring(mesh: Mesh, axis: str):
    # jit specializes on shapes/dtypes itself — cache only per (mesh, axis)

    @jax.jit
    def run(q, k, v, valid):
        f = shard_map(
            lambda q, k, v, m: ring_attention(q, k, v, m, axis),
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
        )
        return f(q, k, v, valid)

    return run


def ring_attention_sharded(q, k, v, kv_valid, mesh: Mesh, axis: str):
    """Host-facing helper: place global ``[B, T, H, Dh]`` arrays with the
    sequence axis sharded over ``axis`` and run ring attention."""
    spec = NamedSharding(mesh, P(None, axis))
    q, k, v = (jax.device_put(x, spec) for x in (q, k, v))
    kv_valid = jax.device_put(kv_valid, spec)
    return _compiled_ring(mesh, axis)(q, k, v, kv_valid)
