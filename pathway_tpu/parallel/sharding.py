"""Tensor/data-parallel partition specs for the JAX model stack.

The reference has no on-device model (its embedders call torch inside UDFs,
xpacks/llm/embedders.py:270), so these rules have no reference counterpart
to translate — they are the standard Megatron-style TP split expressed as
``jax.sharding`` annotations, letting XLA insert the psum/all-gathers:

* attention q/k/v kernels ``(D, H, Hd)`` split over heads → ``model``;
* attention out kernel ``(H, Hd, D)`` split over heads → ``model`` (row
  parallel — XLA emits one psum after it);
* MLP in ``(D, M)`` column-split, MLP out ``(M, D)`` row-split;
* embeddings/layernorms replicated; activations sharded over ``data``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import data_axis, model_axis

__all__ = ["encoder_param_specs", "shard_params", "batch_spec", "mesh_setup", "decoder_param_specs", "shard_decoder_params"]


def batch_spec() -> P:
    """Activations: batch dim over ``data``, everything else replicated."""
    return P(data_axis, None)


def _spec_for(path: tuple, leaf) -> P:
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    ndim = getattr(leaf, "ndim", 0)
    if "attention" in joined:
        if names[-1] == "kernel":
            if "out" in joined and ndim == 3:  # (H, Hd, D) row-parallel
                return P(model_axis, None, None)
            if ndim == 3:  # q/k/v (D, H, Hd) column-parallel over heads
                return P(None, model_axis, None)
        if names[-1] == "bias" and ndim == 2:  # (H, Hd)
            return P(model_axis, None)
        return P(*([None] * ndim))
    if names[-1] == "kernel" and ndim == 2:
        if "mlp_in" in joined or "pooler" in joined:
            return P(None, model_axis)  # (D, M) column-parallel
        if "mlp_out" in joined:
            return P(model_axis, None)  # (M, D) row-parallel
        return P(None, None)
    if names[-1] == "bias" and ndim == 1 and "mlp_in" in joined:
        return P(model_axis)
    return P(*([None] * ndim))


def encoder_param_specs(params: Any) -> Any:
    """PartitionSpec pytree matching ``TransformerEncoder`` params."""
    return jax.tree_util.tree_map_with_path(_spec_for, params)


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a param pytree onto ``mesh`` with the TP specs above."""
    specs = encoder_param_specs(params)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )


def mesh_setup(params: Any, mesh: Mesh):
    """The dp/tp placement recipe shared by every bucketed-dispatch model
    (SentenceEncoder, CrossEncoder): tensor-parallel weights, a
    data-parallel batch sharding for inputs, and the multiple the batch
    bucket must round to so it divides the data axis.

    Returns ``(sharded_params, data_sharding, batch_multiple)``."""
    from .mesh import data_axis

    return (
        shard_params(params, mesh),
        NamedSharding(mesh, batch_spec()),
        int(mesh.shape.get(data_axis, 1)),
    )


def decoder_param_specs(params: Any) -> Any:
    """PartitionSpec pytree for ``models/decoder.py`` (GPT-2 layout).

    Megatron split adapted to the fused-qkv layout: ``c_attn (D, 3D)``
    and ``c_fc (D, M)`` column-parallel, ``attn_proj``/``mlp_proj``
    row-parallel (one psum each), embeddings/layernorms replicated.
    Note the fused qkv's output shards span q/k/v boundaries; GSPMD
    repartitions after the in-graph split (correctness guaranteed; a
    de-fused qkv would save that collective — future optimization)."""

    def spec_for(path: tuple, leaf) -> P:
        names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
        joined = "/".join(str(n) for n in names)
        ndim = getattr(leaf, "ndim", 0)
        if names[-1] == "kernel" and ndim == 2:
            if "c_attn" in joined or "c_fc" in joined:
                return P(None, model_axis)
            if "attn_proj" in joined or "mlp_proj" in joined:
                return P(model_axis, None)
            return P(None, None)
        if names[-1] == "bias" and ndim == 1 and (
            "c_attn" in joined or "c_fc" in joined
        ):
            return P(model_axis)
        return P(*([None] * ndim))

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_decoder_params(params: Any, mesh: Mesh) -> Any:
    specs = decoder_param_specs(params)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )
