"""Multi-chip plane: device meshes, sharding rules, distributed index.

reference counterpart: timely's TCP ``CommunicationConfig::Cluster``
transport + worker sharding (src/engine/dataflow/config.rs:63-120,
value.rs:38-99 shard field) and the index-replica-per-worker broadcast
(src/engine/dataflow/operators/external_index.rs:95-98).

TPU redesign: no record-level TCP exchange between workers — the numeric
plane (embeddings, index matrices, scores) lives in HBM sharded over a
``jax.sharding.Mesh``; queries fan out as one ``shard_map``-compiled
program whose cross-device traffic is XLA collectives on ICI
(all-gather of per-shard top-k, psum for stats) instead of the
reference's per-worker replica search.
"""

from .mesh import make_mesh, serving_mesh, data_axis, model_axis
from .sharding import encoder_param_specs, shard_params, batch_spec
from .index import ShardedKnnIndex
from .ring_attention import ring_attention, ring_attention_sharded
from .long_encoder import ring_encode, ring_forward

__all__ = [
    "make_mesh",
    "serving_mesh",
    "data_axis",
    "model_axis",
    "encoder_param_specs",
    "shard_params",
    "batch_spec",
    "ShardedKnnIndex",
    "ring_attention",
    "ring_attention_sharded",
    "ring_encode",
    "ring_forward",
]
