"""JAX version compatibility for the parallel package.

``shard_map`` has moved twice across JAX releases: old versions expose it
only as ``jax.experimental.shard_map.shard_map`` (replication check kwarg
``check_rep``), newer ones promote it to ``jax.shard_map`` (kwarg renamed
``check_vma``) and eventually drop the experimental module.  Every caller
in this package goes through :func:`shard_map` below so the resolution and
the kwarg translation live in exactly one place.
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = ["shard_map"]


def _resolve():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, "check_vma"
    from jax.experimental.shard_map import shard_map as fn  # type: ignore

    return fn, "check_rep"


_SHARD_MAP, _CHECK_KW = _resolve()


def shard_map(
    f,
    *,
    mesh,
    in_specs: Any,
    out_specs: Any,
    check_replication: bool | None = None,
):
    """Version-portable ``shard_map``.

    ``check_replication`` maps onto whichever of ``check_vma`` /
    ``check_rep`` the installed JAX understands; ``None`` keeps the
    library default.
    """
    kwargs = {}
    if check_replication is not None:
        kwargs[_CHECK_KW] = check_replication
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
