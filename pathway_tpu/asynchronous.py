"""Deprecated alias of :mod:`pathway_tpu.udfs`.

reference: python/pathway/asynchronous.py — kept for API parity; new code
should use ``pw.udfs`` (retry strategies, caches, executors).
"""

from __future__ import annotations

from warnings import warn

from .internals import udfs as _udfs


def __getattr__(name: str):
    value = getattr(_udfs, name)
    warn(
        f"pathway_tpu.asynchronous.{name} is deprecated, use "
        f"pathway_tpu.udfs.{name}",
        DeprecationWarning,
        stacklevel=2,
    )
    return value
