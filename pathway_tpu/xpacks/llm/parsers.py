"""Parser UDFs — bytes -> list[(text, metadata)].

reference: python/pathway/xpacks/llm/parsers.py — ``ParseUtf8``:53,
``ParseUnstructured``:79, ``OpenParse``:235, ``ImageParser``:396,
``SlideParser``:569, ``PypdfParser``:746.

``Utf8Parser`` is the native default; the library-backed ones import their
dependency lazily and raise a clear error when the library is missing from
the image (no network installs here).
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals import udfs
from ...internals.udfs import UDF

__all__ = [
    "Utf8Parser",
    "ParseUtf8",
    "UnstructuredParser",
    "ParseUnstructured",
    "PypdfParser",
    "ImageParser",
    "SlideParser",
]


class Utf8Parser(UDF):
    """Decode UTF-8 bytes into one chunk (reference: parsers.py:53)."""

    def __init__(self):
        super().__init__(deterministic=True)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            docs = contents
        else:
            docs = bytes(contents).decode("utf-8", errors="replace")
        return [(docs, {})]


ParseUtf8 = Utf8Parser  # reference keeps both names across versions


class UnstructuredParser(UDF):
    """unstructured-io partitioner (reference: parsers.py:79) — chunking
    modes: single / elements / paged / basic / by_title."""

    def __init__(
        self,
        mode: str = "single",
        post_processors: list[Callable] | None = None,
        **unstructured_kwargs,
    ):
        if mode not in ("single", "elements", "paged", "basic", "by_title"):
            raise ValueError(
                f"mode '{mode}' not supported; use single/elements/paged/basic/by_title"
            )
        super().__init__()
        self.mode = mode
        self.post_processors = post_processors or []
        self.unstructured_kwargs = unstructured_kwargs

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        import unstructured.partition.auto  # optional dependency

        elements = unstructured.partition.auto.partition(
            file=io.BytesIO(bytes(contents)), **{**self.unstructured_kwargs, **kwargs}
        )
        for el in elements:
            for pp in self.post_processors:
                el.apply(pp)

        if self.mode == "single":
            meta: dict = {}
            text = "\n\n".join(str(el) for el in elements)
            return [(text, meta)]
        if self.mode in ("elements", "basic"):
            out = []
            for el in elements:
                m = el.metadata.to_dict() if hasattr(el, "metadata") else {}
                m["category"] = getattr(el, "category", None)
                out.append((str(el), m))
            return out
        # paged / by_title: group elements by page / section
        groups: dict[Any, list] = {}
        for el in elements:
            m = el.metadata.to_dict() if hasattr(el, "metadata") else {}
            gk = m.get("page_number", 1)
            groups.setdefault(gk, []).append(str(el))
        return [
            ("\n\n".join(parts), {"page_number": page})
            for page, parts in sorted(groups.items(), key=lambda kv: str(kv[0]))
        ]


ParseUnstructured = UnstructuredParser


class PypdfParser(UDF):
    """pypdf text extraction, one chunk per page
    (reference: parsers.py:746 w/ optional de-hyphenation cleanup)."""

    def __init__(self, apply_text_cleanup: bool = True):
        super().__init__()
        self.apply_text_cleanup = apply_text_cleanup

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        from pypdf import PdfReader  # optional dependency

        reader = PdfReader(io.BytesIO(bytes(contents)))
        out = []
        for page_num, page in enumerate(reader.pages):
            text = page.extract_text() or ""
            if self.apply_text_cleanup:
                text = _cleanup_pdf_text(text)
            if text.strip():
                out.append((text, {"page_number": page_num + 1}))
        return out


def _cleanup_pdf_text(text: str) -> str:
    import re

    text = re.sub(r"-\n(\w)", r"\1", text)  # de-hyphenate line breaks
    text = re.sub(r"(?<!\n)\n(?!\n)", " ", text)  # unwrap soft newlines
    return re.sub(r" {2,}", " ", text).strip()


class _VisionParserBase(UDF):
    """Shared shape of the LLM-vision parsers (reference: parsers.py:396
    ImageParser / :569 SlideParser): describe each image/slide with a
    multimodal chat UDF and emit the description as the chunk text."""

    def __init__(self, llm, prompt: str, **kwargs):
        super().__init__(executor=udfs.async_executor())
        self.llm = llm
        self.prompt = prompt
        self.kwargs = kwargs

    async def _describe(self, b64_image: str) -> str:
        fn = getattr(self.llm, "__wrapped__", self.llm)
        messages = (
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": self.prompt},
                    {
                        "type": "image_url",
                        "image_url": {"url": f"data:image/jpeg;base64,{b64_image}"},
                    },
                ],
            },
        )
        res = fn(messages)
        import inspect

        if inspect.iscoroutine(res):
            res = await res
        return str(res)


class ImageParser(_VisionParserBase):
    """reference: parsers.py:396"""

    def __init__(self, llm, prompt: str = "Describe the image contents.", **kwargs):
        super().__init__(llm, prompt, **kwargs)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import base64

        b64 = base64.b64encode(bytes(contents)).decode()
        return [(await self._describe(b64), {})]


class SlideParser(_VisionParserBase):
    """reference: parsers.py:569 — renders pdf slides to images first
    (needs pdf2image in the environment)."""

    def __init__(self, llm, prompt: str = "Describe the slide contents.", **kwargs):
        super().__init__(llm, prompt, **kwargs)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import base64
        import io

        from pdf2image import convert_from_bytes  # optional dependency

        pages = convert_from_bytes(bytes(contents))
        out = []
        for i, img in enumerate(pages):
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            b64 = base64.b64encode(buf.getvalue()).decode()
            out.append((await self._describe(b64), {"slide_number": i + 1}))
        return out
