"""Parser UDFs — bytes -> list[(text, metadata)].

reference: python/pathway/xpacks/llm/parsers.py — ``ParseUtf8``:53,
``ParseUnstructured``:79, ``OpenParse``:235, ``ImageParser``:396,
``SlideParser``:569, ``PypdfParser``:746.

``Utf8Parser`` is the native default; the library-backed ones import their
dependency lazily and raise a clear error when the library is missing from
the image (no network installs here).
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals import udfs
from ...internals.udfs import UDF

__all__ = [
    "Utf8Parser",
    "ParseUtf8",
    "UnstructuredParser",
    "ParseUnstructured",
    "PypdfParser",
    "OpenParse",
    "AutoParser",
    "ImageParser",
    "SlideParser",
]


class Utf8Parser(UDF):
    """Decode UTF-8 bytes into one chunk (reference: parsers.py:53)."""

    def __init__(self):
        super().__init__(deterministic=True)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            docs = contents
        else:
            docs = bytes(contents).decode("utf-8", errors="replace")
        return [(docs, {})]


ParseUtf8 = Utf8Parser  # reference keeps both names across versions


class UnstructuredParser(UDF):
    """unstructured-io partitioner (reference: parsers.py:79) — chunking
    modes: single / elements / paged / basic / by_title."""

    def __init__(
        self,
        mode: str = "single",
        post_processors: list[Callable] | None = None,
        **unstructured_kwargs,
    ):
        if mode not in ("single", "elements", "paged", "basic", "by_title"):
            raise ValueError(
                f"mode '{mode}' not supported; use single/elements/paged/basic/by_title"
            )
        super().__init__()
        self.mode = mode
        self.post_processors = post_processors or []
        self.unstructured_kwargs = unstructured_kwargs

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        import unstructured.partition.auto  # optional dependency

        elements = unstructured.partition.auto.partition(
            file=io.BytesIO(bytes(contents)), **{**self.unstructured_kwargs, **kwargs}
        )
        for el in elements:
            for pp in self.post_processors:
                el.apply(pp)

        if self.mode == "single":
            meta: dict = {}
            text = "\n\n".join(str(el) for el in elements)
            return [(text, meta)]
        if self.mode in ("elements", "basic"):
            out = []
            for el in elements:
                m = el.metadata.to_dict() if hasattr(el, "metadata") else {}
                m["category"] = getattr(el, "category", None)
                out.append((str(el), m))
            return out
        # paged / by_title: group elements by page / section
        groups: dict[Any, list] = {}
        for el in elements:
            m = el.metadata.to_dict() if hasattr(el, "metadata") else {}
            gk = m.get("page_number", 1)
            groups.setdefault(gk, []).append(str(el))
        return [
            ("\n\n".join(parts), {"page_number": page})
            for page, parts in sorted(groups.items(), key=lambda kv: str(kv[0]))
        ]


ParseUnstructured = UnstructuredParser


class PypdfParser(UDF):
    """PDF text extraction, one chunk per page
    (reference: parsers.py:746 w/ optional de-hyphenation cleanup).

    Uses the pypdf package when present; otherwise the native extractor
    (:mod:`pathway_tpu.utils.pdftext` — object model, Flate streams,
    content-stream text operators, ToUnicode CMaps) so real PDFs parse
    without any external PDF dependency."""

    def __init__(self, apply_text_cleanup: bool = True):
        super().__init__()
        self.apply_text_cleanup = apply_text_cleanup

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        out = []
        for page_num, text in enumerate(_pdf_page_texts(bytes(contents))):
            if self.apply_text_cleanup:
                text = _cleanup_pdf_text(text)
            if text.strip():
                out.append((text, {"page_number": page_num + 1}))
        return out


def _pdf_page_texts(data: bytes) -> list[str]:
    try:
        from pypdf import PdfReader  # optional dependency, preferred

        import io

        reader = PdfReader(io.BytesIO(data))
        return [page.extract_text() or "" for page in reader.pages]
    except ImportError:
        from ...utils import pdftext

        doc = pdftext.PdfDocument(data)
        return [pdftext.extract_page_text(doc, p) for p in doc.pages()]


class OpenParse(UDF):
    """Structure-aware PDF parser (reference: parsers.py:235 ``OpenParse``
    — the openparse package's layout pipeline: heading detection, block
    grouping, table extraction).  Built on the native positioned-run
    extractor: headings split chunks (runs ≥ ``heading_ratio`` × the page's
    median font size), lines group into blocks by vertical gaps, and
    column-aligned blocks render as markdown tables — each chunk carries
    ``page_number``/``headings``/``kind`` metadata like the reference's
    node model."""

    def __init__(
        self,
        heading_ratio: float = 1.25,
        table_args: dict | None = None,
        **kwargs,
    ):
        super().__init__(deterministic=True)
        self.heading_ratio = heading_ratio
        self.table_args = table_args or {}

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        from ...utils import pdftext

        doc = pdftext.PdfDocument(bytes(contents))
        chunks: list[tuple[str, dict]] = []
        headings: list[str] = []
        for page_num, page in enumerate(doc.pages(), start=1):
            runs = pdftext.extract_runs(doc, page)
            if not runs:
                continue
            lines = _group_lines(runs)
            sizes = sorted(r.size for r in runs)
            median = sizes[len(sizes) // 2]
            blocks = _group_blocks(lines)
            for block in blocks:
                text_lines = [ln for ln in block if ln[2].strip()]
                if not text_lines:
                    continue
                block_size = max(ln[1] for ln in text_lines)
                body = [ln[2] for ln in text_lines]
                if (
                    block_size >= self.heading_ratio * median
                    and len(text_lines) <= 2
                ):
                    headings = [" ".join(body)]
                    chunks.append(
                        (
                            " ".join(body),
                            {
                                "page_number": page_num,
                                "kind": "heading",
                                "headings": list(headings),
                            },
                        )
                    )
                elif _looks_tabular(block):
                    chunks.append(
                        (
                            _render_table(block),
                            {
                                "page_number": page_num,
                                "kind": "table",
                                "headings": list(headings),
                            },
                        )
                    )
                else:
                    chunks.append(
                        (
                            "\n".join(body),
                            {
                                "page_number": page_num,
                                "kind": "text",
                                "headings": list(headings),
                            },
                        )
                    )
        return chunks


def _group_lines(runs) -> list[tuple[float, float, str, list]]:
    """(y, size, text, cells) per line, top-down; cells keep x positions."""
    by_y: dict[float, list] = {}
    for r in runs:
        by_y.setdefault(round(r.y / 2) * 2, []).append(r)
    lines = []
    for y, rs in sorted(by_y.items(), key=lambda kv: -kv[0]):
        rs.sort(key=lambda r: r.x)
        text = " ".join(r.text.strip() for r in rs if r.text.strip())
        cells = [(r.x, r.text.strip()) for r in rs if r.text.strip()]
        if text:
            lines.append((y, max(r.size for r in rs), text, cells))
    return lines


def _group_blocks(lines) -> list[list]:
    """Split a page's lines into blocks at vertical gaps > 1.8 line
    heights (openparse's block grouping heuristic)."""
    blocks: list[list] = []
    cur: list = []
    prev_y = None
    for y, size, text, cells in lines:
        if prev_y is not None and prev_y - y > 1.8 * size:
            if cur:
                blocks.append(cur)
            cur = []
        cur.append((y, size, text, cells))
        prev_y = y
    if cur:
        blocks.append(cur)
    return blocks


def _looks_tabular(block) -> bool:
    """≥2 rows sharing ≥2 aligned cell x-positions ⇒ a table."""
    multi = [ln for ln in block if len(ln[3]) >= 2]
    if len(multi) < 2:
        return False
    base = {round(x) for x, _ in multi[0][3]}
    aligned = sum(
        1
        for ln in multi[1:]
        if len(base & {round(x) for x, _ in ln[3]}) >= 2
    )
    return aligned >= len(multi) - 1


def _render_table(block) -> str:
    rows = [ln[3] for ln in block if ln[3]]
    md = []
    for i, cells in enumerate(rows):
        md.append("| " + " | ".join(text for _x, text in cells) + " |")
        if i == 0:
            md.append("|" + "---|" * len(cells))
    return "\n".join(md)


def _cleanup_pdf_text(text: str) -> str:
    import re

    text = re.sub(r"-\n(\w)", r"\1", text)  # de-hyphenate line breaks
    text = re.sub(r"(?<!\n)\n(?!\n)", " ", text)  # unwrap soft newlines
    return re.sub(r" {2,}", " ", text).strip()


class AutoParser(UDF):
    """Content-sniffing parser: routes each document by magic bytes —
    PDFs through the structural :class:`OpenParse` pipeline (or plain
    per-page extraction with ``structural=False``), everything else
    through UTF-8 decoding.  The no-dependency counterpart of the
    reference's auto-partitioning ``ParseUnstructured`` (parsers.py:79),
    so a watched directory can mix .txt and .pdf files."""

    def __init__(self, structural: bool = True, **kwargs):
        super().__init__(deterministic=True)
        self._pdf = OpenParse(**kwargs) if structural else PypdfParser()
        self._text = Utf8Parser()

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        raw = bytes(contents)
        if raw.startswith(b"%PDF"):
            return await self._pdf.__wrapped__(raw, **kwargs)
        return await self._text.__wrapped__(raw, **kwargs)


class _VisionParserBase(UDF):
    """Shared shape of the LLM-vision parsers (reference: parsers.py:396
    ImageParser / :569 SlideParser): describe each image/slide with a
    multimodal chat UDF and emit the description as the chunk text."""

    def __init__(self, llm, prompt: str, **kwargs):
        super().__init__(executor=udfs.async_executor())
        self.llm = llm
        self.prompt = prompt
        self.kwargs = kwargs

    async def _describe(self, b64_image: str) -> str:
        fn = getattr(self.llm, "__wrapped__", self.llm)
        messages = (
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": self.prompt},
                    {
                        "type": "image_url",
                        "image_url": {"url": f"data:image/jpeg;base64,{b64_image}"},
                    },
                ],
            },
        )
        res = fn(messages)
        import inspect

        if inspect.iscoroutine(res):
            res = await res
        return str(res)


class ImageParser(_VisionParserBase):
    """reference: parsers.py:396"""

    def __init__(self, llm, prompt: str = "Describe the image contents.", **kwargs):
        super().__init__(llm, prompt, **kwargs)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import base64

        b64 = base64.b64encode(bytes(contents)).decode()
        return [(await self._describe(b64), {})]


class SlideParser(_VisionParserBase):
    """reference: parsers.py:569 — renders pdf slides to images first
    (needs pdf2image in the environment)."""

    def __init__(self, llm, prompt: str = "Describe the slide contents.", **kwargs):
        super().__init__(llm, prompt, **kwargs)

    async def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import base64
        import io

        from pdf2image import convert_from_bytes  # optional dependency

        pages = convert_from_bytes(bytes(contents))
        out = []
        for i, img in enumerate(pages):
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            b64 = base64.b64encode(buf.getvalue()).decode()
            out.append((await self._describe(b64), {"slide_number": i + 1}))
        return out
