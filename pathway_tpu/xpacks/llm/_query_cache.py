"""Serving-plane query cache stack + CPU/TPU collaborative embedding.

Production query streams are heavily repeated and near-duplicate, so the
cheapest device tick is the one that never launches (ROADMAP item 5).
Three layers sit between ``RetrievePlane._batch`` and the device, each
independently bounded and disable-able:

* an **embedding cache** keyed on the token-id hash of the query (one
  level up from ``models/tokenizer.py`` ``TokenCache`` — POST
  tokenization, so whitespace/casing variants that tokenize identically
  hit), bounded LRU of ``PATHWAY_EMBED_CACHE`` rows.  Hits skip the
  encoder entirely; only the misses ride the device tick as a PARTIAL
  batch (a tick with 6/8 hits launches a 2-row bucket — PR 5 packed
  dispatch bucketing makes the smaller launch bit-exact, and a fused
  device-array result re-enters ``search_embedded`` combined ON DEVICE
  with the cached host rows, no host round trip for the fresh rows);

* a **result cache** keyed on ``(token-hash, k, metric, filter)`` whose
  entries carry the index freshness watermark
  (``ExternalIndexNode.commit_seq``, bumped by every flush that changes
  the corpus — PR 4's freshness plumbing grown into an exact
  invalidation signal).  A hit is served only while the index has not
  advanced past the entry's watermark; ``PATHWAY_RESULT_CACHE_STALE_S``
  is a stale-while-revalidate window — within it a stale entry is
  served as-is and the query is resubmitted in the background as a
  DEFERRED runtime item (``DeviceTickRuntime.submit(defer=True)``, PR
  12) so the entry refreshes off the latency path.  Tier migrations
  (PR 12) deliberately do NOT bump the watermark: scores are
  tier-independent by construction, and a migration storm must not
  flush the cache;

* a **WindVE-style collaborative path** (arXiv:2504.14941): when the
  INTERACTIVE queue depth exceeds ``PATHWAY_COLLAB_DEPTH``, short cold
  queries (token mass ≤ ``PATHWAY_COLLAB_MAX_TOKENS``) embed on host
  CPU — the SAME flax model applied on the CPU backend over the exact
  param tree, parity-checked against the device encoder once at first
  engagement — concurrently with the in-flight device launch instead of
  queuing behind it.

Correctness across the existing surface: the stack is bypassed entirely
while the index is restoring (PR 6), while the breaker is anything but
closed (PR 3 — BM25 answers must never be cached as authoritative, and
a half-open probe must actually probe the device), and for lexical
(``query_is_text``) indexes; caches live per serving plane, so entries
are per-encoder and per-mesh-identity (PR 8) by construction, and the
values cached are the final f32 embeddings / (key, score) rows — valid
at every ``index_dtype`` (PR 11).

Counters (``pathway_query_cache_*_total{layer=}``,
``pathway_collab_embeds_total``) feed ``/status`` via a weak-registry
metrics provider and a ``"query_cache"`` block on ``/v1/health`` gated
on this module being imported (probes never pull jax).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import warnings
import weakref
from typing import Any

import numpy as np

from ...internals.lru import BoundedLru

__all__ = [
    "EmbeddingCache",
    "ResultCache",
    "CollabEncoder",
    "QueryCacheStack",
    "build_stack",
    "query_cache_stats",
    "query_cache_status",
    "reset_query_cache_counters",
]


# ---------------------------------------------------------------------------
# knobs (garbage warns and falls back to the default — the PR 11 idiom;
# one shared parser in internals/config so every knob family warns the
# same way)
# ---------------------------------------------------------------------------

from ...internals.config import env_float as _base_env_float
from ...internals.config import env_int as _base_env_int


def _env_int(name: str, default: int, lo: int = 0) -> int:
    return _base_env_int(name, default, lo=lo)


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    return _base_env_float(name, default, lo=lo)


def embed_cache_rows() -> int:
    """``PATHWAY_EMBED_CACHE`` (default 4096; 0 disables): embedding-cache
    LRU capacity in rows."""
    return _env_int("PATHWAY_EMBED_CACHE", 4096)


def result_cache_rows() -> int:
    """``PATHWAY_RESULT_CACHE`` (default 2048; 0 disables): result-cache
    LRU capacity in entries."""
    return _env_int("PATHWAY_RESULT_CACHE", 2048)


def result_cache_stale_s() -> float:
    """``PATHWAY_RESULT_CACHE_STALE_S`` (default 0 = exact invalidation
    only): stale-while-revalidate window in seconds — a result whose
    watermark the index advanced past within this window is still
    served, with a deferred background refresh."""
    return _env_float("PATHWAY_RESULT_CACHE_STALE_S", 0.0)


def collab_depth() -> int:
    """``PATHWAY_COLLAB_DEPTH`` (default 8; 0 disables the collaborative
    path): INTERACTIVE queue depth beyond which short cold queries embed
    on host CPU instead of queuing for the device."""
    return _env_int("PATHWAY_COLLAB_DEPTH", 8)


def collab_max_tokens() -> int:
    """``PATHWAY_COLLAB_MAX_TOKENS`` (default 32): token-mass ceiling for
    a query to be eligible for the CPU collaborative path (long queries
    stay on the MXU where they are cheap per token)."""
    return _env_int("PATHWAY_COLLAB_MAX_TOKENS", 32, lo=1)


def collab_tolerance() -> float:
    """``PATHWAY_COLLAB_TOL`` (default 0.05): max |CPU − device|
    embedding divergence tolerated by the one-time parity probe before
    the collaborative path disables itself (bf16 device compute vs the
    CPU backend's rounding is the expected source)."""
    return _env_float("PATHWAY_COLLAB_TOL", 5e-2)


# ---------------------------------------------------------------------------
# process-global counters (+ /status provider, /v1/health block)
# ---------------------------------------------------------------------------

_LAYERS = ("embed", "result")
_counters_lock = threading.Lock()
_counters: dict[str, dict[str, int]] = {
    layer: {"hits": 0, "misses": 0, "stale_served": 0, "evictions": 0}
    for layer in _LAYERS
}
_collab_counters = {"embeds_total": 0, "engaged_ticks": 0, "parity_failures": 0}

#: live stacks for the health block (weak: a finished plane's stack
#: drops out with it)
_LIVE_STACKS: "weakref.WeakSet[QueryCacheStack]" = weakref.WeakSet()


def _record(layer: str, **deltas: int) -> None:
    with _counters_lock:
        c = _counters[layer]
        for key, n in deltas.items():
            c[key] += int(n)


def _record_collab(**deltas: int) -> None:
    with _counters_lock:
        for key, n in deltas.items():
            _collab_counters[key] += int(n)


def query_cache_stats() -> dict[str, Any]:
    """Counter snapshot (layer -> totals, plus the collab counters)."""
    with _counters_lock:
        snap: dict[str, Any] = {
            layer: dict(c) for layer, c in _counters.items()
        }
        snap["collab"] = dict(_collab_counters)
    for layer in _LAYERS:
        c = snap[layer]
        total = c["hits"] + c["misses"]
        c["hit_rate"] = round(c["hits"] / total, 4) if total else 0.0
    return snap


def reset_query_cache_counters() -> None:
    """Test isolation hook."""
    with _counters_lock:
        for c in _counters.values():
            for key in c:
                c[key] = 0
        for key in _collab_counters:
            _collab_counters[key] = 0


class _QueryCacheMetricsProvider:
    """``pathway_query_cache_*`` / ``pathway_collab_embeds_total``
    OpenMetrics series for the ``/status`` exposition."""

    def stats(self) -> dict:
        return query_cache_stats()

    def openmetrics_lines(self) -> list[str]:
        snap = query_cache_stats()
        lines: list[str] = []
        for family, key in (
            ("pathway_query_cache_hits_total", "hits"),
            ("pathway_query_cache_misses_total", "misses"),
            ("pathway_query_cache_stale_served_total", "stale_served"),
            ("pathway_query_cache_evictions_total", "evictions"),
        ):
            lines.append(f"# TYPE {family} counter")
            for layer in _LAYERS:
                lines.append(
                    f'{family}{{layer="{layer}"}} {snap[layer][key]}'
                )
        lines.append("# TYPE pathway_collab_embeds_total counter")
        lines.append(
            f"pathway_collab_embeds_total {snap['collab']['embeds_total']}"
        )
        return lines


#: strong module ref — monitoring's provider table is weak-valued
_provider: _QueryCacheMetricsProvider | None = None
_provider_lock = threading.Lock()


def _ensure_provider() -> None:
    global _provider
    with _provider_lock:
        if _provider is None:
            _provider = _QueryCacheMetricsProvider()
            from ...internals.monitoring import register_metrics_provider

            register_metrics_provider("query_cache", _provider)


def query_cache_status() -> dict | None:
    """Per-stack configuration + process counters for ``/v1/health``
    (None when no serving plane built a cache stack)."""
    stacks = [s for s in _LIVE_STACKS]
    if not stacks:
        return None
    out: dict[str, Any] = {"counters": query_cache_stats()}
    per_stack = {}
    for stack in stacks:
        # planes share the default "retrieve" label — disambiguate so one
        # long-lived server's stack can't shadow another's in the block
        label = stack.label
        if label in per_stack:
            label = f"{stack.label}#{stack.stack_id}"
        per_stack[label] = {
            "embed_rows": stack.embed_cache.capacity if stack.embed_cache else 0,
            "embed_used": len(stack.embed_cache) if stack.embed_cache else 0,
            "result_rows": (
                stack.result_cache.capacity if stack.result_cache else 0
            ),
            "result_used": len(stack.result_cache) if stack.result_cache else 0,
            "stale_s": stack.stale_s,
            "collab": stack.collab is not None,
            "collab_depth": stack.collab_depth,
            "collab_max_tokens": stack.collab_max_tokens,
        }
    out["planes"] = per_stack
    return out


# ---------------------------------------------------------------------------
# cache layers
# ---------------------------------------------------------------------------


class EmbeddingCache(BoundedLru):
    """Bounded LRU of token-hash -> final embedding row (np.float32).

    Stores the embeddings EXACTLY as the encoder produced them (the
    fused tick's device rows pulled to host once at fill time), so a
    hit hands the search the same values a fresh encode would — the
    partial-batch parity pin depends on it."""

    def get_many(self, keys: list) -> list:
        out, hits = super().get_many(keys)
        _record("embed", hits=hits, misses=len(keys) - hits)
        return out

    def put_many(self, items: list) -> None:
        evicted = super().put_many(items)
        if evicted:
            _record("embed", evictions=evicted)


class ResultCache(BoundedLru):
    """Bounded LRU of (token-hash, k, metric, filter) -> (node epoch,
    watermark, raw result rows).  Rows are the index's (key, score)
    pairs — the payload join happens at serve time against the LIVE doc
    payloads, so a retracted doc drops out of a cached answer the same
    way it drops out of a fresh one.

    ``get`` is the inherited one — (epoch, watermark, rows) or None; the
    HIT/MISS accounting is the caller's (a watermark mismatch is a miss
    or a stale serve, which this layer can't tell apart)."""

    def put(self, key, epoch: int, watermark: int, rows) -> None:
        evicted = super().put(key, (epoch, watermark, rows))
        if evicted:
            _record("result", evictions=evicted)


# ---------------------------------------------------------------------------
# collaborative CPU twin (WindVE)
# ---------------------------------------------------------------------------


class CollabEncoder:
    """CPU twin of a :class:`~pathway_tpu.models.encoder.SentenceEncoder`:
    the SAME flax module applied on the CPU backend over the EXACT param
    tree (copied once, lazily), so short cold queries can embed on host
    concurrently with the in-flight device launch when the INTERACTIVE
    queue is deep.

    ``pallas``/``ragged`` attention impls remap to the fused XLA kernel
    for the dense CPU apply (same numerics contract as the encoder's own
    off-TPU dense fallback); everything else runs as-is.  A one-time
    parity probe against the device encoder guards engagement — past
    ``PATHWAY_COLLAB_TOL`` the path disables itself loudly."""

    def __init__(self, encoder: Any):
        self.encoder = encoder
        self._lock = threading.Lock()
        self._apply = None
        self._params_cpu = None
        self._cpu_device = None
        #: None = not probed yet; True/False once the parity probe ran
        self.parity_ok: bool | None = None

    def _ensure_built(self):
        with self._lock:
            if self._apply is not None:
                return
            import dataclasses

            import jax

            from ...models.encoder import TransformerEncoder

            cfg = self.encoder.cfg
            if cfg.attention_impl in ("pallas", "ragged"):
                cfg = dataclasses.replace(cfg, attention_impl="fused")
            model = TransformerEncoder(cfg)
            self._cpu_device = jax.devices("cpu")[0]
            # one D2H per param, once — afterwards the twin never touches
            # the accelerator
            self._params_cpu = jax.tree_util.tree_map(
                lambda p: jax.device_put(np.asarray(p), self._cpu_device),
                self.encoder.params,
            )

            def forward(params, ids, mask):
                return model.apply({"params": params}, ids, mask)

            self._apply = jax.jit(forward)

    def encode_rows(self, ids_all: np.ndarray, mask_all: np.ndarray) -> np.ndarray:
        """Embed already-tokenized rows on the CPU backend -> [n, dim]
        f32 (normalized, like the device encoder's output).  Shapes pad
        to the shared (batch, seq) bucket grid so the twin's compile set
        stays as bounded as the device one's."""
        self._ensure_built()
        import jax

        from ...models.encoder import (
            BATCH_BUCKETS,
            SEQ_BUCKETS,
            _bucket,
            dispatch_dtype,
            pad_chunk,
        )

        n = ids_all.shape[0]
        longest = max(int(mask_all.sum(axis=1).max()), 1)
        seq = min(_bucket(longest, SEQ_BUCKETS), ids_all.shape[1])
        bb = _bucket(n, BATCH_BUCKETS)
        ids, mask, _ = pad_chunk(
            ids_all[:, :seq], mask_all[:, :seq], bb, seq,
            ids_dtype=dispatch_dtype(self.encoder.cfg.vocab_size),
        )
        dev = self._cpu_device
        out = self._apply(
            self._params_cpu,
            jax.device_put(ids, dev),
            jax.device_put(mask, dev),
        )
        return np.asarray(out, dtype=np.float32)[:n]

    def check_parity(self, device_rows: np.ndarray, ids, mask) -> bool:
        """One-time probe: |twin − device| on one query must stay within
        tolerance, else the collaborative path disables itself."""
        if self.parity_ok is not None:
            return self.parity_ok
        try:
            twin = self.encode_rows(ids, mask)
            diff = float(
                np.max(np.abs(twin - np.asarray(device_rows, dtype=np.float32)))
            )
            self.parity_ok = diff <= collab_tolerance()
            if not self.parity_ok:
                _record_collab(parity_failures=1)
                warnings.warn(
                    f"collaborative CPU embed disabled: parity probe diff "
                    f"{diff:.4g} exceeds PATHWAY_COLLAB_TOL="
                    f"{collab_tolerance():g}",
                    stacklevel=2,
                )
        except Exception as exc:  # noqa: BLE001 — never fail the tick
            self.parity_ok = False
            _record_collab(parity_failures=1)
            warnings.warn(
                f"collaborative CPU embed disabled: twin build failed "
                f"({type(exc).__name__}: {exc})",
                stacklevel=2,
            )
        return self.parity_ok


# ---------------------------------------------------------------------------
# the stack
# ---------------------------------------------------------------------------


def _token_hash(row: np.ndarray) -> bytes:
    """Key of one trimmed token-id row: whitespace/casing variants that
    tokenize identically share it (the whole point of hashing POST
    tokenization)."""
    return hashlib.blake2b(row.tobytes(), digest_size=16).digest()


_node_epochs = itertools.count(1)
_stack_ids = itertools.count(1)


def _node_epoch(node) -> int:
    """Process-unique epoch stamped per index node: commit_seq restarts
    near 0 for every engine life, so without the epoch a result cached
    at life 1's seq 5 would read as exactly fresh once life 2's counter
    reaches 5 again.  Monotonic counter, never id() (recyclable)."""
    ep = getattr(node, "_pw_query_cache_epoch", None)
    if ep is None:
        ep = next(_node_epochs)
        node._pw_query_cache_epoch = ep
    return ep


class QueryCacheStack:
    """Per-plane cache stack (see module docstring).  One instance per
    :class:`~pathway_tpu.xpacks.llm._scheduler.RetrievePlane`, so keys
    are scoped to one embedder + one index (one mesh identity, one
    metric) by construction."""

    def __init__(
        self,
        embedder: Any,
        label: str = "retrieve",
        *,
        embed_rows: int | None = None,
        result_rows: int | None = None,
        stale_s: float | None = None,
        depth: int | None = None,
        max_tokens: int | None = None,
    ):
        self.embedder = embedder
        self.label = label
        embed_rows = embed_cache_rows() if embed_rows is None else embed_rows
        result_rows = (
            result_cache_rows() if result_rows is None else result_rows
        )
        self.embed_cache = EmbeddingCache(embed_rows) if embed_rows > 0 else None
        self.result_cache = (
            ResultCache(result_rows) if result_rows > 0 else None
        )
        self.stale_s = result_cache_stale_s() if stale_s is None else stale_s
        self.collab_depth = collab_depth() if depth is None else depth
        self.collab_max_tokens = (
            collab_max_tokens() if max_tokens is None else max_tokens
        )
        self.stack_id = next(_stack_ids)
        ensure = getattr(embedder, "_ensure_encoder", None)
        self._has_encoder = ensure is not None
        self.collab: CollabEncoder | None = None
        if self._has_encoder and self.collab_depth > 0:
            self.collab = CollabEncoder(ensure())
        #: queue-depth signal (overridable in tests); reads the runtime's
        #: INTERACTIVE backlog without spawning its thread
        self._depth_fn = self._runtime_depth
        #: result keys with an in-flight deferred refresh (dedup)
        self._refreshing: set = set()
        self._refresh_lock = threading.Lock()
        _ensure_provider()
        _LIVE_STACKS.add(self)

    # -- keys ------------------------------------------------------------
    def _encoder(self):
        if not self._has_encoder:
            return None
        return self.embedder._ensure_encoder()

    def _tokenize_keys(self, texts: list[str]):
        """(token keys, ids, mask, token lengths).  Model-backed
        embedders key on the trimmed token-id row (the TokenCache makes
        the repeat tokenize a dict lookup); generic deterministic UDF
        embedders fall back to the coerced text."""
        from ._utils import coerce_str

        enc = self._encoder()
        if enc is None:
            keys = [("text", coerce_str(t)) for t in texts]
            return keys, None, None, None
        ids_all, mask_all = enc.tokenizer.encode_batch(
            [coerce_str(t) for t in texts], max_length=enc.max_length
        )
        lens = mask_all.sum(axis=1).astype(int)
        keys = [
            _token_hash(ids_all[i, : lens[i]]) for i in range(len(texts))
        ]
        return keys, ids_all, mask_all, lens

    def _runtime_depth(self) -> int:
        from ...runtime import QoS, get_runtime, runtime_enabled

        if not runtime_enabled():
            return 0
        return get_runtime().queue_depth(QoS.INTERACTIVE)

    # -- serve -----------------------------------------------------------
    def serve(self, plane, node, index, texts, specs, items):
        """The healthy vector path of ``RetrievePlane._batch`` with the
        cache stack in front: returns the raw result rows (one list of
        (key, score) per query), having launched the device encoder only
        for queries no layer could answer."""
        n = len(texts)
        tkeys, ids_all, mask_all, lens = self._tokenize_keys(texts)
        metric = getattr(index, "metric", None) or getattr(
            getattr(index, "index", None), "metric", ""
        )
        results: list = [None] * n
        pending: list[int] = list(range(n))
        # 1. result cache (exact watermark, else stale-within-window)
        if self.result_cache is not None:
            epoch_now = _node_epoch(node)
            wm_now = node.commit_seq
            pending = []
            hits = misses = stale = 0
            refresh: list[tuple] = []
            for i in range(n):
                k, flt = specs[i]
                rkey = (tkeys[i], int(k), metric, flt)
                ent = self.result_cache.get(rkey)
                if ent is None:
                    misses += 1
                    pending.append(i)
                    continue
                epoch, watermark, rows = ent
                if epoch == epoch_now and watermark == wm_now:
                    hits += 1
                    results[i] = rows
                    continue
                # guard BEFORE the stale_age scan: with the window
                # disabled (the default) a watermark mismatch must stay
                # a plain miss without paying the per-query history walk
                age = (
                    node.stale_age(watermark)
                    if self.stale_s > 0 and epoch == epoch_now
                    else None
                )
                if (
                    age is not None
                    and age <= self.stale_s
                    and self._can_refresh()
                ):
                    stale += 1
                    results[i] = rows
                    refresh.append((rkey, items[i]))
                else:
                    misses += 1
                    pending.append(i)
            _record("result", hits=hits, misses=misses, stale_served=stale)
            if refresh:
                self._schedule_refresh(plane, refresh)
        if not pending:
            return results
        # 2. embedding cache + 3. collab split + device launch for the rest
        wm_entry = node.commit_seq  # BEFORE the index read: a flush that
        # lands mid-search makes the entry conservatively old (a future
        # lookup misses), never wrongly fresh
        qvecs, collab_js = self._embed_pending(
            plane, texts, tkeys, ids_all, mask_all, lens, pending
        )
        from ...internals.flight_recorder import batch_stage

        with batch_stage("search"):
            raw = index.search_embedded(
                qvecs, [specs[i] for i in pending]
            )
        if self.result_cache is not None:
            for j, i in enumerate(pending):
                if j in collab_js:
                    # twin-embedded answers are tolerance-bounded, not
                    # bit-exact: serve them (that's the WindVE deal under
                    # pressure) but never freeze them into the cache —
                    # a later calm-queue repeat must recompute on device
                    continue
                k, flt = specs[i]
                self.result_cache.put(
                    (tkeys[i], int(k), metric, flt),
                    _node_epoch(node), wm_entry, raw[j],
                )
        for j, i in enumerate(pending):
            results[i] = raw[j]
        return results

    def _embed_pending(self, plane, texts, tkeys, ids_all, mask_all, lens,
                       pending):
        """Embeddings for the result-cache misses: cached rows fill from
        the embedding cache, short cold rows may take the CPU twin under
        queue pressure, the rest launch on the device as a partial
        batch.  Returns ``(query batch, collab-served positions)``: the
        [len(pending), dim] batch — a DEVICE array when fresh rows came
        back fused (cached host rows join it on device; the fresh rows
        never round-trip to host except once, to fill the cache) — plus
        the set of pending positions whose row came from the CPU twin
        (tolerance-bounded: the caller must not cache their results)."""
        from ._scheduler import _batch_embed, _batch_embed_device
        from ...internals.flight_recorder import batch_stage

        cached_rows = (
            self.embed_cache.get_many([tkeys[i] for i in pending])
            if self.embed_cache is not None
            else [None] * len(pending)
        )
        miss_pos = [j for j, row in enumerate(cached_rows) if row is None]
        collab_pos: list[int] = []
        if (
            miss_pos
            and self.collab is not None
            and self.collab.parity_ok is not False
            and ids_all is not None
            and self._depth_fn() > self.collab_depth
        ):
            collab_pos = [
                j
                for j in miss_pos
                if int(lens[pending[j]]) <= self.collab_max_tokens
            ]
        collab_set = set(collab_pos)
        device_pos = [j for j in miss_pos if j not in collab_set]
        collab_out: dict = {}
        dev_embs = None
        dev_host = None
        with batch_stage("embed"):
            collab_thread = None
            if collab_pos:
                rows_idx = [pending[j] for j in collab_pos]
                c_ids, c_mask = ids_all[rows_idx], mask_all[rows_idx]
                if self.collab.parity_ok is None:
                    # one-time probe: the FIRST engagement embeds its rows
                    # on the device too and compares — collab serves only
                    # once the twin proved itself
                    probe_rows = _batch_embed(plane.embedder,
                                              [texts[i] for i in rows_idx])
                    if self.collab.check_parity(
                        np.asarray(probe_rows, dtype=np.float32), c_ids, c_mask
                    ):
                        _record_collab(engaged_ticks=1)
                    collab_out["rows"] = np.asarray(probe_rows, np.float32)
                    collab_pos_run = []
                else:
                    collab_pos_run = collab_pos

                    def _twin():
                        try:
                            collab_out["rows"] = self.collab.encode_rows(
                                c_ids, c_mask
                            )
                        except Exception as exc:  # noqa: BLE001 — fall back
                            collab_out["error"] = exc

                    collab_thread = threading.Thread(
                        target=_twin, name="pw-collab-embed", daemon=True
                    )
                    collab_thread.start()
            else:
                collab_pos_run = []
            if device_pos:
                dev_texts = [texts[pending[j]] for j in device_pos]
                dev_embs = _batch_embed_device(plane.embedder, dev_texts)
                if dev_embs is None:
                    dev_host = np.asarray(
                        _batch_embed(plane.embedder, dev_texts),
                        dtype=np.float32,
                    )
            if collab_thread is not None:
                collab_thread.join()
                if "error" in collab_out:
                    # twin failed mid-flight: embed those rows on device
                    # after all (correctness over the concurrency win)
                    self.collab.parity_ok = False
                    _record_collab(parity_failures=1)
                    fb = np.asarray(
                        _batch_embed(
                            plane.embedder,
                            [texts[pending[j]] for j in collab_pos_run],
                        ),
                        dtype=np.float32,
                    )
                    collab_out["rows"] = fb
                elif collab_pos_run:
                    _record_collab(
                        embeds_total=len(collab_pos_run), engaged_ticks=1
                    )
        # every position the collab branch produced rows for is
        # non-cacheable: post-probe twin rows are tolerance-bounded, and
        # the probe tick's / twin-error fallback's rows come from the
        # HOST `_batch_embed` path — on a fused plane those differ from
        # the device encode at ~1e-7, enough to swap a near-tie rank, so
        # freezing their results would break the cached-vs-off bit-exact
        # contract for every later calm-queue repeat
        collab_served = set(collab_pos)
        # assemble the query batch.  Rows pad to the SAME power-of-two
        # batch-bucket grid the fused tick's encode_padded uses: the
        # search (and the device combine below) then compile against the
        # bounded bucket shapes instead of one program per distinct
        # hit/miss occupancy — pad rows are discarded by the search's
        # n_valid contract exactly like fused dispatch pads
        from ...models.encoder import BATCH_BUCKETS, _bucket

        dim = None
        for row in cached_rows:
            if row is not None:
                dim = len(row)
                break
        if dim is None and "rows" in collab_out:
            dim = collab_out["rows"].shape[1]
        if dim is None and dev_host is not None:
            dim = dev_host.shape[1]
        if dim is None and dev_embs is not None:
            dim = int(dev_embs.shape[1])
        n_p = len(pending)
        qb = _bucket(n_p, BATCH_BUCKETS) if n_p <= BATCH_BUCKETS[-1] else n_p
        base = np.zeros((qb, dim), dtype=np.float32)
        for j, row in enumerate(cached_rows):
            if row is not None:
                base[j] = row
        if "rows" in collab_out:
            for jj, j in enumerate(collab_pos):
                base[j] = collab_out["rows"][jj]
        # only DEVICE-encoder rows ever fill the embedding cache: collab
        # twin rows (and the probe tick's host-path rows) are tolerance-
        # bounded, not bit-exact — caching one would freeze its divergence
        # into every later hit, including under zero queue pressure.  The
        # twin absorbs pressure transiently; the cache fills from the
        # device once the queue drains
        fill_items = []
        if dev_host is not None:
            for jj, j in enumerate(device_pos):
                base[j] = dev_host[jj]
                if self.embed_cache is not None:
                    fill_items.append((tkeys[pending[j]], dev_host[jj].copy()))
            if fill_items:
                self.embed_cache.put_many(fill_items)
            # the fresh rows came from the HOST embed path, so the
            # cache-off tick would have searched a host array — match it
            return base[:n_p], collab_served
        if dev_embs is not None:
            # fused path: combine ON DEVICE — cached/collab host rows ride
            # one H2D, the fresh device rows never leave the device for
            # the search (one bounded D2H below only fills the cache).
            # The scatter index pads to the fresh batch's bucket with an
            # out-of-bounds slot (mode="drop"), so the combine compiles
            # once per (bucket, bucket) pair, not per occupancy
            import jax.numpy as jnp

            fresh = jnp.asarray(dev_embs).astype(jnp.float32)
            idx = np.full((int(fresh.shape[0]),), qb, dtype=np.int32)
            idx[: len(device_pos)] = device_pos
            q = jnp.asarray(base).at[jnp.asarray(idx)].set(
                fresh, mode="drop"
            )
            if self.embed_cache is not None:
                host_fresh = np.asarray(fresh, dtype=np.float32)
                for jj, j in enumerate(device_pos):
                    fill_items.append(
                        (tkeys[pending[j]], host_fresh[jj].copy())
                    )
            if fill_items:
                self.embed_cache.put_many(fill_items)
            return q, collab_served
        if fill_items:
            self.embed_cache.put_many(fill_items)
        if self._fused_serving():
            # no fresh device rows this tick, but the cache-off tick
            # would have searched DEVICE queries (encode_padded →
            # _prep_queries normalizes on device) — hand the cached rows
            # over as a device array so hits are bit-exact with misses
            import jax.numpy as jnp

            return jnp.asarray(base), collab_served
        return base[:n_p], collab_served

    def _fused_serving(self) -> bool:
        """Would ``_batch_embed_device`` take the fused path for this
        embedder?  Decides whether cached rows re-enter the search as a
        device array (bit-exact with the fused tick) or a host one."""
        from ._scheduler import _env_flag

        if not _env_flag("PATHWAY_FUSED_SERVING", True):
            return False
        enc = self._encoder()
        return enc is not None and getattr(enc, "encode_padded", None) is not None

    # -- stale-while-revalidate ------------------------------------------
    def _can_refresh(self) -> bool:
        from ...runtime import runtime_enabled

        return runtime_enabled()

    def _schedule_refresh(self, plane, refresh: list[tuple]) -> None:
        """Resubmit stale-served queries as DEFERRED runtime items
        (fire-and-forget, BULK_INGEST class — a cache refresh must not
        displace interactive work); at most one in flight per key.  The
        payload carries the result key so EVERY exit of the deferred
        batch (including the bypass paths: breaker open, node restoring)
        can release the in-flight marker — a leaked key would disable
        revalidation for that query for the plane's lifetime."""
        from ...runtime import QoS, get_runtime

        rt = get_runtime()
        group = plane._cache_refresh_group()
        for rkey, item in refresh:
            with self._refresh_lock:
                if rkey in self._refreshing:
                    continue
                self._refreshing.add(rkey)
            try:
                rt.submit(
                    group, (*item, rkey), qos=QoS.BULK_INGEST, defer=True,
                    sheddable=False,
                )
            except Exception:  # noqa: BLE001 — refresh is best-effort
                with self._refresh_lock:
                    self._refreshing.discard(rkey)

    def release_refresh(self, rkeys: list) -> None:
        """Drop the in-flight markers for a deferred batch, however it
        ended (computed, bypassed, or failed)."""
        with self._refresh_lock:
            for rkey in rkeys:
                self._refreshing.discard(rkey)

    def refresh(self, plane, node, index, items, rkeys) -> None:
        """Deferred-refresh handler body: recompute WITHOUT reading the
        result cache (a read would hit the same stale entry and loop)
        and write the fresh rows back under the keys the stale serve
        recorded.  The caller releases the in-flight markers."""
        from ...testing import faults as _faults

        if _faults.enabled:
            # chaos site cache.refresh: a raise here is contained by the
            # scheduler's refresh-batch guard (which logs and ALWAYS
            # releases the in-flight markers), so a failed recompute just
            # leaves the stale entry serving out its window
            _faults.perturb("cache.refresh")
        texts = [q for q, _, _ in items]
        specs = [(k, flt) for _, k, flt in items]
        tkeys, ids_all, mask_all, lens = self._tokenize_keys(texts)
        wm_entry = node.commit_seq
        epoch = _node_epoch(node)
        qvecs, collab_js = self._embed_pending(
            plane, texts, tkeys, ids_all, mask_all, lens,
            list(range(len(items))),
        )
        raw = index.search_embedded(qvecs, specs)
        if self.result_cache is not None:
            for i, rkey in enumerate(rkeys):
                if i in collab_js:
                    # a twin-embedded refresh must not freeze its
                    # tolerance-bounded answer; the marker release lets a
                    # later stale serve re-schedule on a calmer queue
                    continue
                self.result_cache.put(rkey, epoch, wm_entry, raw[i])


def build_stack(embedder: Any, label: str = "retrieve") -> QueryCacheStack | None:
    """Stack for one serving plane, or None when every layer is disabled
    or the embedder can't be keyed (non-deterministic UDF with no
    tokenizer — caching its output would freeze nondeterminism into
    answers)."""
    if embedder is None:
        return None
    has_encoder = getattr(embedder, "_ensure_encoder", None) is not None
    if not has_encoder and not getattr(embedder, "deterministic", False):
        return None
    embed_rows = embed_cache_rows()
    result_rows = result_cache_rows()
    depth = collab_depth() if has_encoder else 0
    if embed_rows <= 0 and result_rows <= 0 and depth <= 0:
        return None
    return QueryCacheStack(embedder, label=label)
