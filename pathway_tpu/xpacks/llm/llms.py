"""Chat-model UDFs.

reference: python/pathway/xpacks/llm/llms.py — ``BaseChat``:27,
``OpenAIChat``:84, ``LiteLLMChat``:313, ``HFPipelineChat``:441,
``CohereChat``:544, ``prompt_chat_single_qa``:686.

Chats take a tuple/list of ``{"role": ..., "content": ...}`` dicts (or a
Json of the same) and return the completion string.  API chats are async
UDFs with capacity/retry/cache; ``HFPipelineChat`` runs a local
transformers pipeline (torch CPU), and ``JaxPipelineChat`` is its
TPU-native counterpart — the flax causal-LM with jitted prefill +
scan + kv-cache decoding (models/decoder.py).
"""

from __future__ import annotations

import json as _json
import logging
import uuid
from typing import Any

from ...internals import udfs
from ...internals.expression import ColumnExpression, MakeTupleExpression
from ...internals.udfs import UDF
from ...internals.value import Json
from ._utils import check_provider_accepts_arg, coerce_str, prep_message_log

logger = logging.getLogger(__name__)


_SECRET_KEY_MARKERS = ("key", "secret", "token", "password", "credential")


def _log_request(provider: str, kwargs: dict, messages: list, verbose: bool) -> str:
    """Structured request log line (reference: llms.py:270-273).
    Credential-shaped kwargs are redacted — providers like litellm take
    api_key/aws_secret_access_key as plain call kwargs."""
    msg_id = str(uuid.uuid4())[-8:]
    logged = {
        k: ("<redacted>" if any(m in k.lower() for m in _SECRET_KEY_MARKERS) else str(v))
        for k, v in kwargs.items()
    }
    logger.info(
        _json.dumps(
            {
                "_type": f"{provider}_chat_request",
                "kwargs": logged,
                "id": msg_id,
                "messages": prep_message_log(messages, verbose),
            },
            ensure_ascii=False,
        )
    )
    return msg_id


def _log_response(provider: str, msg_id: str, response: str | None, verbose: bool) -> None:
    text = response or ""
    logger.info(
        _json.dumps(
            {
                "_type": f"{provider}_chat_response",
                "response": text if verbose else text[: min(50, len(text))] + "...",
                "id": msg_id,
            },
            ensure_ascii=False,
        )
    )

__all__ = [
    "BaseChat",
    "OpenAIChat",
    "LiteLLMChat",
    "HFPipelineChat",
    "JaxPipelineChat",
    "CohereChat",
    "prompt_chat_single_qa",
]


def _messages_to_list(messages: Any) -> list[dict]:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, (dict, str)):
        messages = [messages]
    out = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        if isinstance(m, str):
            m = {"role": "user", "content": m}
        out.append(dict(m))
    return out


class BaseChat(UDF):
    """reference: llms.py:27"""

    def _accepts_call_arg(self, arg_name: str) -> bool:
        """Whether the underlying provider accepts ``arg_name`` as a call
        kwarg (reference: llms.py BaseChat._accepts_call_arg)."""
        return False


class OpenAIChat(BaseChat):
    """reference: llms.py:84"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "gpt-4o-mini",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **openai_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(openai_kwargs)
        self.model = model
        if model is not None:
            self.kwargs["model"] = model
        # constructor-level credentials are client config, not call args
        self._creds = {
            k: self.kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in self.kwargs
        }
        self._client = None
        self._override_clients: dict = {}

    def _accepts_call_arg(self, arg_name: str) -> bool:
        if self.model is None:
            return False
        return check_provider_accepts_arg(self.model, "openai", arg_name)

    def _ensure_client(self, **overrides):
        import openai  # optional dependency

        if not overrides:
            if self._client is None:
                self._client = openai.AsyncOpenAI(**self._creds)
            return self._client
        # per-call credentials: cache per distinct override set — a fresh
        # client per row would leak httpx connections under load
        key = tuple(sorted(overrides.items()))
        client = self._override_clients.get(key)
        if client is None:
            client = openai.AsyncOpenAI(**{**self._creds, **overrides})
            self._override_clients[key] = client
        return client

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        kwargs = {**self.kwargs, **kwargs}
        verbose = bool(kwargs.pop("verbose", False))
        # per-call credentials (reference llms.py:262-264) select a
        # per-override cached client
        overrides = {
            k: kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in kwargs
        }
        client = self._ensure_client(**overrides)
        msgs = _messages_to_list(messages)
        msg_id = _log_request("openai", kwargs, msgs, verbose)
        ret = await client.chat.completions.create(messages=msgs, **kwargs)
        response = ret.choices[0].message.content
        _log_response("openai", msg_id, response, verbose)
        return response


class LiteLLMChat(BaseChat):
    """reference: llms.py:313"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **litellm_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(litellm_kwargs)
        self.model = model
        if model is not None:
            self.kwargs["model"] = model

    def _accepts_call_arg(self, arg_name: str) -> bool:
        if self.model is None:
            return False
        provider = self.model.split("/", 1)[0] if "/" in self.model else "openai"
        return check_provider_accepts_arg(self.model, provider, arg_name)

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        import litellm  # optional dependency

        kwargs = {**self.kwargs, **kwargs}
        verbose = bool(kwargs.pop("verbose", False))
        msgs = _messages_to_list(messages)
        msg_id = _log_request("litellm", kwargs, msgs, verbose)
        ret = await litellm.acompletion(messages=msgs, **kwargs)
        response = ret.choices[0]["message"]["content"]
        _log_response("litellm", msg_id, response, verbose)
        return response


class HFPipelineChat(BaseChat):
    """Local transformers text-generation pipeline
    (reference: llms.py:441 — the pipeline is built once and shared; calls
    run on the sync executor since the model itself is the bottleneck)."""

    def __init__(
        self,
        model: str | None = "gpt2",
        call_kwargs: dict = {},
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        super().__init__(executor=udfs.async_executor())
        self.model = model
        self.call_kwargs = dict(call_kwargs)
        self.device = device
        self.pipeline_kwargs = dict(pipeline_kwargs)
        self._pipeline = None

    def _ensure_pipeline(self):
        if self._pipeline is None:
            import transformers

            self._pipeline = transformers.pipeline(
                "text-generation", model=self.model, device=self.device,
                **self.pipeline_kwargs,
            )
        return self._pipeline

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        tokenizer = self._ensure_pipeline().tokenizer
        tokens = tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
        return tokenizer.convert_tokens_to_string(tokens)

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        pipe = self._ensure_pipeline()
        msgs = _messages_to_list(messages)
        kwargs = {**self.call_kwargs, **kwargs}
        if getattr(pipe.tokenizer, "chat_template", None) is not None:
            output = pipe(msgs, return_full_text=False, **kwargs)
            result = output[0]["generated_text"]
        else:
            prompt = "\n".join(m["content"] for m in msgs)
            output = pipe(prompt, return_full_text=False, **kwargs)
            result = output[0]["generated_text"]
        return coerce_str(result)


class CohereChat(BaseChat):
    """reference: llms.py:544 — returns (response, cited docs) tuple."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "command",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **cohere_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(cohere_kwargs)
        self.model = model
        if model is not None:
            self.kwargs["model"] = model

    def _accepts_call_arg(self, arg_name: str) -> bool:
        if self.model is None:
            return False
        return check_provider_accepts_arg(self.model, "cohere", arg_name)

    async def __wrapped__(self, messages, docs, **kwargs) -> tuple:
        import cohere  # optional dependency

        kwargs = {**self.kwargs, **kwargs}
        verbose = bool(kwargs.pop("verbose", False))
        api_key = kwargs.pop("api_key", None)
        msgs = _messages_to_list(messages)
        if isinstance(docs, Json):
            docs = docs.value
        client = cohere.AsyncClient(api_key=api_key) if api_key else cohere.AsyncClient()
        message = msgs[-1]["content"]
        chat_history = msgs[:-1]
        msg_id = _log_request("cohere", kwargs, msgs, verbose)
        ret = await client.chat(
            message=message, chat_history=chat_history, documents=docs,
            **kwargs,
        )
        cited_docs = [dict(c.__dict__) for c in (ret.citations or [])]
        _log_response("cohere", msg_id, ret.text, verbose)
        return ret.text, cited_docs


def prompt_chat_single_qa(question: ColumnExpression) -> ColumnExpression:
    """Wrap a question column into a single-message chat tuple
    (reference: llms.py:686)."""
    from ...internals.expression import ApplyExpression, smart_wrap

    def to_msg(q) -> Json:
        return Json([{"role": "user", "content": coerce_str(q)}])

    return ApplyExpression(to_msg, Json, smart_wrap(question))


class JaxPipelineChat(BaseChat):
    """Local causal-LM chat on TPU (models/decoder.py CausalLM): the
    jit-compiled prefill + scan + kv-cache counterpart of the
    reference's torch ``HFPipelineChat`` (llms.py:441).  ``model``
    resolves a local GPT-2-family checkpoint; pass ``causal_lm=`` for a
    ready :class:`pathway_tpu.models.decoder.CausalLM`."""

    def __init__(
        self,
        model: str | None = "gpt2",
        *,
        causal_lm: Any = None,
        call_kwargs: dict = {},
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        **init_kwargs,
    ):
        super().__init__(executor=udfs.async_executor(), deterministic=True)
        self.model = model
        self._lm = causal_lm
        self.call_kwargs = dict(call_kwargs)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self._init_kwargs = init_kwargs

    def _ensure_lm(self):
        if self._lm is None:
            from ...models.decoder import CausalLM

            self._lm = CausalLM(self.model, **self._init_kwargs)
        return self._lm

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return arg_name in (
            "max_new_tokens", "temperature", "seed", "top_k", "top_p"
        )

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        import asyncio

        lm = self._ensure_lm()
        kwargs = {**self.call_kwargs, **kwargs}
        msgs = _messages_to_list(messages)
        prompt = "\n".join(coerce_str(m.get("content", "")) for m in msgs)

        def _gen() -> str:
            [text] = lm.generate(
                [prompt],
                max_new_tokens=int(
                    kwargs.get("max_new_tokens", self.max_new_tokens)
                ),
                temperature=float(kwargs.get("temperature", self.temperature)),
                seed=int(kwargs.get("seed", 0)),
                top_k=int(kwargs.get("top_k", 0)),
                top_p=float(kwargs.get("top_p", 1.0)),
            )
            return text

        # compile + device generation are seconds-long synchronous work;
        # run off the event loop so concurrent async chats keep flowing
        return await asyncio.to_thread(_gen)
