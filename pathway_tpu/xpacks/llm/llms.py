"""Chat-model UDFs.

reference: python/pathway/xpacks/llm/llms.py — ``BaseChat``:27,
``OpenAIChat``:84, ``LiteLLMChat``:313, ``HFPipelineChat``:441,
``CohereChat``:544, ``prompt_chat_single_qa``:686.

Chats take a tuple/list of ``{"role": ..., "content": ...}`` dicts (or a
Json of the same) and return the completion string.  API chats are async
UDFs with capacity/retry/cache; ``HFPipelineChat`` runs a local
transformers pipeline (torch CPU in this image — a flax causal-LM serving
path is the models/ roadmap item).
"""

from __future__ import annotations

from typing import Any

from ...internals import udfs
from ...internals.expression import ColumnExpression, MakeTupleExpression
from ...internals.udfs import UDF
from ...internals.value import Json
from ._utils import coerce_str

__all__ = [
    "BaseChat",
    "OpenAIChat",
    "LiteLLMChat",
    "HFPipelineChat",
    "CohereChat",
    "prompt_chat_single_qa",
]


def _messages_to_list(messages: Any) -> list[dict]:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, (dict, str)):
        messages = [messages]
    out = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        if isinstance(m, str):
            m = {"role": "user", "content": m}
        out.append(dict(m))
    return out


class BaseChat(UDF):
    """reference: llms.py:27"""

    def _accepts_call_arg(self, arg_name: str) -> bool:
        """Whether the underlying provider accepts ``arg_name`` as a call
        kwarg (reference: llms.py BaseChat._accepts_call_arg)."""
        return False


class OpenAIChat(BaseChat):
    """reference: llms.py:84"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "gpt-4o-mini",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **openai_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(openai_kwargs)
        self.model = model
        if model is not None:
            self.kwargs["model"] = model
        self._client = None

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return arg_name in (
            "model",
            "temperature",
            "max_tokens",
            "top_p",
            "logit_bias",
            "stop",
            "seed",
            "response_format",
        )

    def _ensure_client(self):
        if self._client is None:
            import openai  # optional dependency

            self._client = openai.AsyncOpenAI(
                **{
                    k: self.kwargs.pop(k)
                    for k in ("api_key", "base_url", "organization")
                    if k in self.kwargs
                }
            )
        return self._client

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        client = self._ensure_client()
        kwargs = {**self.kwargs, **kwargs}
        ret = await client.chat.completions.create(
            messages=_messages_to_list(messages), **kwargs
        )
        return ret.choices[0].message.content


class LiteLLMChat(BaseChat):
    """reference: llms.py:313"""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **litellm_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(litellm_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return arg_name in ("model", "temperature", "max_tokens", "top_p", "stop")

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        import litellm  # optional dependency

        ret = await litellm.acompletion(
            messages=_messages_to_list(messages), **{**self.kwargs, **kwargs}
        )
        return ret.choices[0]["message"]["content"]


class HFPipelineChat(BaseChat):
    """Local transformers text-generation pipeline
    (reference: llms.py:441 — the pipeline is built once and shared; calls
    run on the sync executor since the model itself is the bottleneck)."""

    def __init__(
        self,
        model: str | None = "gpt2",
        call_kwargs: dict = {},
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        super().__init__(executor=udfs.async_executor())
        self.model = model
        self.call_kwargs = dict(call_kwargs)
        self.device = device
        self.pipeline_kwargs = dict(pipeline_kwargs)
        self._pipeline = None

    def _ensure_pipeline(self):
        if self._pipeline is None:
            import transformers

            self._pipeline = transformers.pipeline(
                "text-generation", model=self.model, device=self.device,
                **self.pipeline_kwargs,
            )
        return self._pipeline

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        tokenizer = self._ensure_pipeline().tokenizer
        tokens = tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
        return tokenizer.convert_tokens_to_string(tokens)

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        pipe = self._ensure_pipeline()
        msgs = _messages_to_list(messages)
        kwargs = {**self.call_kwargs, **kwargs}
        if getattr(pipe.tokenizer, "chat_template", None) is not None:
            output = pipe(msgs, return_full_text=False, **kwargs)
            result = output[0]["generated_text"]
        else:
            prompt = "\n".join(m["content"] for m in msgs)
            output = pipe(prompt, return_full_text=False, **kwargs)
            result = output[0]["generated_text"]
        return coerce_str(result)


class CohereChat(BaseChat):
    """reference: llms.py:544 — returns (response, cited docs) tuple."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "command",
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **cohere_kwargs,
    ):
        super().__init__(
            executor=udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.kwargs = dict(cohere_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return arg_name in ("model", "temperature", "max_tokens")

    async def __wrapped__(self, messages, docs, **kwargs) -> tuple:
        import cohere  # optional dependency

        msgs = _messages_to_list(messages)
        if isinstance(docs, Json):
            docs = docs.value
        client = cohere.AsyncClient()
        message = msgs[-1]["content"]
        chat_history = msgs[:-1]
        ret = await client.chat(
            message=message, chat_history=chat_history, documents=docs,
            **{**self.kwargs, **kwargs},
        )
        cited_docs = [dict(c.__dict__) for c in (ret.citations or [])]
        return ret.text, cited_docs


def prompt_chat_single_qa(question: ColumnExpression) -> ColumnExpression:
    """Wrap a question column into a single-message chat tuple
    (reference: llms.py:686)."""
    from ...internals.expression import ApplyExpression, smart_wrap

    def to_msg(q) -> Json:
        return Json([{"role": "user", "content": coerce_str(q)}])

    return ApplyExpression(to_msg, Json, smart_wrap(question))
