"""Shared document pipeline: sources → parse → flatten → post-process →
split → flatten (+ stats reduce).

One implementation behind both ``VectorStoreServer`` (vector_store.py:227
in the reference) and ``DocumentStore`` (document_store.py:286) — the
reference duplicates this pipeline across the two classes.
"""

from __future__ import annotations

from typing import Callable, Iterable

from ...internals import dtype as dt
from ...internals import reducers
from ...internals.expression import ApplyExpression
from ...internals.table import Table
from ...internals.udfs import UDF
from ...internals.value import Json
from ._utils import coerce_str

__all__ = ["build_document_pipeline", "component_expr", "merge_meta"]


def component_expr(component: Callable, *args):
    """Parser/splitter slot: a UDF builds its own apply expression; a plain
    callable becomes a deterministic row-wise apply returning chunk lists."""
    if isinstance(component, UDF):
        return component(*args)
    return ApplyExpression(component, dt.List(dt.ANY), *args)


def merge_meta(pair, file_meta) -> Json:
    """Chunk metadata overlaid on the source file's metadata."""
    chunk_meta = pair[1]
    meta = (
        dict(file_meta.value) if isinstance(file_meta, Json) else dict(file_meta or {})
    )
    if isinstance(chunk_meta, Json):
        chunk_meta = chunk_meta.value
    meta.update(chunk_meta or {})
    return Json(meta)


def _post_process_chain(post_processors: Iterable[Callable]):
    def process(text, metadata):
        if isinstance(metadata, Json):
            metadata = dict(metadata.value)
        for pp in post_processors:
            text, metadata = pp(text, metadata)
        return text, metadata

    return process


def build_document_pipeline(
    docs_tables: list[Table],
    parser: Callable,
    splitter: Callable,
    doc_post_processors: list[Callable],
) -> dict:
    if not docs_tables:
        raise ValueError(
            "Please provide at least one data source, e.g. read files from disk"
        )
    docs = docs_tables[0]
    if len(docs_tables) > 1:
        docs = docs.concat_reindex(*docs_tables[1:])
    if "_metadata" not in docs.column_names():
        docs = docs.select(
            data=docs.data,
            _metadata=ApplyExpression(lambda d: Json({}), Json, docs.data),
        )

    parsed = docs.select(
        _parsed=component_expr(parser, docs.data), _metadata=docs["_metadata"]
    )
    parsed = parsed.flatten(parsed["_parsed"])
    parsed_docs = parsed.select(
        text=ApplyExpression(lambda p: coerce_str(p[0]), dt.STR, parsed["_parsed"]),
        metadata=ApplyExpression(
            merge_meta, Json, parsed["_parsed"], parsed["_metadata"]
        ),
    )

    if doc_post_processors:
        chain = _post_process_chain(doc_post_processors)

        def post(text, metadata):
            new_text, new_meta = chain(text, metadata)
            return (coerce_str(new_text), Json(new_meta))

        pp = parsed_docs.select(
            _pair=ApplyExpression(
                post, dt.Tuple(dt.STR, dt.JSON), parsed_docs.text, parsed_docs.metadata
            )
        )
        parsed_docs = pp.select(
            text=ApplyExpression(lambda p: p[0], dt.STR, pp["_pair"]),
            metadata=ApplyExpression(lambda p: p[1], dt.JSON, pp["_pair"]),
        )

    chunked = parsed_docs.select(
        _chunks=component_expr(splitter, parsed_docs.text),
        metadata=parsed_docs.metadata,
    )
    chunked = chunked.flatten(chunked["_chunks"])
    chunked_docs = chunked.select(
        text=ApplyExpression(lambda c: coerce_str(c[0]), dt.STR, chunked["_chunks"]),
        metadata=ApplyExpression(
            merge_meta, Json, chunked["_chunks"], chunked.metadata
        ),
    )

    stats = parsed_docs.reduce(
        count=reducers.count(),
        last_modified=reducers.max(
            ApplyExpression(
                lambda m: (m.value or {}).get("modified_at"), dt.Optional(dt.INT),
                parsed_docs.metadata,
            )
        ),
        last_indexed=reducers.max(
            ApplyExpression(
                lambda m: (m.value or {}).get("seen_at"), dt.Optional(dt.INT),
                parsed_docs.metadata,
            )
        ),
    )
    return dict(
        docs=docs, parsed_docs=parsed_docs, chunked_docs=chunked_docs, stats=stats
    )
