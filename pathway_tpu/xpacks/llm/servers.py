"""REST servers for the LLM xpack.

reference: python/pathway/xpacks/llm/servers.py — ``BaseRestServer``:25
(``serve``), ``DocumentStoreServer``:92, ``QARestServer``:140,
``QASummaryRestServer``:193, ``serve_callable``:227.
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals.schema import Schema, SchemaMetaclass, schema_from_types
from ...internals.table import Table
from ...io.http import EndpointDocumentation, PathwayWebserver, rest_connector

__all__ = [
    "BaseRestServer",
    "DocumentStoreServer",
    "QARestServer",
    "QASummaryRestServer",
    "serve_callable",
]


class BaseRestServer:
    """reference: servers.py:25"""

    def __init__(self, host: str, port: int, **rest_kwargs):
        self.webserver = PathwayWebserver(host=host, port=port)
        self.rest_kwargs = rest_kwargs

    def serve(
        self,
        route: str,
        schema: SchemaMetaclass,
        handler: Callable[[Table], Table],
        documentation: EndpointDocumentation | None = None,
        **additional_endpoint_kwargs,
    ) -> None:
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            methods=("GET", "POST"),
            schema=schema,
            delete_completed_queries=True,
            documentation=documentation,
            **{**self.rest_kwargs, **additional_endpoint_kwargs},
        )
        writer(handler(queries))

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        persistence_config: Any = None,
        **kwargs,
    ):
        """reference: servers.py run — wires UDF caching persistence.
        An explicit ``persistence_config`` (e.g. the durable
        OPERATOR_PERSISTING recovery plane) takes precedence over the
        in-memory UDF cache."""
        from ._utils import run_with_cache

        return run_with_cache(
            threaded=threaded,
            with_cache=with_cache,
            cache_backend=cache_backend,
            terminate_on_error=terminate_on_error,
            persistence_config=persistence_config,
        )

    run_server = run


class DocumentStoreServer(BaseRestServer):
    """reference: servers.py:92

    With the serving scheduler enabled (default), ``/v1/retrieve``
    answers off the shared cross-request scheduler (fused embed→search,
    deadline shedding) when the store exposes a plane for it; hybrid or
    embedder-less stores keep the engine-routed endpoint.  Under the
    unified device-tick runtime (``PATHWAY_RUNTIME=1``, default) those
    ticks run as ``INTERACTIVE``-class work on the shared QoS executor,
    ahead of engine-plane rerank/embed micro-batches (``LLM_RERANK``)
    and bulk ingest (``BULK_INGEST``).
    """

    def __init__(
        self,
        host: str,
        port: int,
        document_store,
        with_scheduler: bool | None = None,
        deadline_ms: float | None = None,
        **rest_kwargs,
    ):
        super().__init__(host, port, **rest_kwargs)
        self.document_store = document_store
        ds = document_store
        plane = None
        if with_scheduler is None:
            from ._scheduler import scheduler_enabled

            with_scheduler = scheduler_enabled()
        if with_scheduler and hasattr(ds, "scheduler_retrieve_plane"):
            plane = ds.scheduler_retrieve_plane(deadline_ms=deadline_ms)
        self._retrieve_plane = plane
        if plane is not None:
            from .vector_store import _wire_index_maintenance

            self.webserver.add_raw_route(
                "/v1/retrieve",
                ("GET", "POST"),
                plane.aiohttp_handler(),
                EndpointDocumentation(summary="Retrieve documents", tags=["pathway"]),
            )
            _wire_index_maintenance(
                ds.retrieve_query,
                ds.RetrieveQuerySchema if hasattr(ds, "RetrieveQuerySchema") else _retrieve_schema(),
            )
        else:
            self.serve(
                "/v1/retrieve",
                ds.RetrieveQuerySchema if hasattr(ds, "RetrieveQuerySchema") else _retrieve_schema(),
                ds.retrieve_query,
                EndpointDocumentation(summary="Retrieve documents", tags=["pathway"]),
            )
        self.serve(
            "/v1/statistics",
            ds.StatisticsQuerySchema if hasattr(ds, "StatisticsQuerySchema") else _stats_schema(),
            ds.statistics_query,
            EndpointDocumentation(summary="Document store statistics", tags=["pathway"]),
        )
        self.serve(
            "/v1/inputs",
            ds.InputsQuerySchema if hasattr(ds, "InputsQuerySchema") else _inputs_schema(),
            ds.inputs_query,
            EndpointDocumentation(summary="Indexed input files", tags=["pathway"]),
        )


def _retrieve_schema():
    from .vector_store import RetrieveQuerySchema

    return RetrieveQuerySchema


def _stats_schema():
    from .vector_store import StatisticsQuerySchema

    return StatisticsQuerySchema


def _inputs_schema():
    from .vector_store import InputsQuerySchema

    return InputsQuerySchema


class QARestServer(BaseRestServer):
    """reference: servers.py:140"""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.rag_question_answerer = rag_question_answerer
        qa = rag_question_answerer
        self.serve(
            "/v1/retrieve",
            qa.RetrieveQuerySchema,
            qa.retrieve,
            EndpointDocumentation(summary="Retrieve documents", tags=["pathway"]),
        )
        self.serve(
            "/v1/statistics",
            qa.StatisticsQuerySchema,
            qa.statistics,
            EndpointDocumentation(summary="Index statistics", tags=["pathway"]),
        )
        self.serve(
            "/v1/pw_list_documents",
            qa.InputsQuerySchema,
            qa.list_documents,
            EndpointDocumentation(summary="List indexed documents", tags=["pathway"]),
        )
        self.serve(
            "/v1/pw_ai_answer",
            qa.AnswerQuerySchema,
            qa.answer_query,
            EndpointDocumentation(summary="Ask a question", tags=["pathway"]),
        )

    # reference keeps /v2/answer aliases in newer versions; /v1 is canonical


class QASummaryRestServer(QARestServer):
    """reference: servers.py:193"""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        qa = rag_question_answerer
        self.serve(
            "/v1/pw_ai_summary",
            qa.SummarizeQuerySchema,
            qa.summarize_query,
            EndpointDocumentation(summary="Summarize texts", tags=["pathway"]),
        )


def serve_callable(
    route: str,
    schema: SchemaMetaclass | None = None,
    host: str = "0.0.0.0",
    port: int = 8000,
    webserver: PathwayWebserver | None = None,
    **kwargs,
):
    """Expose an (async) Python function as a REST endpoint wired through
    the dataflow (reference: servers.py:227).

    Use as a decorator::

        @serve_callable(route="/echo", schema=MySchema, host=..., port=...)
        def handler(**row) -> str: ...

    Returns the decorated function; the endpoint serves once ``pw.run``
    (or a threaded server run) starts.
    """

    def decorate(fn: Callable):
        from ... import apply_async
        from ...internals.udfs import coerce_async

        nonlocal schema, webserver
        if schema is None:
            import inspect

            params = [
                p
                for p in inspect.signature(fn).parameters.values()
                if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
            ]
            types = {
                p.name: (p.annotation if p.annotation is not inspect._empty else str)
                for p in params
            }
            schema = schema_from_types(**types)
        ws = webserver or PathwayWebserver(host=host, port=port)
        queries, writer = rest_connector(
            webserver=ws, route=route, schema=schema,
            delete_completed_queries=True, **kwargs,
        )
        afn = coerce_async(fn)

        async def row_fn(*args):
            return await afn(*[_unwrap(a) for a in args])

        cols = [queries[n] for n in schema.column_names()]
        result = queries.select(result=apply_async(row_fn, *cols))
        writer(result)
        fn._pathway_endpoint = (ws, route)  # type: ignore[attr-defined]
        return fn

    def _unwrap(v):
        from ...internals.value import Json

        return v.value if isinstance(v, Json) else v

    return decorate
