"""Reranker UDFs.

reference: python/pathway/xpacks/llm/rerankers.py —
``rerank_topk_filter``:14, ``LLMReranker``:58 (1–5 scoring),
``CrossEncoderReranker``:186 (sentence-transformers CrossEncoder — the
north-star config), ``EncoderReranker``:251, ``FlashRankReranker``:319.

TPU design: ``CrossEncoderReranker`` runs the flax cross-encoder
(models/cross_encoder.py) — (query, doc) pairs arriving concurrently in one
micro-batch coalesce into one padded device batch, same pattern as the
embedder.  ``EncoderReranker`` scores with the sentence encoder's dot
products (bi-encoder rescoring).
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from ...internals import udfs
from ...internals.udfs import UDF, udf
from ...internals.value import Json
from ._utils import AsyncMicroBatcher, coerce_str

__all__ = [
    "rerank_topk_filter",
    "LLMReranker",
    "CrossEncoderReranker",
    "EncoderReranker",
    "FlashRankReranker",
]


@udf
def rerank_topk_filter(docs, scores, k: int = 5) -> tuple:
    """Keep the k best (doc, score) pairs (reference: rerankers.py:14).
    Returns (docs_tuple, scores_tuple)."""
    if isinstance(docs, Json):
        docs = docs.value
    if isinstance(scores, Json):
        scores = scores.value
    docs = list(docs or ())
    scores = [float(s) for s in (scores or ())]
    order = sorted(range(len(docs)), key=lambda i: -scores[i])[:k]
    return tuple(docs[i] for i in order), tuple(scores[i] for i in order)


class LLMReranker(UDF):
    """Ask a chat model to rate relevance 1-5 (reference: rerankers.py:58;
    there the score is extracted via logit_bias + single-token decoding —
    provider-specific, so the parse here accepts any leading number)."""

    def __init__(
        self,
        llm,
        *,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        use_logit_bias: bool | None = None,
    ):
        super().__init__(
            executor=udfs.async_executor(retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.llm = llm
        self.use_logit_bias = use_logit_bias

    async def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        prompt = (
            "Given a query and a document, rate on a scale from 1 to 5 how "
            "relevant the document is to the query. Respond with only the "
            "number.\n"
            f"Document: {coerce_str(doc)}\n"
            f"Query: {coerce_str(query)}\n"
            "Score:"
        )
        fn = getattr(self.llm, "__wrapped__", self.llm)
        res = fn(({"role": "user", "content": prompt},))
        import inspect

        if inspect.iscoroutine(res):
            res = await res
        import re

        m = re.search(r"[1-5](\.\d+)?", coerce_str(res))
        if m is None:
            raise ValueError(f"reranker LLM returned unparsable score: {res!r}")
        return float(m.group(0))


class CrossEncoderReranker(UDF):
    """Pointwise cross-encoder scoring on TPU (reference: rerankers.py:186).

    ``model_name`` keeps the reference's signature; the geometry is the
    MiniLM-class flax cross-encoder.  Pass ``cross_encoder=`` to supply a
    ready :class:`pathway_tpu.models.cross_encoder.CrossEncoder`.
    """

    def __init__(
        self,
        model_name: str = "cross-encoder/ms-marco-MiniLM-L-6-v2",
        *,
        cross_encoder: Any = None,
        max_batch: int = 1024,
        use_scheduler: bool | None = None,
        **init_kwargs,
    ):
        super().__init__(executor=udfs.async_executor(), deterministic=True)
        self.model_name = model_name
        self._model = cross_encoder
        self._batcher: AsyncMicroBatcher | None = None
        self._max_batch = max_batch
        self._use_scheduler = use_scheduler
        self._init_kwargs = init_kwargs

    def _ensure_model(self):
        if self._model is None:
            from ...models.cross_encoder import CrossEncoder

            self._model = CrossEncoder(self.model_name, **self._init_kwargs)
        if self._batcher is None:
            model = self._model

            def batch_score(pairs: list[tuple[str, str]]) -> list[float]:
                return [float(s) for s in model.predict(pairs)]

            self._batcher = AsyncMicroBatcher(
                batch_score, max_batch=self._max_batch,
                use_scheduler=self._use_scheduler,
            )
        return self._model

    async def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        self._ensure_model()
        return await self._batcher.call((coerce_str(query), coerce_str(doc)))


class EncoderReranker(UDF):
    """Bi-encoder rescoring with the sentence encoder's embeddings
    (reference: rerankers.py:251)."""

    def __init__(
        self,
        model_name: str = "all-MiniLM-L6-v2",
        *,
        encoder: Any = None,
        max_batch: int = 1024,
        use_scheduler: bool | None = None,
        **init_kwargs,
    ):
        super().__init__(executor=udfs.async_executor(), deterministic=True)
        self.model_name = model_name
        self._encoder = encoder
        self._batcher: AsyncMicroBatcher | None = None
        self._max_batch = max_batch
        self._use_scheduler = use_scheduler
        self._init_kwargs = init_kwargs

    def _ensure(self):
        if self._encoder is None:
            from ...models.encoder import SentenceEncoder

            self._encoder = SentenceEncoder(self.model_name, **self._init_kwargs)
        if self._batcher is None:
            enc = self._encoder

            def batch_score(pairs: list[tuple[str, str]]) -> list[float]:
                # embeddings are L2-normalized: dot = cosine similarity
                queries = enc.encode([q for q, _ in pairs])
                docs = enc.encode([d for _, d in pairs])
                return [float(np.dot(q, d)) for q, d in zip(queries, docs)]

            self._batcher = AsyncMicroBatcher(
                batch_score, max_batch=self._max_batch,
                use_scheduler=self._use_scheduler,
            )

    async def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        self._ensure()
        return await self._batcher.call((coerce_str(query), coerce_str(doc)))


class FlashRankReranker(UDF):
    """flashrank listwise reranker (reference: rerankers.py:319) — needs the
    flashrank library in the image."""

    def __init__(self, model: str = "ms-marco-TinyBERT-L-2-v2", **kwargs):
        super().__init__(executor=udfs.async_executor())
        self.model = model
        self.kwargs = kwargs
        self._ranker = None

    def _ensure(self):
        if self._ranker is None:
            from flashrank import Ranker  # optional dependency

            self._ranker = Ranker(model_name=self.model, **self.kwargs)
        return self._ranker

    async def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        from flashrank import RerankRequest  # optional dependency

        ranker = self._ensure()
        req = RerankRequest(
            query=coerce_str(query), passages=[{"text": coerce_str(doc)}]
        )
        results = ranker.rerank(req)
        return float(results[0]["score"])
