"""Answer-correctness evaluation for served RAG apps.

reference: integration_tests/rag_evals/evaluator.py (RAGEvaluator,
``compare_sim_with_date``), ragas_utils.py (LLM-judged AnswerCorrectness),
test_eval.py (serve → query labeled dataset → assert accuracy threshold).

The north-star measuring stick BASELINE.md calls for: drive a *served*
app over a labeled (file, question, label) dataset and score the answers
themselves — not just retrieval.  Two scorers, matching the reference's
pair:

* ``compare_sim_with_date`` — deterministic string scoring (dates
  normalized, alphanumeric SequenceMatcher ratio);
* ``judge_correctness`` — an LLM judge prompted RAGAS-style to grade
  each (question, ground truth, answer) triple.  The judge is any chat
  UDF (``xpacks.llm.llms``); CI uses :class:`MockJudgeChat`, a
  deterministic stand-in that grades the same prompt format.
"""

from __future__ import annotations

import csv
import re
import time
from dataclasses import asdict, dataclass, field
from datetime import datetime
from difflib import SequenceMatcher
from typing import Any, Callable

__all__ = [
    "Data",
    "PredictedData",
    "RAGEvaluator",
    "MockJudgeChat",
    "compare_sim_with_date",
    "build_judge_prompt",
    "load_dataset_tsv",
    "run_eval_experiment",
]


@dataclass
class Data:
    """One labeled example (reference: evaluator.py:23)."""

    question: str
    label: str
    file: str
    reworded_question: str = ""

    def __post_init__(self):
        if not self.reworded_question:
            self.reworded_question = self.question


@dataclass
class PredictedData(Data):
    pred: str = ""
    docs: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# deterministic string scoring (reference: evaluator.py:36-80)
# ---------------------------------------------------------------------------

_DATE_RE = re.compile(r"\b(0?[1-9]|1[0-2])/(0?[1-9]|[12]\d|3[01])/\d{2}\b")


def _norm(s) -> str:
    """Lowercase alphanumeric normalization shared by every scorer."""
    return "".join(c for c in str(s).lower() if c.isalnum())


def is_date(s: str) -> bool:
    return bool(_DATE_RE.match(s))


def parse_date(s: str) -> datetime | None:
    for fmt in ("%d %B %Y", "%B %d, %Y", "%m %d, %Y"):
        try:
            return datetime.strptime(s, fmt)
        except ValueError:
            continue
    return None


def _strip_date_zeros(s: str) -> str:
    """Unpad month/day only — the year stays %y-style zero-padded
    ('05/08/09' -> '5/8/09')."""
    parts = s.split("/")
    head = [p.lstrip("0") or "0" for p in parts[:2]]
    return "/".join(head + parts[2:])


def compare_dates(pred: str, label: str) -> bool:
    d = parse_date(pred)
    if d is None:
        return False
    # zero-padded labels ('05/08/14') must match like '5/8/14'
    return f"{d.month}/{d.day}/{d:%y}" == _strip_date_zeros(label)


def compare_sim_with_date(
    pred: str, label: str, min_sequence_match: float = 0.4
) -> bool:
    """reference: evaluator.py:65 — date-aware lenient string match.

    Example:

    >>> from pathway_tpu.xpacks.llm.rag_evals import compare_sim_with_date
    >>> compare_sim_with_date("The capital is Berlin", "Berlin", 0.2)
    True
    >>> compare_sim_with_date("May 8, 2014", "5/8/14")
    True
    >>> compare_sim_with_date("Madrid", "Berlin")
    False
    """
    if "No information" in str(pred) and str(label) == "nan":
        return True
    if is_date(label):
        return compare_dates(pred, label)
    a, b = _norm(pred), _norm(label)
    return SequenceMatcher(None, a, b).ratio() > min_sequence_match


# ---------------------------------------------------------------------------
# LLM-judged answer correctness (reference: ragas_utils.py)
# ---------------------------------------------------------------------------

JUDGE_PROMPT = """You are grading a question-answering system.
Given the question, the ground truth and the system's answer, decide
whether the answer conveys the ground truth. The answer may be less or
more verbose than the ground truth; if the ground truth is 'Yes' and the
answer is 'Yes, [details]', it is CORRECT.

Question: {question}
Ground truth: {label}
Answer: {answer}

Reply with exactly one word: CORRECT or INCORRECT."""


def build_judge_prompt(question: str, label: str, answer: str) -> str:
    return JUDGE_PROMPT.format(question=question, label=label, answer=answer)


class MockJudgeChat:
    """Deterministic stand-in for the judge LLM: parses the judge prompt
    and grades by normalized containment / similarity — the verdict a
    well-behaved judge model reaches on unambiguous cases.  Callable like
    the chat UDFs' plain-python form.

    Example:

    >>> from pathway_tpu.xpacks.llm.rag_evals import (
    ...     MockJudgeChat, build_judge_prompt)
    >>> judge = MockJudgeChat()
    >>> judge(build_judge_prompt("capital?", "Berlin", "It is Berlin."))
    'CORRECT'
    """

    def __call__(self, prompt: str, **kwargs) -> str:
        m = re.search(
            r"Ground truth: (.*?)\nAnswer: (.*?)\n\nReply with", prompt, re.S
        )
        if not m:
            return "INCORRECT"
        label, answer = m.group(1), m.group(2)
        a, b = _norm(answer), _norm(label)
        if not b:
            return "CORRECT" if not a else "INCORRECT"
        if b in a:
            return "CORRECT"
        ratio = SequenceMatcher(None, a, b).ratio()
        return "CORRECT" if ratio > 0.6 else "INCORRECT"


# ---------------------------------------------------------------------------
# evaluator
# ---------------------------------------------------------------------------


def load_dataset_tsv(path) -> list[dict]:
    """Labeled TSV with ``file``/``question``/``label``
    [/``reworded_question``] columns (reference: dataset/labeled.tsv)."""
    with open(path) as f:
        rows = list(csv.DictReader(f, delimiter="\t"))
    return [
        dict(
            question=r["question"],
            label=r["label"],
            file=r.get("file", ""),
            reworded_question=r.get("reworded_question") or r["question"],
        )
        for r in rows
    ]


class RAGEvaluator:
    """Drive a served RAG app over a labeled dataset and score answers
    (reference: evaluator.py:114 ``RAGEvaluator``)."""

    def __init__(
        self,
        dataset: list[dict],
        compare: Callable[[str, str], bool] = compare_sim_with_date,
        connector: Any = None,
    ):
        self.dataset = [Data(**d) for d in dataset]
        self.compare = compare
        self.connector = connector
        self.predicted_dataset: list[PredictedData] = []
        self.latencies: list[float] = []
        self.result_metrics: dict = {}

    @property
    def predicted_dataset_as_dict_list(self) -> list[dict]:
        return [asdict(p) for p in self.predicted_dataset]

    def predict_dataset(self) -> None:
        """Ask the served app every question (file-scoped when the row
        names a file)."""
        self.predicted_dataset = []
        self.latencies = []
        for d in self.dataset:
            filters = (
                f"globmatch(`**/{d.file}`, path)" if d.file else None
            )
            t0 = time.perf_counter()
            answer = self.connector.pw_ai_answer(
                d.reworded_question,
                filters=filters,
                return_context_docs=True,
            )
            self.latencies.append(time.perf_counter() - t0)
            self.predicted_dataset.append(
                PredictedData(
                    question=d.question,
                    label=d.label,
                    file=d.file,
                    reworded_question=d.reworded_question,
                    pred=str(answer.get("response", "")),
                    docs=answer.get("context_docs") or [],
                )
            )

    def calculate_accuracy(
        self, compare: Callable[[str, str], bool] | None = None
    ) -> float:
        """Deterministic string-compared accuracy over the predictions."""
        compare = compare or self.compare
        total = len(self.predicted_dataset)
        if not total:
            return 0.0
        ok = 0
        for p in self.predicted_dataset:
            try:
                if compare(p.pred, p.label):
                    ok += 1
            except Exception:
                pass
        return ok / total

    def judge_correctness(self, judge_chat: Callable[[str], str]) -> float:
        """Fraction of answers an LLM judge grades CORRECT
        (reference: ragas_utils.py AnswerCorrectness)."""
        total = len(self.predicted_dataset)
        if not total:
            return 0.0
        return self.judge_correct_count(judge_chat) / total

    def judge_correct_count(self, judge_chat: Callable[[str], str]) -> int:
        ok = 0
        for p in self.predicted_dataset:
            verdict = str(
                judge_chat(build_judge_prompt(p.question, p.label, p.pred))
            )
            if "INCORRECT" not in verdict.upper() and "CORRECT" in verdict.upper():
                ok += 1
        return ok

    def calculate_retrieval_metrics(self) -> dict:
        """Context hit rate + MRR: was the labeled info in the retrieved
        docs, and how high (reference: evaluator.py retrieval metrics)."""
        hits = 0
        rr_total = 0.0
        total = len(self.predicted_dataset)
        for p in self.predicted_dataset:
            label_norm = _norm(p.label)
            rank = None
            for i, doc in enumerate(p.docs):
                text = doc.get("text") if isinstance(doc, dict) else str(doc)
                doc_norm = _norm(text)
                if label_norm and label_norm in doc_norm:
                    rank = i + 1
                    break
            if rank is not None:
                hits += 1
                rr_total += 1.0 / rank
        return {
            "context_hit_rate": hits / total if total else 0.0,
            "mrr": rr_total / total if total else 0.0,
        }


def run_eval_experiment(
    connector,
    dataset_path,
    judge_chat: Callable[[str], str] | None = None,
    compare: Callable[[str, str], bool] = compare_sim_with_date,
) -> dict:
    """Serve-side entry point: query the dataset through ``connector``
    (a ``RAGClient``), score, return the metrics dict
    (reference: experiment.py ``run_eval_experiment``)."""
    evaluator = RAGEvaluator(
        load_dataset_tsv(dataset_path), compare=compare, connector=connector
    )
    evaluator.predict_dataset()
    lat = sorted(evaluator.latencies)
    metrics: dict = {
        "n_questions": len(evaluator.dataset),
        "string_accuracy": round(evaluator.calculate_accuracy(), 3),
        "p50_latency_ms": round(lat[len(lat) // 2] * 1000, 1) if lat else None,
        **{
            k: round(v, 3)
            for k, v in evaluator.calculate_retrieval_metrics().items()
        },
    }
    if judge_chat is not None:
        n_ok = evaluator.judge_correct_count(judge_chat)
        metrics["n_correct"] = n_ok
        metrics["answer_correctness"] = round(
            n_ok / max(len(evaluator.dataset), 1), 3
        )
    evaluator.result_metrics = metrics
    return metrics
