"""RAG question-answering pipelines.

reference: python/pathway/xpacks/llm/question_answering.py —
``BaseRAGQuestionAnswerer``:314 (``answer_query``:451 retrieve → context →
prompt → LLM; ``summarize_query``:491; ``build_server``/``run_server``),
``AdaptiveRAGQuestionAnswerer``:620 over
``answer_with_geometric_rag_strategy[_from_index]``:97/:162 (geometric
2,4,8,… document escalation), ``DeckRetriever``:736, ``RAGClient``:854.
"""

from __future__ import annotations

from typing import Any, Callable

from ...internals import dtype as dt
from ...internals.expression import ApplyExpression
from ...internals.schema import Schema, column_definition
from ...internals.table import Table
from ...internals.thisclass import right
from ...internals.udfs import udf
from ...internals.value import Json
from ._utils import RestClientBase, coerce_str
from .llms import BaseChat, prompt_chat_single_qa
from . import prompts
from .vector_store import (
    InputsQuerySchema,
    RetrieveQuerySchema,
    StatisticsQuerySchema,
    _merge_filters,
)

__all__ = [
    "BaseQuestionAnswerer",
    "SummaryQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "AdaptiveRAGQuestionAnswerer",
    "answer_with_geometric_rag_strategy",
    "answer_with_geometric_rag_strategy_from_index",
    "DeckRetriever",
    "RAGClient",
]


class AIResponseType:
    SHORT = "short"
    LONG = "long"


# ---------------------------------------------------------------------------
# abstract surface consumed by QARestServer (reference: question_answering.py
# BaseQuestionAnswerer / SummaryQuestionAnswerer protocols)
# ---------------------------------------------------------------------------


class BaseQuestionAnswerer:
    RetrieveQuerySchema = RetrieveQuerySchema
    StatisticsQuerySchema = StatisticsQuerySchema
    InputsQuerySchema = InputsQuerySchema

    class AnswerQuerySchema(Schema):
        prompt: str
        filters: str | None = column_definition(default_value=None)
        model: str | None = column_definition(default_value=None)
        return_context_docs: bool = column_definition(default_value=False)
        response_type: str = column_definition(default_value=AIResponseType.SHORT)

    def answer_query(self, pw_ai_queries: Table) -> Table: ...

    def retrieve(self, queries: Table) -> Table: ...

    def statistics(self, queries: Table) -> Table: ...

    def list_documents(self, queries: Table) -> Table: ...


class SummaryQuestionAnswerer(BaseQuestionAnswerer):
    class SummarizeQuerySchema(Schema):
        text_list: Json
        model: str | None = column_definition(default_value=None)

    def summarize_query(self, summarize_queries: Table) -> Table: ...


import itertools as _itertools

_qa_seq = _itertools.count()


class BaseRAGQuestionAnswerer(SummaryQuestionAnswerer):
    """reference: question_answering.py:314

    Failure domain: LLM calls run through a circuit breaker
    (``xpacks/llm/_breaker.py``).  Consecutive LLM failures trip it, after
    which ``/v1/pw_ai_answer`` keeps answering with *retrieval-only*
    results (``response: null``, ``"degraded": true``, context docs
    included) instead of 5xx-ing; a half-open probe restores full answers
    once the model heals.
    """

    def __init__(
        self,
        llm: BaseChat,
        indexer,  # VectorStoreServer | DocumentStore
        *,
        default_llm_name: str | None = None,
        short_prompt_template=prompts.prompt_short_qa,
        long_prompt_template=prompts.prompt_qa,
        summarize_template=prompts.prompt_summarize,
        search_topk: int = 6,
        llm_breaker: Any = None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.default_llm_name = default_llm_name or getattr(llm, "model", None)
        self.short_prompt_template = short_prompt_template
        self.long_prompt_template = long_prompt_template
        self.summarize_template = summarize_template
        self.search_topk = search_topk
        self.server: Any = None
        self._pending_endpoints: list = []
        if llm_breaker is None:
            from ._breaker import CircuitBreaker

            llm_breaker = CircuitBreaker(f"llm-{next(_qa_seq)}")
        self.llm_breaker = llm_breaker

    def _guarded_llm(self):
        """The LLM as a breaker-guarded async UDF: a refused or failed
        call yields ``None`` (→ degraded retrieval-only answer) instead of
        an engine-visible exception."""
        from ...internals.udfs import async_executor, udf

        base = self.llm.async_callable()
        breaker = self.llm_breaker

        @udf(executor=async_executor(), return_type=dt.Optional(dt.STR))
        async def guarded_llm(messages, model: str | None = None):
            import time as _time_mod

            from ...internals.flight_recorder import observe_stage, record_span

            if not breaker.allow():
                return None
            wall0 = _time_mod.time()
            t0 = _time_mod.monotonic()
            try:
                result = await base(messages, model=model)
            except Exception as exc:  # noqa: BLE001 — degrade, don't poison
                breaker.record_failure(exc)
                from ...internals.errors import register_error

                register_error(
                    f"LLM call failed, answer degraded to retrieval-only: "
                    f"{type(exc).__name__}: {exc}",
                    kind="serving",
                    operator="llm",
                )
                dur_ms = (_time_mod.monotonic() - t0) * 1000.0
                record_span(
                    "llm", "llm", wall0, dur_ms,
                    attrs={"model": model, "ok": False},
                )
                # failures observe too — a histogram that only sees the
                # healthy calls hides exactly the timeout tail it exists
                # to expose
                observe_stage("llm", dur_ms)
                return None
            breaker.record_success()
            # LLM latency is usually the answer path's dominant stage:
            # span for trace dumps + pathway_request_stage_ms{stage="llm"}
            dur_ms = (_time_mod.monotonic() - t0) * 1000.0
            record_span(
                "llm", "llm", wall0, dur_ms, attrs={"model": model, "ok": True}
            )
            observe_stage("llm", dur_ms)
            return result

        return guarded_llm

    # -- the 4-select answer pipeline (reference: :451-482) --
    def answer_query(self, pw_ai_queries: Table) -> Table:
        queries = pw_ai_queries.select(
            prompt=pw_ai_queries.prompt,
            filters=pw_ai_queries.filters,
            model=ApplyExpression(
                lambda m: m or self.default_llm_name,
                dt.Optional(dt.STR),
                pw_ai_queries.model,
            ),
            return_context_docs=pw_ai_queries.return_context_docs,
            response_type=pw_ai_queries.response_type,
        )
        retrieve_table = queries.select(
            query=queries.prompt,
            k=ApplyExpression(lambda p: self.search_topk, dt.INT, queries.prompt),
            metadata_filter=queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), queries.prompt
            ),
        )
        docs_result = self.indexer.retrieve_query(retrieve_table)
        with_docs = queries.with_universe_of(docs_result).select(
            prompt=queries.prompt,
            model=queries.model,
            return_context_docs=queries.return_context_docs,
            response_type=queries.response_type,
            docs=ApplyExpression(
                lambda r: tuple(
                    d.get("text") if isinstance(d, dict) else d
                    for d in (r.value if isinstance(r, Json) else r or ())
                ),
                dt.List(dt.STR),
                docs_result.result,
            ),
        )

        def pick_template(response_type):
            if response_type == AIResponseType.LONG:
                return self.long_prompt_template
            return self.short_prompt_template

        # both templates are UDFs; response_type is per-row, so build both
        # and pick row-wise (the reference dispatches the same way)
        prompted = with_docs.select(
            prompt_short=self.short_prompt_template(
                with_docs.prompt, with_docs.docs
            ),
            prompt_long=self.long_prompt_template(with_docs.prompt, with_docs.docs),
            response_type=with_docs.response_type,
            model=with_docs.model,
            return_context_docs=with_docs.return_context_docs,
            docs=with_docs.docs,
        )
        chosen = prompted.select(
            rag_prompt=ApplyExpression(
                lambda rt, s, l: l if rt == AIResponseType.LONG else s,
                dt.STR,
                prompted.response_type,
                prompted.prompt_short,
                prompted.prompt_long,
            ),
            model=prompted.model,
            return_context_docs=prompted.return_context_docs,
            docs=prompted.docs,
        )
        answered = chosen.select(
            response=self._guarded_llm()(
                prompt_chat_single_qa(chosen.rag_prompt), model=chosen.model
            ),
            return_context_docs=chosen.return_context_docs,
            docs=chosen.docs,
        )

        def pack(response, return_context_docs, docs) -> Json:
            if response is None:
                # LLM breaker open / call failed: retrieval-only answer
                return Json(
                    {
                        "response": None,
                        "degraded": True,
                        "context_docs": [coerce_str(d) for d in (docs or ())],
                    }
                )
            out: dict = {"response": coerce_str(response)}
            if return_context_docs:
                out["context_docs"] = [coerce_str(d) for d in (docs or ())]
            return Json(out)

        return answered.select(
            result=ApplyExpression(
                pack, Json, answered.response, answered.return_context_docs,
                answered.docs,
            )
        )

    # -- summarize (reference: :491) --
    def summarize_query(self, summarize_queries: Table) -> Table:
        queries = summarize_queries.select(
            text_list=summarize_queries.text_list,
            model=ApplyExpression(
                lambda m: m or self.default_llm_name,
                dt.Optional(dt.STR),
                summarize_queries.model,
            ),
        )
        prompted = queries.select(
            prompt=self.summarize_template(queries.text_list),
            model=queries.model,
        )
        return prompted.select(
            result=self.llm(prompt_chat_single_qa(prompted.prompt), model=prompted.model)
        )

    # -- passthrough endpoints --
    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    # -- serving (reference: build_server/run_server) --
    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        from .servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)

    def run_server(self, host: str = "0.0.0.0", port: int = 8000, **kwargs):
        if self.server is None:
            self.build_server(host=host, port=port)
        return self.server.run(**kwargs)


# ---------------------------------------------------------------------------
# adaptive RAG (reference: :97-162, :620)
# ---------------------------------------------------------------------------

_NO_INFO = "No information found."


def answer_with_geometric_rag_strategy(
    questions: Table,
    documents,  # ColumnReference to a list-of-docs column on `questions`
    llm_chat_model: BaseChat,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    strict_prompt: bool = False,
) -> Table:
    """Ask with 2, 4, 8, … context documents until the model answers
    (reference: question_answering.py:97).  Each escalation round runs only
    for the still-unanswered questions — chained filters, no fixpoint
    operator needed, exactly like the reference."""
    base = questions.select(question=questions.prompt, docs=documents)
    n_documents = n_starting_documents
    answered_tables: list[Table] = []
    remaining = base
    def make_prompt_udf(n: int):
        @udf
        def build_prompt(question: str, docs) -> str:
            doc_list = [coerce_str(d) for d in (docs or ())][:n]
            return prompts.prompt_qa_geometric_rag(
                question, doc_list,
                information_not_found_response=_NO_INFO,
                strict_prompt=strict_prompt,
            )

        return build_prompt

    for _ in range(max_iterations):
        build_prompt = make_prompt_udf(n_documents)
        asked = remaining.select(
            question=remaining.question,
            docs=remaining.docs,
            answer=llm_chat_model(
                prompt_chat_single_qa(build_prompt(remaining.question, remaining.docs))
            ),
        )
        found = asked.filter(
            ApplyExpression(
                lambda a: a is not None and coerce_str(a).strip() != _NO_INFO
                and coerce_str(a).strip() != "",
                dt.BOOL,
                asked.answer,
            )
        )
        answered_tables.append(found.select(result=found.answer))
        remaining = asked.filter(
            ApplyExpression(
                lambda a: a is None or coerce_str(a).strip() == _NO_INFO
                or coerce_str(a).strip() == "",
                dt.BOOL,
                asked.answer,
            )
        ).select(question=asked.question, docs=asked.docs)
        n_documents *= factor
    giving_up = remaining.select(
        result=ApplyExpression(lambda q: _NO_INFO, dt.STR, remaining.question)
    )
    result = answered_tables[0]
    return result.concat(*answered_tables[1:], giving_up)


def answer_with_geometric_rag_strategy_from_index(
    questions: Table,
    index,  # DataIndex
    documents_column: str,
    llm_chat_model: BaseChat,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    metadata_filter=None,
    strict_prompt: bool = False,
) -> Table:
    """reference: question_answering.py:162 — one index query fetches the
    max escalation depth, the strategy then slices locally."""
    max_docs = n_starting_documents * factor ** (max_iterations - 1)
    res = index.query_as_of_now(
        questions.prompt,
        number_of_matches=max_docs,
        metadata_filter=metadata_filter,
        collapse_rows=True,
    )
    with_docs = res.select(prompt=questions.prompt, docs=right[documents_column])
    return answer_with_geometric_rag_strategy(
        with_docs.select(prompt=with_docs.prompt),
        with_docs.docs,
        llm_chat_model,
        n_starting_documents=n_starting_documents,
        factor=factor,
        max_iterations=max_iterations,
        strict_prompt=strict_prompt,
    )


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """reference: question_answering.py:620"""

    def __init__(
        self,
        llm: BaseChat,
        indexer,
        *,
        default_llm_name: str | None = None,
        summarize_template=prompts.prompt_summarize,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
    ):
        super().__init__(
            llm, indexer,
            default_llm_name=default_llm_name,
            summarize_template=summarize_template,
        )
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, pw_ai_queries: Table) -> Table:
        max_docs = self.n_starting_documents * self.factor ** (
            self.max_iterations - 1
        )
        retrieve_table = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=ApplyExpression(lambda p: max_docs, dt.INT, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), pw_ai_queries.prompt
            ),
        )
        docs_result = self.indexer.retrieve_query(retrieve_table)
        with_docs = pw_ai_queries.with_universe_of(docs_result).select(
            prompt=pw_ai_queries.prompt,
            docs=ApplyExpression(
                lambda r: tuple(
                    d.get("text") if isinstance(d, dict) else d
                    for d in (r.value if isinstance(r, Json) else r or ())
                ),
                dt.List(dt.STR),
                docs_result.result,
            ),
        )
        answers = answer_with_geometric_rag_strategy(
            with_docs,
            with_docs.docs,
            self.llm,
            n_starting_documents=self.n_starting_documents,
            factor=self.factor,
            max_iterations=self.max_iterations,
            strict_prompt=self.strict_prompt,
        )
        # restore the query universe for the response writer
        packed = answers.select(
            result=ApplyExpression(
                lambda a: Json({"response": coerce_str(a)}), Json, answers.result
            )
        )
        return pw_ai_queries.with_universe_of(packed).select(result=packed.result)


class DeckRetriever(BaseRAGQuestionAnswerer):
    """Slide-deck retrieval app (reference: question_answering.py:736)."""

    excluded_response_metadata = ["b64_image"]

    def answer_query(self, pw_ai_queries: Table) -> Table:
        retrieve_table = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=ApplyExpression(lambda p: self.search_topk, dt.INT, pw_ai_queries.prompt),
            metadata_filter=pw_ai_queries.filters,
            filepath_globpattern=ApplyExpression(
                lambda p: None, dt.Optional(dt.STR), pw_ai_queries.prompt
            ),
        )
        docs = self.indexer.retrieve_query(retrieve_table)

        def strip_meta(r) -> Json:
            out = []
            for d in r.value if isinstance(r, Json) else (r or ()):
                if isinstance(d, dict):
                    d = dict(d)
                    meta = d.get("metadata") or {}
                    d["metadata"] = {
                        k: v for k, v in meta.items()
                        if k not in self.excluded_response_metadata
                    }
                out.append(d)
            return Json(out)

        return docs.select(
            result=ApplyExpression(strip_meta, Json, docs.result)
        )


# ---------------------------------------------------------------------------
# client (reference: question_answering.py:854)
# ---------------------------------------------------------------------------


class RAGClient(RestClientBase):
    """HTTP client for QARestServer/QASummaryRestServer."""

    def __init__(self, *args, timeout: float = 90.0, **kwargs):
        super().__init__(*args, timeout=timeout, **kwargs)

    def retrieve(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    def statistics(self):
        return self._post("/v1/statistics", {})

    def pw_list_documents(self, filters: str | None = None, keys: list | None = None):
        return self._post("/v1/pw_list_documents", {"metadata_filter": filters})

    def pw_ai_answer(
        self,
        prompt: str,
        filters: str | None = None,
        model: str | None = None,
        return_context_docs: bool = False,
        response_type: str = AIResponseType.SHORT,
    ):
        payload: dict = {
            "prompt": prompt,
            "return_context_docs": return_context_docs,
            "response_type": response_type,
        }
        if filters is not None:
            payload["filters"] = filters
        if model is not None:
            payload["model"] = model
        return self._post("/v1/pw_ai_answer", payload)

    answer = pw_ai_answer

    def pw_ai_summary(self, text_list: list[str], model: str | None = None):
        payload: dict = {"text_list": text_list}
        if model is not None:
            payload["model"] = model
        return self._post("/v1/pw_ai_summary", payload)

    summarize = pw_ai_summary
